# Quality gates, mirroring the reference's Makefile:102-174 + ADR-002
# (unit tests w/ race detector -> pytest; golangci-lint -> tools/qa.py
# lint; gocyclo -over N -> tools/qa.py cyclo; coverage >= 80% ->
# tools/qa.py coverage on sys.monitoring). No third-party QA tools are
# baked into this image, so the gates are first-party (tools/qa.py).

PY ?= python

.PHONY: all check lint cyclo test coverage native bench clean hooks

all: check

check: lint cyclo test

lint:
	$(PY) tools/qa.py lint

cyclo:
	$(PY) tools/qa.py cyclo --over 12

test:
	$(PY) -m pytest tests/ -x -q

coverage:
	$(PY) tools/qa.py coverage --fail-under 80

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

hooks:
	chmod +x scripts/githooks/*
	git config core.hooksPath scripts/githooks
	@echo "git hooks installed (pre-commit: lint+cyclo; pre-push: make check)"

clean:
	rm -rf .qa_coverage.json $(shell find . -name __pycache__ -type d)
