# Quality gates, mirroring the reference's Makefile:102-174 + ADR-002
# (unit tests w/ race detector -> pytest; golangci-lint -> tools/qa.py
# lint; gocyclo -over N -> tools/qa.py cyclo; coverage >= 80% ->
# tools/qa.py coverage on sys.monitoring). No third-party QA tools are
# baked into this image, so the gates are first-party (tools/qa.py).

PY ?= python
SHELL := /bin/bash           # pipefail in the test target

.PHONY: all check lint cyclo test test-asan coverage native bench clean hooks

all: check

check: lint cyclo test

lint:
	$(PY) tools/qa.py lint

cyclo:
	$(PY) tools/qa.py cyclo --over 12

# --tb=long is unconditional via pyproject addopts; keep the log so a
# flake's first occurrence is diagnosable (docs/qa_report.md)
test:
	set -o pipefail; $(PY) -m pytest tests/ -x -q 2>&1 | tee pytest.log

coverage:
	$(PY) tools/qa.py coverage --fail-under 80

native:
	$(MAKE) -C native

# ASAN gate for the native boundary (the reference runs its unit tests
# with the Go race detector on every invocation, Makefile:105; the C
# extension's refcount/lifetime discipline gets the equivalent here).
# LD_PRELOAD because the python binary itself is not ASAN-built;
# detect_leaks=0 because CPython intentionally leaks at interpreter
# exit and the interceptor would drown real findings in that noise.
# libstdc++ is preloaded alongside libasan: python itself links no C++
# runtime, so at preload-init dlsym(RTLD_NEXT, "__cxa_throw") finds
# nothing and the interceptor CHECK-fails the first time a dlopen'd
# C++ library (jaxlib) throws. Loading libstdc++ up front fixes the
# symbol resolution order.
ASAN_LIB = $(shell $(CXX) -print-file-name=libasan.so)
STDCXX_LIB = $(shell $(CXX) -print-file-name=libstdc++.so.6)
test-asan:
	$(MAKE) -C native asan
	# preflight: the gate must FAIL, not silently skip, if the
	# instrumented extensions don't load under the ASAN runtime
	LD_PRELOAD="$(ASAN_LIB) $(STDCXX_LIB)" \
	ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
	MAXMQ_NATIVE_DIR=$(CURDIR)/native/asan \
	$(PY) -c "from maxmq_tpu import native; \
	    assert native.available(), 'asan ctypes lib failed to load'; \
	    assert native.decode_module(build=False), 'asan decode ext failed to load'"
	LD_PRELOAD="$(ASAN_LIB) $(STDCXX_LIB)" \
	ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
	MAXMQ_NATIVE_DIR=$(CURDIR)/native/asan \
	JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_sig_parity.py tests/test_churn_stress.py \
	    tests/test_native.py tests/test_refdecode.py -x -q

bench:
	$(PY) bench.py

hooks:
	chmod +x scripts/githooks/*
	git config core.hooksPath scripts/githooks
	@echo "git hooks installed (pre-commit: lint+cyclo; pre-push: make check)"

clean:
	rm -rf .qa_coverage.json $(shell find . -name __pycache__ -type d)
