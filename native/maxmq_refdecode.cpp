// maxmq_refdecode — an INDEPENDENT MQTT wire-format decoder used only to
// differentially validate the production codec (maxmq_tpu/protocol/).
//
// Role (VERDICT r4 #6): the reference validates its codec against a
// foreign implementation (Eclipse Paho, tests/system/mqtt_test.go:35-253
// and the engine's interop-suite claim). No second MQTT implementation is
// installable in this image, so this file is the strongest available
// substitute: a decoder-only re-derivation of the OASIS MQTT 3.1.1
// (mqtt-v3.1.1-os) and 5.0 (mqtt-v5.0-os) specifications — plus the
// 3.1 "MQIsdp" dialect — in a different language, sharing ZERO code,
// tables, or constants with maxmq_tpu/protocol/{codec,packets,
// properties}.py. The differential fuzzer (tests/test_refdecode.py)
// decodes every conformance-corpus case and thousands of randomized /
// mutated packets through both and requires byte-identical canonical
// output (or agreement that the bytes are invalid).
//
// Deliberately NOT shared with the production codec: this file reads
// the spec's tables (2.2.2 property identifiers, 3.x packet layouts)
// directly into switch statements; a transcription error here that
// disagrees with protocol/ is exactly what the fuzzer exists to surface.
//
// Canonical output format (the comparison contract, mirrored by the
// canonicalizer in tests/test_refdecode.py): "key=value\n" lines in a
// fixed order; strings/bytes as lowercase hex; properties as
// "p.<id>=<v>" ascending by id (will-properties "w.p.<id>=<v>");
// empty-string/empty-bytes property values canonicalize to absent,
// matching the production encoder's absence semantics.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace {

// ---------------------------------------------------------------- reader

struct Reader {
  const uint8_t *p;
  int64_t len;
  int64_t off = 0;
  bool err = false;

  bool need(int64_t n) {
    if (err || off + n > len) {
      err = true;
      return false;
    }
    return true;
  }
  uint8_t u8() {
    if (!need(1)) return 0;
    return p[off++];
  }
  uint16_t u16() {
    if (!need(2)) return 0;
    uint16_t v = (uint16_t)((p[off] << 8) | p[off + 1]);
    off += 2;
    return v;
  }
  uint32_t u32() {
    if (!need(4)) return 0;
    uint32_t v = ((uint32_t)p[off] << 24) | ((uint32_t)p[off + 1] << 16) |
                 ((uint32_t)p[off + 2] << 8) | (uint32_t)p[off + 3];
    off += 4;
    return v;
  }
  // Variable Byte Integer, spec 1.5.5: at most 4 bytes; non-minimal
  // encodings are accepted (the spec forbids ENCODERS from emitting
  // them but places no requirement on decoders; the production codec
  // and the Go reference both accept them).
  uint32_t varint() {
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
      if (!need(1)) return 0;
      uint8_t b = p[off++];
      v |= (uint32_t)(b & 0x7F) << (7 * i);
      if (!(b & 0x80)) return v;
    }
    err = true;  // continuation bit on the 4th byte: malformed (1.5.5)
    return 0;
  }
  // length-prefixed binary data, spec 1.5.6
  bool bin(const uint8_t **out, int64_t *n) {
    uint16_t ln = u16();
    if (!need(ln)) return false;
    *out = p + off;
    *n = ln;
    off += ln;
    return true;
  }
};

// UTF-8 validity per spec 1.5.4: well-formed UTF-8, no U+0000, no
// UTF-16 surrogates (U+D800..U+DFFF), no overlong encodings, max
// U+10FFFF. (Noncharacters U+FFFE/U+FFFF "should not" appear — not a
// MUST, so they are accepted, as the production codec accepts them.)
bool utf8_ok(const uint8_t *s, int64_t n) {
  int64_t i = 0;
  while (i < n) {
    uint8_t b = s[i];
    if (b == 0x00) return false;
    if (b < 0x80) {
      i++;
    } else if ((b & 0xE0) == 0xC0) {
      if (i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return false;
      uint32_t cp = ((b & 0x1Fu) << 6) | (s[i + 1] & 0x3Fu);
      if (cp < 0x80) return false;  // overlong
      i += 2;
    } else if ((b & 0xF0) == 0xE0) {
      if (i + 2 >= n || (s[i + 1] & 0xC0) != 0x80 ||
          (s[i + 2] & 0xC0) != 0x80)
        return false;
      uint32_t cp = ((b & 0x0Fu) << 12) | ((s[i + 1] & 0x3Fu) << 6) |
                    (s[i + 2] & 0x3Fu);
      if (cp < 0x800) return false;                  // overlong
      if (cp >= 0xD800 && cp <= 0xDFFF) return false;  // surrogate
      i += 3;
    } else if ((b & 0xF8) == 0xF0) {
      if (i + 3 >= n || (s[i + 1] & 0xC0) != 0x80 ||
          (s[i + 2] & 0xC0) != 0x80 || (s[i + 3] & 0xC0) != 0x80)
        return false;
      uint32_t cp = ((b & 0x07u) << 18) | ((s[i + 1] & 0x3Fu) << 12) |
                    ((s[i + 2] & 0x3Fu) << 6) | (s[i + 3] & 0x3Fu);
      if (cp < 0x10000 || cp > 0x10FFFF) return false;  // overlong / range
      i += 4;
    } else {
      return false;  // stray continuation byte or 0xF8+
    }
  }
  return true;
}

// UTF-8 string (1.5.4): length-prefixed + validity
bool str(Reader &r, const uint8_t **out, int64_t *n) {
  if (!r.bin(out, n)) return false;
  if (!utf8_ok(*out, *n)) {
    r.err = true;
    return false;
  }
  return true;
}

// ------------------------------------------------------------- canonical

void emit_kv(std::string &out, const char *k, int64_t v) {
  char buf[48];
  snprintf(buf, sizeof buf, "%s=%lld\n", k, (long long)v);
  out += buf;
}

void emit_hex_nonl(std::string &out, const uint8_t *p, int64_t n) {
  static const char *hexd = "0123456789abcdef";
  for (int64_t i = 0; i < n; i++) {
    out += hexd[p[i] >> 4];
    out += hexd[p[i] & 0xF];
  }
}

void emit_khex(std::string &out, const char *k, const uint8_t *p, int64_t n) {
  out += k;
  out += '=';
  emit_hex_nonl(out, p, n);
  out += '\n';
}

void emit_khex(std::string &out, const char *k, const std::string &s) {
  emit_khex(out, k, (const uint8_t *)s.data(), (int64_t)s.size());
}

// ------------------------------------------------------------ properties

// Control packet type codes, spec table 2-1 (re-derived, not imported).
enum {
  kConnect = 1,
  kConnack = 2,
  kPublish = 3,
  kPuback = 4,
  kPubrec = 5,
  kPubrel = 6,
  kPubcomp = 7,
  kSubscribe = 8,
  kSuback = 9,
  kUnsubscribe = 10,
  kUnsuback = 11,
  kPingreq = 12,
  kPingresp = 13,
  kDisconnect = 14,
  kAuth = 15,
};
// Will-properties context marker for the validity check (spec 3.1.3.2).
constexpr int kWillCtx = 0;

// Property validity, spec 5.0 table 2-4 ("Valid Packets" column),
// encoded as a bitmask over packet-type codes; bit 0 = will properties.
uint32_t prop_mask(uint32_t pid) {
  auto M = [](std::initializer_list<int> types) {
    uint32_t m = 0;
    for (int t : types) m |= 1u << t;
    return m;
  };
  switch (pid) {
    case 0x01: return M({kPublish, kWillCtx});             // Payload Format
    case 0x02: return M({kPublish, kWillCtx});             // Message Expiry
    case 0x03: return M({kPublish, kWillCtx});             // Content Type
    case 0x08: return M({kPublish, kWillCtx});             // Response Topic
    case 0x09: return M({kPublish, kWillCtx});             // Correlation Data
    case 0x0B: return M({kPublish, kSubscribe});           // Subscription Id
    case 0x11: return M({kConnect, kConnack, kDisconnect});  // Session Expiry
    case 0x12: return M({kConnack});                       // Assigned Client Id
    case 0x13: return M({kConnack});                       // Server Keep Alive
    case 0x15: return M({kConnect, kConnack, kAuth});      // Auth Method
    case 0x16: return M({kConnect, kConnack, kAuth});      // Auth Data
    case 0x17: return M({kConnect});                       // Req Problem Info
    case 0x18: return M({kWillCtx});                       // Will Delay
    case 0x19: return M({kConnect});                       // Req Response Info
    case 0x1A: return M({kConnack});                       // Response Info
    case 0x1C: return M({kConnack, kDisconnect});          // Server Reference
    case 0x1F:
      return M({kConnack, kPuback, kPubrec, kPubrel, kPubcomp, kSuback,
                kUnsuback, kDisconnect, kAuth});           // Reason String
    case 0x21: return M({kConnect, kConnack});             // Receive Maximum
    case 0x22: return M({kConnect, kConnack});             // Topic Alias Max
    case 0x23: return M({kPublish});                       // Topic Alias
    case 0x24: return M({kConnack});                       // Maximum QoS
    case 0x25: return M({kConnack});                       // Retain Available
    case 0x26:
      return M({kConnect, kConnack, kPublish, kPuback, kPubrec, kPubrel,
                kPubcomp, kSubscribe, kSuback, kUnsubscribe, kUnsuback,
                kDisconnect, kAuth, kWillCtx});            // User Property
    case 0x27: return M({kConnect, kConnack});             // Max Packet Size
    case 0x28: return M({kConnack});                       // Wildcard Sub Avail
    case 0x29: return M({kConnack});                       // Sub Id Available
    case 0x2A: return M({kConnack});                       // Shared Sub Avail
    default: return 0;
  }
}

struct Props {
  // -1 = absent for integer-valued properties (all values fit 32 bits)
  int64_t vals[0x2B];
  bool has_str[0x2B];
  std::string strs[0x2B];  // string/binary-valued property payloads
  std::vector<uint32_t> sub_ids;
  std::vector<std::pair<std::string, std::string>> user_props;

  Props() {
    for (auto &v : vals) v = -1;
    for (auto &h : has_str) h = false;
  }
};

bool is_str_prop(uint32_t pid) {
  switch (pid) {
    case 0x03: case 0x08: case 0x09: case 0x12: case 0x15: case 0x16:
    case 0x1A: case 0x1C: case 0x1F:
      return true;
    default:
      return false;
  }
}
// binary-data properties (no UTF-8 requirement), spec table 2-4 types
bool is_bin_prop(uint32_t pid) { return pid == 0x09 || pid == 0x16; }

// integer width per property id (1, 2, 4 bytes, or 0 for varint)
int int_prop_width(uint32_t pid) {
  switch (pid) {
    case 0x01: case 0x17: case 0x19: case 0x24: case 0x25: case 0x28:
    case 0x29: case 0x2A:
      return 1;
    case 0x13: case 0x21: case 0x22: case 0x23:
      return 2;
    case 0x02: case 0x11: case 0x18: case 0x27:
      return 4;
    default:
      return -1;
  }
}

// Decode one property block (spec 2.2.2): length varint + properties.
// ctx is the packet-type code, or kWillCtx for the will block.
bool decode_props(Reader &r, int ctx, Props &out) {
  uint32_t plen = r.varint();
  if (r.err) return false;
  int64_t end = r.off + plen;
  if (end > r.len) {
    r.err = true;
    return false;
  }
  bool seen[0x2B] = {false};
  while (r.off < end) {
    uint32_t pid = r.varint();
    if (r.err) return false;
    if (pid > 0x2A || !(prop_mask(pid) & (1u << ctx))) {
      r.err = true;  // unknown / invalid-in-this-packet property
      return false;
    }
    // 2.2.2.2: a property may appear at most once, except User
    // Property; Subscription Identifier repeats in PUBLISH delivery
    if (pid != 0x26 && pid != 0x0B) {
      if (seen[pid]) {
        r.err = true;
        return false;
      }
      seen[pid] = true;
    }
    if (pid == 0x0B) {  // Subscription Identifier: varint, nonzero
      uint32_t sid = r.varint();
      if (r.err) return false;
      if (sid == 0) {
        r.err = true;
        return false;
      }
      out.sub_ids.push_back(sid);
    } else if (pid == 0x26) {  // User Property: two UTF-8 strings
      const uint8_t *k;
      int64_t kn;
      const uint8_t *v;
      int64_t vn;
      if (!str(r, &k, &kn) || !str(r, &v, &vn)) return false;
      out.user_props.emplace_back(std::string((const char *)k, kn),
                                  std::string((const char *)v, vn));
    } else if (is_str_prop(pid)) {
      const uint8_t *s;
      int64_t n;
      if (is_bin_prop(pid)) {
        if (!r.bin(&s, &n)) return false;
      } else {
        if (!str(r, &s, &n)) return false;
      }
      out.has_str[pid] = true;
      out.strs[pid].assign((const char *)s, n);
    } else {
      int w = int_prop_width(pid);
      int64_t v;
      if (w == 1) v = r.u8();
      else if (w == 2) v = r.u16();
      else v = r.u32();
      if (r.err) return false;
      // value constraints the production codec also enforces at decode
      if (pid == 0x21 && v == 0) r.err = true;  // Receive Max 0 (3.1.2.11.3)
      if (pid == 0x23 && v == 0) r.err = true;  // Topic Alias 0 (3.3.2.3.4)
      if (pid == 0x27 && v == 0) r.err = true;  // Max Packet Size 0
      if (pid == 0x24 && v > 1) r.err = true;   // Maximum QoS in {0,1}
      if (r.err) return false;
      out.vals[pid] = v;
    }
  }
  if (r.off != end) {  // property value crossed the declared block end
    r.err = true;
    return false;
  }
  return true;
}

void emit_props(std::string &out, const Props &p, const char *prefix) {
  for (uint32_t pid = 1; pid <= 0x2A; pid++) {
    char key[24];
    snprintf(key, sizeof key, "%sp.%u", prefix, pid);
    if (pid == 0x0B) {
      for (uint32_t sid : p.sub_ids) emit_kv(out, key, sid);
    } else if (pid == 0x26) {
      for (const auto &kv : p.user_props) {
        out += key;
        out += '=';
        emit_hex_nonl(out, (const uint8_t *)kv.first.data(),
                      (int64_t)kv.first.size());
        out += ',';
        emit_hex_nonl(out, (const uint8_t *)kv.second.data(),
                      (int64_t)kv.second.size());
        out += '\n';
      }
    } else if (is_str_prop(pid)) {
      // empty values canonicalize to absent (comparison contract)
      if (p.has_str[pid] && !p.strs[pid].empty())
        emit_khex(out, key, p.strs[pid]);
    } else if (p.vals[pid] >= 0) {
      emit_kv(out, key, p.vals[pid]);
    }
  }
}

// ------------------------------------------------------------- per-type

bool dec_connect(Reader &r, std::string &out) {
  const uint8_t *nm;
  int64_t nn;
  if (!str(r, &nm, &nn)) return false;
  uint8_t ver = r.u8();
  if (r.err) return false;
  // 3.1.2.1/3.1.2.2 + the 3.1 dialect: name/level pairs
  bool known = (ver == 3 && nn == 6 && !memcmp(nm, "MQIsdp", 6)) ||
               ((ver == 4 || ver == 5) && nn == 4 && !memcmp(nm, "MQTT", 4));
  if (!known) return false;
  bool v5 = ver == 5;
  uint8_t flags = r.u8();
  if (r.err) return false;
  if (flags & 0x01) return false;  // reserved bit [MQTT-3.1.2-3]
  bool clean = flags & 0x02;
  bool will_flag = flags & 0x04;
  uint8_t will_qos = (flags >> 3) & 0x3;
  bool will_retain = flags & 0x20;
  bool pass_flag = flags & 0x40;
  bool user_flag = flags & 0x80;
  if (!will_flag && (will_qos || will_retain)) return false;  // 3.1.2-11..15
  if (will_qos > 2) return false;                             // 3.1.2-14
  // [MQTT-3.1.2-22] (3.1.1): password requires username; v5 lifts it
  if (pass_flag && !user_flag && !v5) return false;
  uint16_t keepalive = r.u16();
  if (r.err) return false;
  Props props;
  if (v5 && !decode_props(r, kConnect, props)) return false;
  const uint8_t *cid;
  int64_t cidn;
  if (!str(r, &cid, &cidn)) return false;

  emit_kv(out, "v", ver);
  emit_kv(out, "clean", clean ? 1 : 0);
  emit_kv(out, "ka", keepalive);
  emit_props(out, props, "");
  emit_khex(out, "cid", cid, cidn);
  if (will_flag) {
    Props wprops;
    if (v5 && !decode_props(r, kWillCtx, wprops)) return false;
    const uint8_t *wt;
    int64_t wtn;
    if (!str(r, &wt, &wtn)) return false;
    const uint8_t *wp;
    int64_t wpn;
    if (!r.bin(&wp, &wpn)) return false;
    if (wtn == 0) return false;  // empty will topic
    emit_kv(out, "w", 1);
    emit_kv(out, "w.qos", will_qos);
    emit_kv(out, "w.retain", will_retain ? 1 : 0);
    emit_props(out, wprops, "w.");
    emit_khex(out, "w.topic", wt, wtn);
    emit_khex(out, "w.payload", wp, wpn);
  }
  emit_kv(out, "uf", user_flag ? 1 : 0);
  if (user_flag) {
    const uint8_t *u;
    int64_t un;
    if (!r.bin(&u, &un)) return false;
    emit_khex(out, "un", u, un);
  }
  emit_kv(out, "pf", pass_flag ? 1 : 0);
  if (pass_flag) {
    const uint8_t *pw;
    int64_t pn;
    if (!r.bin(&pw, &pn)) return false;
    emit_khex(out, "pw", pw, pn);
  }
  if (r.off != r.len) return false;  // trailing bytes after payload
  return true;
}

bool dec_publish(Reader &r, bool v5, int qos, std::string &out) {
  const uint8_t *t;
  int64_t tn;
  if (!str(r, &t, &tn)) return false;
  int64_t pid = 0;
  if (qos > 0) {
    pid = r.u16();
    if (r.err) return false;
    if (pid == 0) return false;  // [MQTT-2.3.1-1]
  }
  Props props;
  if (v5 && !decode_props(r, kPublish, props)) return false;
  emit_khex(out, "topic", t, tn);
  emit_kv(out, "pid", pid);
  emit_props(out, props, "");
  emit_khex(out, "pl", r.p + r.off, r.len - r.off);
  return true;
}

bool dec_sub_unsub(Reader &r, bool v5, bool subscribe, std::string &out) {
  int64_t pid = r.u16();
  if (r.err) return false;
  if (pid == 0) return false;  // [MQTT-2.3.1-1]
  Props props;
  if (v5 &&
      !decode_props(r, subscribe ? kSubscribe : kUnsubscribe, props))
    return false;
  if (subscribe && props.sub_ids.size() > 1) return false;
  emit_kv(out, "pid", pid);
  emit_props(out, props, "");
  int nfilters = 0;
  while (r.off < r.len) {
    const uint8_t *f;
    int64_t fn;
    if (!str(r, &f, &fn)) return false;
    if (subscribe) {
      uint8_t opts = r.u8();
      if (r.err) return false;  // filter missing options byte
      if ((opts & 0x3) == 3) return false;  // QoS 3 [MQTT-3.8.3-4]
      if (v5) {
        if (opts & 0xC0) return false;         // reserved bits (3.8.3.1)
        if (((opts >> 4) & 0x3) == 3) return false;  // retain handling 3
      } else {
        if (opts & 0xFC) return false;  // 3.1.1: upper 6 bits reserved
      }
      out += "f=";
      emit_hex_nonl(out, f, fn);
      char buf[32];
      if (v5)
        snprintf(buf, sizeof buf, ",%d,%d,%d,%d\n", opts & 0x3,
                 (opts >> 2) & 1, (opts >> 3) & 1, (opts >> 4) & 0x3);
      else
        snprintf(buf, sizeof buf, ",%d,0,0,0\n", opts & 0x3);
      out += buf;
    } else {
      emit_khex(out, "f", f, fn);
    }
    nfilters++;
  }
  if (nfilters == 0) return false;  // [MQTT-3.8.3-3] / [MQTT-3.10.3-2]
  return true;
}

}  // namespace

// --------------------------------------------------------------- C ABI

// Decode one packet: first_byte + declared remaining length + body.
// proto_ver is the session protocol level (3, 4, or 5); a CONNECT body
// carries its own. Writes the canonical text form to out.
// Returns: >=0 canonical length; -1 reject (malformed / protocol
// error); -2 out buffer too small.
extern "C" int64_t mq_ref_decode(uint8_t first_byte, int64_t remaining,
                                 const uint8_t *body, int64_t body_len,
                                 int32_t proto_ver, char *out,
                                 int64_t out_cap) {
  int type = (first_byte >> 4) & 0xF;
  int flags = first_byte & 0xF;
  bool v5 = proto_ver == 5;

  // fixed-header flag rules, spec table 2-2
  int qos = 0;
  bool dup = false, retain = false;
  if (type == kPublish) {
    dup = flags & 0x8;
    qos = (flags >> 1) & 0x3;
    retain = flags & 0x1;
    if (qos == 3) return -1;  // [MQTT-3.3.1-4]
    // dup on a QoS-0 message violates the SENDER rule [MQTT-3.3.1-2];
    // receivers tolerate it (mochi's TPublishDup is a pass case)
  } else {
    int required;
    switch (type) {
      case kConnect: case kConnack: case kPuback: case kPubrec:
      case kPubcomp: case kSuback: case kUnsuback: case kPingreq:
      case kPingresp: case kDisconnect: case kAuth:
        required = 0;
        break;
      case kPubrel: case kSubscribe: case kUnsubscribe:
        required = 2;  // spec table 2-2: bit 1 set
        break;
      default:
        return -1;  // reserved packet type 0
    }
    if (flags != required) return -1;
  }
  if (remaining > body_len) return -1;  // truncated body

  Reader r{body, body_len};
  std::string canon;
  emit_kv(canon, "t", type);
  if (type == kPublish) {
    emit_kv(canon, "dup", dup ? 1 : 0);
    emit_kv(canon, "qos", qos);
    emit_kv(canon, "retain", retain ? 1 : 0);
  }

  bool ok = true;
  Props props;
  switch (type) {
    case kConnect:
      ok = dec_connect(r, canon);
      break;
    case kConnack: {
      uint8_t ack = r.u8();
      uint8_t rc = r.u8();
      if (r.err) {
        ok = false;
        break;
      }
      emit_kv(canon, "sp", ack & 0x1);  // bit 0; upper bits tolerated
      emit_kv(canon, "rc", rc);
      if (v5) ok = decode_props(r, kConnack, props);
      if (ok) emit_props(canon, props, "");
      break;
    }
    case kPublish:
      ok = dec_publish(r, v5, qos, canon);
      break;
    case kPuback:
    case kPubrec:
    case kPubrel:
    case kPubcomp: {
      int64_t pid = r.u16();
      if (r.err) {
        ok = false;
        break;
      }
      int64_t rc = 0;
      if (v5 && r.len > r.off) {
        rc = r.u8();
        if (r.len > r.off) ok = decode_props(r, type, props);
      }
      emit_kv(canon, "pid", pid);
      emit_kv(canon, "rc", rc);
      if (ok) emit_props(canon, props, "");
      break;
    }
    case kSubscribe:
      ok = dec_sub_unsub(r, v5, true, canon);
      break;
    case kUnsubscribe:
      ok = dec_sub_unsub(r, v5, false, canon);
      break;
    case kSuback: {
      int64_t pid = r.u16();
      if (r.err) {
        ok = false;
        break;
      }
      if (v5) ok = decode_props(r, kSuback, props);
      if (!ok) break;
      emit_kv(canon, "pid", pid);
      emit_props(canon, props, "");
      emit_khex(canon, "rcs", r.p + r.off, r.len - r.off);
      break;
    }
    case kUnsuback: {
      int64_t pid = r.u16();
      if (r.err) {
        ok = false;
        break;
      }
      emit_kv(canon, "pid", pid);
      if (v5) {
        ok = decode_props(r, kUnsuback, props);
        if (!ok) break;
        emit_props(canon, props, "");
        emit_khex(canon, "rcs", r.p + r.off, r.len - r.off);
      }
      // 3.1.1: UNSUBACK carries no payload; trailing bytes tolerated
      break;
    }
    case kPingreq:
    case kPingresp:
      break;  // no variable header, no payload
    case kDisconnect: {
      int64_t rc = 0;
      if (v5 && r.len > 0) {
        rc = r.u8();
        if (r.err) {
          ok = false;
          break;
        }
        if (r.len > 1) ok = decode_props(r, kDisconnect, props);
      }
      emit_kv(canon, "rc", rc);
      if (ok) emit_props(canon, props, "");
      break;
    }
    case kAuth: {
      if (!v5) return -1;  // type 15 reserved before MQTT 5
      int64_t rc = 0;
      if (r.len > 0) {
        rc = r.u8();
        if (r.err) {
          ok = false;
          break;
        }
        if (r.len > 1) ok = decode_props(r, kAuth, props);
      }
      emit_kv(canon, "rc", rc);
      if (ok) emit_props(canon, props, "");
      break;
    }
    default:
      return -1;
  }
  if (!ok || r.err) return -1;
  if ((int64_t)canon.size() > out_cap) return -2;
  memcpy(out, canon.data(), canon.size());
  return (int64_t)canon.size();
}
