// maxmq_decode — CPython extension owning the decode half of the
// fixed-slot match path: candidate verification + the full per-topic
// subscriber union (maxmq_tpu/matching/sig.py:decode_fixed), plus the
// SubscriberSet result type itself.
//
// Why a C extension and not the ctypes runtime (maxmq_native.cpp): the
// decode's output is Python objects — per-topic SubscriberSets holding
// {client_id: Subscription} dicts, the merged-Subscribers shape of the
// reference's TopicsIndex.Subscribers (vendor/github.com/mochi-co/
// mqtt/v2/topics.go:484-518) — so the hot loop IS object construction
// and PyDict traffic. Doing the verify compare, the dict inserts, AND
// the result-object allocation in one C pass removes the interpreter
// dispatch that capped the python walk at ~1.5M pairs/s and the
// ~1.3us/topic object-building tail.
//
// SubscriberSet here is a heap type with C-speed construction; the
// cold-path semantics (merge_subscription, Subscription copying for
// deep_copy) stay in python and are registered via configure() so the
// v5 identifier-union rules live in exactly one place (trie.py:32-57).
//
// Per compiled snapshot the python side flattens every row's entry
// walk into an ACTION STREAM (CSR over rows). Each action is one of:
//   PLAIN  — insert the stored Subscription aliased (the common case);
//            a same-client collision calls merge_subscription exactly
//            like SubscriberSet.add (trie.py)
//   MERGE  — v5 subscription identifiers present: ALWAYS route through
//            merge_subscription so the identifier-union copy semantics
//            are preserved even for the first insert
//   SHARED — shared-group candidate: shared[(group, filter)][cid] = sub
//            [MQTT-4.8.2-4]; pre-built (group, filter) key tuples
// Verification itself mirrors sig.py:verify_pairs (window compare,
// depth rule, '$'-exclusion, valid bit) on the same arrays.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#if PY_VERSION_HEX < 0x030c0000
// pre-3.12 spelling of the PyMemberDef type/flag constants
#include <structmember.h>
#ifndef Py_T_OBJECT_EX
#define Py_T_OBJECT_EX T_OBJECT_EX
#endif
#endif

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <unordered_map>

// write-intent prefetch (the union read-modify-writes its slot);
// low temporal locality — each slot is touched once per union
#if defined(__GNUC__) || defined(__clang__)
#define PREFETCH_W(p) __builtin_prefetch((p), 1, 1)
#else
#define PREFETCH_W(p) ((void)0)
#endif
#include <vector>

namespace {

constexpr int32_t VER_PLUS = -1;   // '+' — matches any present level
constexpr int32_t VER_ANY = -2;    // past the filter / probe window
constexpr uint8_t FLAG_EXACT = 1;  // no trailing '#': depth must equal
constexpr uint8_t FLAG_WILDF = 2;  // leading wildcard: '$'-excluded
constexpr uint8_t FLAG_VALID = 4;  // row exists in this snapshot

constexpr uint8_t ACT_PLAIN = 0;
constexpr uint8_t ACT_MERGE = 1;
constexpr uint8_t ACT_SHARED = 2;

// registered by trie.py:configure() — the python-side semantics
PyObject *g_merge_fn = nullptr;     // merge_subscription(base, new, filt)
PyObject *g_copy_sub = nullptr;     // copy_subscription(sub)

// ----------------------------------------------------------------- //
//  SubscriberSet — the C result type                                //
// ----------------------------------------------------------------- //

struct SubSetObject {
  PyObject_HEAD
  PyObject *subscriptions;  // dict: client_id -> Subscription
  PyObject *shared;         // dict: (group, filter) -> {cid: Subscription}
};

PyTypeObject *g_subset_type = nullptr;  // set at module init

SubSetObject *subset_alloc() {
  auto *self = PyObject_GC_New(SubSetObject, g_subset_type);
  if (!self) return nullptr;
  self->subscriptions = nullptr;
  self->shared = nullptr;
  PyObject_GC_Track(self);
  return self;
}

// fast constructor used by decode_batch: steals nothing, fills missing
// dicts lazily at first attribute read (see subset_getattro note) —
// no: keep it simple and always materialize, dict alloc is ~40ns
SubSetObject *subset_new_fast(PyObject *subs, PyObject *shared) {
  auto *self = subset_alloc();
  if (!self) return nullptr;
  self->subscriptions = subs ? Py_NewRef(subs) : PyDict_New();
  self->shared = shared ? Py_NewRef(shared) : PyDict_New();
  if (!self->subscriptions || !self->shared) {
    Py_DECREF(self);
    return nullptr;
  }
  return self;
}

int subset_init(PyObject *self_o, PyObject *args, PyObject *kwargs) {
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  PyObject *subs = nullptr, *shared = nullptr;
  static const char *kwlist[] = {"subscriptions", "shared", nullptr};
  if (!PyArg_ParseTupleAndKeywords(args, kwargs, "|OO",
                                   const_cast<char **>(kwlist), &subs,
                                   &shared))
    return -1;
  if (subs == Py_None) subs = nullptr;
  if (shared == Py_None) shared = nullptr;
  PyObject *ns = subs ? Py_NewRef(subs) : PyDict_New();
  PyObject *nh = shared ? Py_NewRef(shared) : PyDict_New();
  if (!ns || !nh) {
    Py_XDECREF(ns);
    Py_XDECREF(nh);
    return -1;
  }
  Py_XSETREF(self->subscriptions, ns);
  Py_XSETREF(self->shared, nh);
  return 0;
}

int subset_traverse(PyObject *self_o, visitproc visit, void *arg) {
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  Py_VISIT(self->subscriptions);
  Py_VISIT(self->shared);
  return 0;
}

int subset_clear(PyObject *self_o) {
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  Py_CLEAR(self->subscriptions);
  Py_CLEAR(self->shared);
  return 0;
}

void subset_dealloc(PyObject *self_o) {
  PyObject_GC_UnTrack(self_o);
  subset_clear(self_o);
  PyTypeObject *tp = Py_TYPE(self_o);
  PyObject_GC_Del(self_o);
  Py_DECREF(tp);  // heap types own a ref from each instance
}

// add(client_id, sub, filter_) — merge-insert one non-shared
// subscription; mirrors trie.py SubscriberSet.add
PyObject *subset_add(PyObject *self_o, PyObject *const *args,
                     Py_ssize_t nargs) {
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError,
                    "add(client_id, sub, filter_) takes 3 arguments");
    return nullptr;
  }
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  PyObject *cur = PyDict_GetItemWithError(self->subscriptions, args[0]);
  if (!cur && PyErr_Occurred()) return nullptr;
  PyObject *mg = PyObject_CallFunctionObjArgs(
      g_merge_fn, cur ? cur : Py_None, args[1], args[2], nullptr);
  if (!mg) return nullptr;
  const int rc = PyDict_SetItem(self->subscriptions, args[0], mg);
  Py_DECREF(mg);
  if (rc < 0) return nullptr;
  Py_RETURN_NONE;
}

// add_shared(group, filter_, client_id, sub)
PyObject *subset_add_shared(PyObject *self_o, PyObject *const *args,
                            Py_ssize_t nargs) {
  if (nargs != 4) {
    PyErr_SetString(
        PyExc_TypeError,
        "add_shared(group, filter_, client_id, sub) takes 4 arguments");
    return nullptr;
  }
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  PyObject *key = PyTuple_Pack(2, args[0], args[1]);
  if (!key) return nullptr;
  PyObject *g = PyDict_GetItemWithError(self->shared, key);
  if (!g) {
    if (PyErr_Occurred()) {
      Py_DECREF(key);
      return nullptr;
    }
    g = PyDict_New();
    if (!g || PyDict_SetItem(self->shared, key, g) < 0) {
      Py_XDECREF(g);
      Py_DECREF(key);
      return nullptr;
    }
    Py_DECREF(g);  // borrowed from self->shared hereafter
  }
  Py_DECREF(key);
  if (PyDict_SetItem(g, args[2], args[3]) < 0) return nullptr;
  Py_RETURN_NONE;
}

// deep_copy() — copies every Subscription via the registered python
// helper; hook-facing cold path (hooks may mutate delivery params)
PyObject *subset_deep_copy(PyObject *self_o, PyObject *) {
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  PyObject *subs = PyDict_New(), *shared = nullptr;
  if (subs) shared = PyDict_New();
  if (!subs || !shared) {
    Py_XDECREF(subs);
    Py_XDECREF(shared);
    return nullptr;
  }
  auto bail = [&]() -> PyObject * {
    Py_DECREF(subs);
    Py_DECREF(shared);
    return nullptr;
  };
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(self->subscriptions, &pos, &k, &v)) {
    PyObject *cp = PyObject_CallOneArg(g_copy_sub, v);
    if (!cp || PyDict_SetItem(subs, k, cp) < 0) {
      Py_XDECREF(cp);
      return bail();
    }
    Py_DECREF(cp);
  }
  pos = 0;
  while (PyDict_Next(self->shared, &pos, &k, &v)) {
    PyObject *m = PyDict_New();
    if (!m || PyDict_SetItem(shared, k, m) < 0) {
      Py_XDECREF(m);
      return bail();
    }
    Py_DECREF(m);
    PyObject *k2, *v2;
    Py_ssize_t pos2 = 0;
    while (PyDict_Next(v, &pos2, &k2, &v2)) {
      PyObject *cp = PyObject_CallOneArg(g_copy_sub, v2);
      if (!cp || PyDict_SetItem(m, k2, cp) < 0) {
        Py_XDECREF(cp);
        return bail();
      }
      Py_DECREF(cp);
    }
  }
  auto *out = subset_new_fast(subs, shared);
  Py_DECREF(subs);
  Py_DECREF(shared);
  return reinterpret_cast<PyObject *>(out);
}

// select_copy() — the hook modify-chain form: FRESH outer dicts (the
// hook may add/drop/replace entries anywhere) over ALIASED Subscription
// records (immutable by contract, ADR 009). One C call replaces the
// per-publish python dict copies on the hook-present fan-out path.
PyObject *subset_select_copy(PyObject *self_o, PyObject *) {
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  PyObject *subs = PyDict_Copy(self->subscriptions);
  if (!subs) return nullptr;
  PyObject *shared = PyDict_New();
  if (!shared) {
    Py_DECREF(subs);
    return nullptr;
  }
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(self->shared, &pos, &k, &v)) {
    PyObject *m = PyDict_Copy(v);
    if (!m || PyDict_SetItem(shared, k, m) < 0) {
      Py_XDECREF(m);
      Py_DECREF(subs);
      Py_DECREF(shared);
      return nullptr;
    }
    Py_DECREF(m);
  }
  auto *out = subset_new_fast(subs, shared);
  Py_DECREF(subs);
  Py_DECREF(shared);
  return reinterpret_cast<PyObject *>(out);
}

Py_ssize_t subset_len(PyObject *self_o) {
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  Py_ssize_t n = PyDict_Size(self->subscriptions);
  PyObject *k, *v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(self->shared, &pos, &k, &v)) n += PyDict_Size(v);
  return n;
}

PyObject *subset_richcompare(PyObject *a, PyObject *b, int op) {
  if ((op != Py_EQ && op != Py_NE) ||
      !PyObject_TypeCheck(a, g_subset_type) ||
      !PyObject_TypeCheck(b, g_subset_type))
    Py_RETURN_NOTIMPLEMENTED;
  auto *x = reinterpret_cast<SubSetObject *>(a);
  auto *y = reinterpret_cast<SubSetObject *>(b);
  int eq = PyObject_RichCompareBool(x->subscriptions, y->subscriptions,
                                    Py_EQ);
  if (eq > 0) eq = PyObject_RichCompareBool(x->shared, y->shared, Py_EQ);
  if (eq < 0) return nullptr;
  return PyBool_FromLong(op == Py_EQ ? eq : !eq);
}

PyObject *subset_repr(PyObject *self_o) {
  auto *self = reinterpret_cast<SubSetObject *>(self_o);
  return PyUnicode_FromFormat("SubscriberSet(subscriptions=%R, shared=%R)",
                              self->subscriptions, self->shared);
}

PyMemberDef subset_members[] = {
    {"subscriptions", Py_T_OBJECT_EX, offsetof(SubSetObject, subscriptions),
     0, "client_id -> merged Subscription"},
    {"shared", Py_T_OBJECT_EX, offsetof(SubSetObject, shared), 0,
     "(group, filter) -> {client_id: Subscription}"},
    {nullptr, 0, 0, 0, nullptr}};

PyMethodDef subset_methods[] = {
    {"add", reinterpret_cast<PyCFunction>(subset_add), METH_FASTCALL,
     "Merge-insert a non-shared subscription."},
    {"add_shared", reinterpret_cast<PyCFunction>(subset_add_shared),
     METH_FASTCALL, "Insert a shared-group candidate."},
    {"deep_copy", subset_deep_copy, METH_NOARGS,
     "Subscription-deep copy for hooks that may mutate."},
    {"select_copy", subset_select_copy, METH_NOARGS,
     "Fresh outer dicts over aliased records (hook modify-chain form)."},
    {nullptr, nullptr, 0, nullptr}};

PyType_Slot subset_slots[] = {
    {Py_tp_doc, const_cast<char *>(
         "Result of a topic match: per-client merged non-shared "
         "subscriptions and shared-group candidate maps "
         "(group -> client -> subscription). C-accelerated twin of "
         "matching/trie.py's python fallback.")},
    {Py_tp_init, reinterpret_cast<void *>(subset_init)},
    {Py_tp_dealloc, reinterpret_cast<void *>(subset_dealloc)},
    {Py_tp_traverse, reinterpret_cast<void *>(subset_traverse)},
    {Py_tp_clear, reinterpret_cast<void *>(subset_clear)},
    {Py_tp_members, subset_members},
    {Py_tp_methods, subset_methods},
    {Py_sq_length, reinterpret_cast<void *>(subset_len)},
    {Py_tp_richcompare, reinterpret_cast<void *>(subset_richcompare)},
    {Py_tp_repr, reinterpret_cast<void *>(subset_repr)},
    {0, nullptr}};

PyType_Spec subset_spec = {
    "maxmq_decode.SubscriberSet", sizeof(SubSetObject), 0,
    Py_TPFLAGS_DEFAULT | Py_TPFLAGS_BASETYPE | Py_TPFLAGS_HAVE_GC,
    subset_slots};

// ----------------------------------------------------------------- //
//  DeliveryIntents — the fan-out hot-path result type               //
// ----------------------------------------------------------------- //
//
// The broker's fan-out does not need a {client_id: Subscription} dict
// per publish — it needs to ITERATE deliveries (reference boundary:
// publishToSubscribers consuming Subscribers(), vendor/.../v2/
// server.go:766-793). Materializing the merged dict per topic is what
// capped the 1M-sub decode at ~12K topics/s: ~330 scattered dict
// inserts + ~660 refcount writes across a ~1M-object heap per topic
// (BASELINE-COMPARE.md r03). DeliveryIntents replaces that with two
// flat pointer arrays BORROWED from the immutable decode table (kept
// alive by one strong ref to the table capsule): construction per
// row-set is an epoch-stamped dedupe writing int32s and pointers —
// no dict, no per-entry refcounting. Same-client overlapping-filter
// collisions (rare) still route through merge_subscription and own
// their merged record. Shared-group candidates keep the dict shape
// ($share selection needs keyed maps). to_set() materializes a full
// SubscriberSet lazily for the hook path (on_select_subscribers) and
// caches it — intents are cached per row-set and shared across
// topics, so consumers treat them as immutable, like cached sets.
//
// CHAINED form (the cold-stream wall killer): on fan-out-heavy corpora
// one shallow-'#' row carries hundreds of entries and the other rows a
// handful, and a cold unique-topic stream makes every row SET distinct
// — so the per-topic union re-copied those hundreds of pairs through
// the DRAM-latency-bound mark table every single topic (measured
// ~43ns/pair, 14us/topic at 1M subs). A chained intents instead holds
// a strong ref to the fat row's SINGLE-ROW cached intents (immutable,
// built once per table rotation) plus only the thin per-topic tail,
// with same-client collisions against the base expressed as slot
// OVERRIDES applied during iteration. Construction cost per topic
// drops from O(total pairs) to O(tail pairs); iteration still yields
// exactly the merged (client, Subscription) stream.

struct IntentsObject {
  PyObject_HEAD
  PyObject *table_cap;  // strong ref: keeps borrowed cid/sub ptrs alive
  Py_ssize_t n;         // OWN plain (non-shared) delivery entries
  PyObject **cids;      // [n] borrowed from the table's cid list
  PyObject **subs;      // [n] borrowed, or owned when owned[i]
  uint8_t *owned;       // [n] subs[i] is an owned merged Subscription
  PyObject *shared;     // (group, filter) -> {cid: sub}, or NULL
  PyObject *set_cache;  // lazily-built SubscriberSet twin
  // chain: own entries are the tail; base holds the fat row's pairs
  // chain bases (round 5: a LIST — heavy cold sets hold several fat
  // '#' rows whose per-row intents all repeat across topics even
  // though their combinations do not): cached single-row intents in
  // ascending row order. The iteration/override slot space is the
  // concatenation of the bases' entries; base_off[j] is base j's
  // first global slot, base_off[n_bases] the total.
  IntentsObject **bases;  // strong refs; one block with base_off
  int32_t *base_off;      // [n_bases + 1] cumulative entry offsets
  int32_t n_bases;
  int32_t *ovr_slots;   // [n_ovr] base slots shadowed, ascending
  PyObject **ovr_subs;  // [n_ovr] owned merged Subscriptions
  Py_ssize_t n_ovr;
  uint8_t sel_seen;     // select_set() ran once (cache on the re-hit)
};

// total plain entries a consumer sees (tail + bases; overrides shadow)
static inline Py_ssize_t intents_total(const IntentsObject *self) {
  return self->n + (self->n_bases ? self->base_off[self->n_bases] : 0);
}

// resolve a global base slot to the base's stored subscription
static inline PyObject *base_sub_at(const IntentsObject *self,
                                    int32_t gs) {
  int32_t b = 0;
  while (gs >= self->base_off[b + 1]) b++;
  return self->bases[b]->subs[gs - self->base_off[b]];
}

static inline PyObject *base_cid_at(const IntentsObject *self,
                                    int32_t gs) {
  int32_t b = 0;
  while (gs >= self->base_off[b + 1]) b++;
  return self->bases[b]->cids[gs - self->base_off[b]];
}

PyTypeObject *g_intents_type = nullptr;
PyTypeObject *g_intents_iter_type = nullptr;

// Intents objects are deliberately NOT GC-tracked: the only reference
// cycle they can sit on runs through the decode-table capsule, which
// is itself invisible to the cycle collector (capsules are never
// tracked) and is broken manually by table_release — so tracking buys
// no collectable cycle while making every GC pass walk the hundreds
// of thousands of cached results, and every cache clear a multi-second
// GC storm (measured: a recurring ~40x whole-batch stall at each
// icache fill). Nothing else can close a cycle onto an intents object:
// its referents are str client ids, plain Subscription records, dicts
// of those, the capsule, and an (acyclic) base intents. tp_traverse /
// tp_clear remain implemented for the HAVE_GC protocol and dealloc.
// COROLLARY OF THE EXISTING IMMUTABILITY CONTRACT (decode_pairs
// docstring): consumers must never graft a reference back onto a
// result's Subscription records (e.g. sub.attr = intents) — results
// and their records are shared, immutable, and deep_copy()'d before
// any mutation, so such a cycle cannot legally arise; an illegal one
// would now be uncollectable.
IntentsObject *intents_alloc(PyObject *capsule, Py_ssize_t capacity) {
  auto *self = PyObject_GC_New(IntentsObject, g_intents_type);
  if (!self) return nullptr;
  self->table_cap = Py_NewRef(capsule);
  self->n = 0;
  self->cids = nullptr;
  self->subs = nullptr;
  self->owned = nullptr;
  self->shared = nullptr;
  self->set_cache = nullptr;
  self->bases = nullptr;
  self->base_off = nullptr;
  self->n_bases = 0;
  self->ovr_slots = nullptr;
  self->ovr_subs = nullptr;
  self->n_ovr = 0;
  self->sel_seen = 0;
  if (capacity) {
    // one block for all three arrays (cids | subs | owned): chain
    // tails allocate per cold topic, so two fewer malloc/free pairs
    // per result is measurable; intents_clear_slot frees cids only
    char *block = static_cast<char *>(
        PyMem_Malloc(capacity * (2 * sizeof(PyObject *) + 1)));
    if (!block) {
      Py_DECREF(self);
      PyErr_NoMemory();
      return nullptr;
    }
    self->cids = reinterpret_cast<PyObject **>(block);
    self->subs = reinterpret_cast<PyObject **>(
        block + capacity * sizeof(PyObject *));
    self->owned = reinterpret_cast<uint8_t *>(
        block + 2 * capacity * sizeof(PyObject *));
  }
  return self;
}

int intents_traverse(PyObject *self_o, visitproc visit, void *arg) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  Py_VISIT(self->table_cap);
  Py_VISIT(self->shared);
  Py_VISIT(self->set_cache);
  for (int32_t b = 0; b < self->n_bases; b++)
    Py_VISIT(reinterpret_cast<PyObject *>(self->bases[b]));
  for (Py_ssize_t i = 0; i < self->n; i++)
    if (self->owned && self->owned[i]) Py_VISIT(self->subs[i]);
  for (Py_ssize_t i = 0; i < self->n_ovr; i++)
    Py_VISIT(self->ovr_subs[i]);
  return 0;
}

int intents_clear_slot(PyObject *self_o) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  if (self->owned)
    for (Py_ssize_t i = 0; i < self->n; i++)
      if (self->owned[i]) Py_CLEAR(self->subs[i]);
  self->n = 0;
  PyMem_Free(self->cids);  // one block carries cids+subs+owned
  self->cids = self->subs = nullptr;
  self->owned = nullptr;
  for (Py_ssize_t i = 0; i < self->n_ovr; i++)
    Py_CLEAR(self->ovr_subs[i]);
  self->n_ovr = 0;
  PyMem_Free(self->ovr_subs);  // one block: ovr_subs | ovr_slots
  self->ovr_slots = nullptr;
  self->ovr_subs = nullptr;
  for (int32_t b = 0; b < self->n_bases; b++)
    Py_CLEAR(self->bases[b]);
  self->n_bases = 0;
  PyMem_Free(self->bases);     // one block: bases | base_off
  self->bases = nullptr;
  self->base_off = nullptr;
  Py_CLEAR(self->table_cap);
  Py_CLEAR(self->shared);
  Py_CLEAR(self->set_cache);
  return 0;
}

void intents_dealloc(PyObject *self_o) {
  PyObject_GC_UnTrack(self_o);
  intents_clear_slot(self_o);
  PyTypeObject *tp = Py_TYPE(self_o);
  PyObject_GC_Del(self_o);
  Py_DECREF(tp);
}

Py_ssize_t intents_len(PyObject *self_o) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  Py_ssize_t n = intents_total(self);
  if (self->shared) {
    PyObject *k, *v;
    Py_ssize_t pos = 0;
    while (PyDict_Next(self->shared, &pos, &k, &v)) n += PyDict_Size(v);
  }
  return n;
}

// fresh plain-delivery dict: base entries first, shadowed by slot
// overrides, then the own tail
PyObject *intents_build_subs(const IntentsObject *self) {
  PyObject *subs = PyDict_New();
  if (!subs) return nullptr;
  for (int32_t b = 0; b < self->n_bases; b++) {
    const IntentsObject *bb = self->bases[b];
    for (Py_ssize_t j = 0; j < bb->n; j++)
      if (PyDict_SetItem(subs, bb->cids[j], bb->subs[j]) < 0) {
        Py_DECREF(subs);
        return nullptr;
      }
  }
  for (Py_ssize_t k = 0; k < self->n_ovr; k++)
    if (PyDict_SetItem(subs, base_cid_at(self, self->ovr_slots[k]),
                       self->ovr_subs[k]) < 0) {
      Py_DECREF(subs);
      return nullptr;
    }
  for (Py_ssize_t i = 0; i < self->n; i++)
    if (PyDict_SetItem(subs, self->cids[i], self->subs[i]) < 0) {
      Py_DECREF(subs);
      return nullptr;
    }
  return subs;
}

// to_set() -> SubscriberSet (cached): the hook-path materialization
PyObject *intents_to_set(PyObject *self_o, PyObject *) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  if (self->set_cache) return Py_NewRef(self->set_cache);
  PyObject *subs = intents_build_subs(self);
  if (!subs) return nullptr;
  // outer dict is fresh (callers re-wrap/copy it before dropping keys);
  // inner member dicts may be shared — consumers never mutate them
  PyObject *shared =
      self->shared ? PyDict_Copy(self->shared) : PyDict_New();
  if (!shared) {
    Py_DECREF(subs);
    return nullptr;
  }
  auto *res = subset_new_fast(subs, shared);
  Py_DECREF(subs);
  Py_DECREF(shared);
  if (!res) return nullptr;
  self->set_cache = reinterpret_cast<PyObject *>(res);
  return Py_NewRef(self->set_cache);
}

// select_set() -> a fresh hook-ready SubscriberSet straight from the
// intents arrays: new outer dicts AND new inner shared dicts (the
// modify chain may add/drop/replace entries anywhere) over aliased
// records. Caching policy: the FIRST call builds directly without
// populating set_cache (a cold unique-topic stream would pay a double
// build for a cache it never rehits); a SECOND call proves the row set
// repeats, so it materializes the to_set() twin once and every later
// call is a PyDict_Copy — one materialization per re-hit row set.
PyObject *intents_select_set(PyObject *self_o, PyObject *) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  if (self->set_cache) return subset_select_copy(self->set_cache, nullptr);
  if (self->sel_seen) {
    PyObject *twin = intents_to_set(self_o, nullptr);
    if (!twin) return nullptr;
    PyObject *res = subset_select_copy(twin, nullptr);
    Py_DECREF(twin);
    return res;
  }
  self->sel_seen = 1;
  PyObject *subs = intents_build_subs(self);
  if (!subs) return nullptr;
  PyObject *shared = PyDict_New();
  if (!shared) {
    Py_DECREF(subs);
    return nullptr;
  }
  if (self->shared) {
    PyObject *k, *v;
    Py_ssize_t pos = 0;
    while (PyDict_Next(self->shared, &pos, &k, &v)) {
      PyObject *m = PyDict_Copy(v);
      if (!m || PyDict_SetItem(shared, k, m) < 0) {
        Py_XDECREF(m);
        Py_DECREF(subs);
        Py_DECREF(shared);
        return nullptr;
      }
      Py_DECREF(m);
    }
  }
  auto *res = subset_new_fast(subs, shared);
  Py_DECREF(subs);
  Py_DECREF(shared);
  return reinterpret_cast<PyObject *>(res);
}

// has_client(cid) -> bool; linear scan (used only by the rare $share
// overlap check, on sets of a few hundred entries at most)
PyObject *intents_has_client(PyObject *self_o, PyObject *cid) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  auto scan = [&](const IntentsObject *part) -> int {
    for (Py_ssize_t i = 0; i < part->n; i++) {
      if (part->cids[i] == cid) return 1;
      const int eq =
          PyObject_RichCompareBool(part->cids[i], cid, Py_EQ);
      if (eq != 0) return eq;   // hit or error
    }
    return 0;
  };
  int r = scan(self);
  for (int32_t b = 0; r == 0 && b < self->n_bases; b++)
    r = scan(self->bases[b]);
  if (r < 0) return nullptr;
  return PyBool_FromLong(r);
}

PyObject *intents_get_shared(PyObject *self_o, void *) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  if (!self->shared) {
    self->shared = PyDict_New();
    if (!self->shared) return nullptr;
  }
  return Py_NewRef(self->shared);
}

PyObject *intents_get_n(PyObject *self_o, void *) {
  return PyLong_FromSsize_t(
      intents_total(reinterpret_cast<IntentsObject *>(self_o)));
}

PyObject *intents_get_chained(PyObject *self_o, void *) {
  return PyBool_FromLong(
      reinterpret_cast<IntentsObject *>(self_o)->n_bases > 0);
}

struct IntentsIterObject {
  PyObject_HEAD
  IntentsObject *it;  // strong
  Py_ssize_t i;
  Py_ssize_t oi;  // cursor into ovr_slots (ascending, so O(1) amort.)
  int32_t b;      // current base (global slots ascend with iteration)
};

PyObject *intents_iter(PyObject *self_o) {
  auto *iter = PyObject_GC_New(IntentsIterObject, g_intents_iter_type);
  if (!iter) return nullptr;
  iter->it = reinterpret_cast<IntentsObject *>(Py_NewRef(self_o));
  iter->i = 0;
  iter->oi = 0;
  iter->b = 0;
  PyObject_GC_Track(iter);
  return reinterpret_cast<PyObject *>(iter);
}

PyObject *intents_iternext(PyObject *self_o) {
  auto *self = reinterpret_cast<IntentsIterObject *>(self_o);
  IntentsObject *v = self->it;
  const Py_ssize_t i = self->i;
  if (i < v->n) {
    self->i++;
    return PyTuple_Pack(2, v->cids[i], v->subs[i]);
  }
  if (!v->n_bases) return nullptr;  // StopIteration
  const Py_ssize_t j = i - v->n;    // global base slot
  if (j >= v->base_off[v->n_bases]) return nullptr;
  while (j >= v->base_off[self->b + 1]) self->b++;
  const IntentsObject *bb = v->bases[self->b];
  const Py_ssize_t lj = j - v->base_off[self->b];
  self->i++;
  while (self->oi < v->n_ovr && v->ovr_slots[self->oi] < j) self->oi++;
  PyObject *sub = (self->oi < v->n_ovr && v->ovr_slots[self->oi] == j)
                      ? v->ovr_subs[self->oi]
                      : bb->subs[lj];
  return PyTuple_Pack(2, bb->cids[lj], sub);
}

int intents_iter_traverse(PyObject *self_o, visitproc visit, void *arg) {
  Py_VISIT(reinterpret_cast<IntentsIterObject *>(self_o)->it);
  return 0;
}

void intents_iter_dealloc(PyObject *self_o) {
  PyObject_GC_UnTrack(self_o);
  Py_CLEAR(reinterpret_cast<IntentsIterObject *>(self_o)->it);
  PyTypeObject *tp = Py_TYPE(self_o);
  PyObject_GC_Del(self_o);
  Py_DECREF(tp);
}

PyObject *intents_repr(PyObject *self_o) {
  auto *self = reinterpret_cast<IntentsObject *>(self_o);
  if (self->n_bases)
    return PyUnicode_FromFormat(
        "DeliveryIntents(n=%zd, tail=%zd, bases=%d, overrides=%zd, "
        "shared=%zd)",
        intents_total(self), self->n, (int)self->n_bases, self->n_ovr,
        self->shared ? PyDict_Size(self->shared) : (Py_ssize_t)0);
  return PyUnicode_FromFormat(
      "DeliveryIntents(n=%zd, shared=%zd)", self->n,
      self->shared ? PyDict_Size(self->shared) : (Py_ssize_t)0);
}

PyMethodDef intents_methods[] = {
    {"to_set", intents_to_set, METH_NOARGS,
     "Materialize (and cache) the SubscriberSet twin for hook paths."},
    {"select_set", intents_select_set, METH_NOARGS,
     "Fresh hook-ready SubscriberSet (new dicts, aliased records)."},
    {"has_client", intents_has_client, METH_O,
     "True when the client id has a plain (non-shared) delivery entry."},
    {nullptr, nullptr, 0, nullptr}};

PyGetSetDef intents_getset[] = {
    {"shared", intents_get_shared, nullptr,
     "(group, filter) -> {client_id: Subscription} candidates", nullptr},
    {"n", intents_get_n, nullptr, "plain delivery entry count", nullptr},
    {"chained", intents_get_chained, nullptr,
     "True when anchored on a cached fat-row base fragment", nullptr},
    {nullptr, nullptr, nullptr, nullptr, nullptr}};

PyType_Slot intents_slots[] = {
    {Py_tp_doc, const_cast<char *>(
         "Per-topic delivery intents: iterable of (client_id, "
         "Subscription) plus shared-group candidate maps — the "
         "fan-out-ready decode result that skips merged-dict "
         "construction. Immutable; shared across topics and calls.")},
    {Py_tp_dealloc, reinterpret_cast<void *>(intents_dealloc)},
    {Py_tp_traverse, reinterpret_cast<void *>(intents_traverse)},
    {Py_tp_clear, reinterpret_cast<void *>(intents_clear_slot)},
    {Py_tp_methods, intents_methods},
    {Py_tp_getset, intents_getset},
    {Py_tp_iter, reinterpret_cast<void *>(intents_iter)},
    {Py_sq_length, reinterpret_cast<void *>(intents_len)},
    {Py_tp_repr, reinterpret_cast<void *>(intents_repr)},
    {0, nullptr}};

PyType_Spec intents_spec = {
    "maxmq_decode.DeliveryIntents", sizeof(IntentsObject), 0,
    Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC | Py_TPFLAGS_DISALLOW_INSTANTIATION,
    intents_slots};

PyType_Slot intents_iter_slots[] = {
    {Py_tp_dealloc, reinterpret_cast<void *>(intents_iter_dealloc)},
    {Py_tp_traverse, reinterpret_cast<void *>(intents_iter_traverse)},
    {Py_tp_iter, reinterpret_cast<void *>(PyObject_SelfIter)},
    {Py_tp_iternext, reinterpret_cast<void *>(intents_iternext)},
    {0, nullptr}};

PyType_Spec intents_iter_spec = {
    "maxmq_decode._DeliveryIntentsIter", sizeof(IntentsIterObject), 0,
    Py_TPFLAGS_DEFAULT | Py_TPFLAGS_HAVE_GC | Py_TPFLAGS_DISALLOW_INSTANTIATION,
    intents_iter_slots};

// configure(merge_fn, copy_sub_fn) — register the python semantics
PyObject *configure(PyObject *, PyObject *args) {
  PyObject *merge, *copy;
  if (!PyArg_ParseTuple(args, "OO", &merge, &copy)) return nullptr;
  Py_XSETREF(g_merge_fn, Py_NewRef(merge));
  Py_XSETREF(g_copy_sub, Py_NewRef(copy));
  Py_RETURN_NONE;
}

// the chained union must be indistinguishable from the full union —
// this test-only switch lets the suite A/B the two builds of the SAME
// row set (flags included, not just the normalize() projection)
bool g_chain_enabled = true;
bool g_multi_base = true;

// chain-decision thresholds (settable for measurement/tests): anchor
// on the fattest row when it has >= min_base plain entries and the
// tail is at most (tail_num/tail_den) of it. Cost model: a tail pair
// costs one slot-map probe (~30ns) on top of the scratch work it pays
// either way, while every base pair SKIPS its ~43ns mark-table visit —
// so chaining pays off whenever fat*43 > tail*30, with min_base
// amortizing the fixed per-chain overhead (base lookup + override
// machinery). Defaults measured on the 1M bench corpus (see ADR 007).
Py_ssize_t g_chain_min_base = 64;
Py_ssize_t g_chain_tail_num = 1;
Py_ssize_t g_chain_tail_den = 1;

// opt-in section timing for the decode hot path (profiling builds of
// the bench drive it via _timing_reset/_timing_get; zero cost when off)
struct DecodeTiming {
  int64_t pass1_ns = 0, pass2_ns = 0, construct_ns = 0;
  int64_t constructs = 0, shared_ns = 0;
  // chain-decision census over timed constructions
  int64_t chained = 0, single_row = 0, decl_minbase = 0, decl_ratio = 0;
  int64_t decl_budget = 0;     // slot-map budget exhausted
  int64_t resolve_ns = 0;      // candidate->base resolution time
  int64_t multi_base = 0;      // chains composing >= 2 row bases
  int64_t entries_built = 0;   // plain entries allocated (tail or full)
};
DecodeTiming g_timing;
bool g_timing_on = false;
int g_timing_depth = 0;   // recursion guard: only depth-0 accumulates

static inline int64_t now_ns() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

struct TimeAcc {
  int64_t *dst;
  int64_t t0;
  bool armed;
  explicit TimeAcc(int64_t *d)
      : dst(d), t0(0), armed(g_timing_on && g_timing_depth == 0) {
    if (armed) t0 = now_ns();
  }
  ~TimeAcc() {
    if (armed) *dst += now_ns() - t0;
  }
};

PyObject *timing_reset(PyObject *, PyObject *arg) {
  const int v = PyObject_IsTrue(arg);
  if (v < 0) return nullptr;
  g_timing = DecodeTiming{};
  g_timing_on = v != 0;
  Py_RETURN_NONE;
}

PyObject *timing_get(PyObject *, PyObject *) {
  return Py_BuildValue(
      "{s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L,s:L}",
      "pass1_ns", (long long)g_timing.pass1_ns,
      "pass2_ns", (long long)g_timing.pass2_ns,
      "construct_ns", (long long)g_timing.construct_ns,
      "constructs", (long long)g_timing.constructs,
      "shared_ns", (long long)g_timing.shared_ns,
      "chained", (long long)g_timing.chained,
      "multi_base", (long long)g_timing.multi_base,
      "single_row", (long long)g_timing.single_row,
      "decl_minbase", (long long)g_timing.decl_minbase,
      "decl_budget", (long long)g_timing.decl_budget,
      "resolve_ns", (long long)g_timing.resolve_ns,
      "decl_ratio", (long long)g_timing.decl_ratio,
      "entries_built", (long long)g_timing.entries_built);
}

PyObject *set_chain_enabled(PyObject *, PyObject *arg) {
  const int v = PyObject_IsTrue(arg);
  if (v < 0) return nullptr;
  g_chain_enabled = v != 0;
  Py_RETURN_NONE;
}

PyObject *set_multi_base(PyObject *, PyObject *arg) {
  const int v = PyObject_IsTrue(arg);
  if (v < 0) return nullptr;
  g_multi_base = v != 0;
  Py_RETURN_NONE;
}

PyObject *set_chain_params(PyObject *, PyObject *args) {
  Py_ssize_t mb, num, den;
  if (!PyArg_ParseTuple(args, "nnn", &mb, &num, &den)) return nullptr;
  // the ratio test multiplies pair counts by num/den — bound them so
  // the products cannot overflow Py_ssize_t (pair counts < 2^40)
  if (mb < 1 || num < 0 || num > (1 << 20) || den < 1 ||
      den > (1 << 20)) {
    PyErr_SetString(PyExc_ValueError, "invalid chain params");
    return nullptr;
  }
  g_chain_min_base = mb;
  g_chain_tail_num = num;
  g_chain_tail_den = den;
  Py_RETURN_NONE;
}

PyObject *get_chain_params(PyObject *, PyObject *) {
  return Py_BuildValue("(nnn)", g_chain_min_base, g_chain_tail_num,
                       g_chain_tail_den);
}

// ----------------------------------------------------------------- //
//  Decode table + batch                                             //
// ----------------------------------------------------------------- //

struct DecodeTable {
  Py_buffer tok;        // int32 [R, W] row-major
  Py_buffer min_depth;  // int32 [R]
  Py_buffer flags;      // uint8 [R]
  Py_buffer offsets;    // int64 [R + 1] action CSR
  Py_buffer kinds;      // uint8 [A]
  PyObject *keys;       // list len A: filter str (PLAIN/MERGE) or
                        //             (group, filter) tuple (SHARED)
  PyObject *cids;       // list len A: client-id str
  PyObject *subs;       // list len A: Subscription
  PyObject *cache;      // verified-row-set bytes -> SubscriberSet
  PyObject *frag;       // row int -> single-row SubscriberSet fragment
  PyObject *icache;     // verified-row-set bytes -> DeliveryIntents
  Py_ssize_t cache_pairs = 0;  // subscriber entries in the row-set cache
  Py_ssize_t frag_pairs = 0;   // subscriber entries in the fragment cache
  Py_ssize_t icache_pairs = 0;  // entries in the intents cache
  // hits since the last clear, per result cache: a full cache that is
  // EARNING hits clears and rebuilds (hot set shifted); a full cache on
  // a unique-topic stream has nothing to rebuild FOR, so new entries
  // are simply not admitted — wholesale clear+refill churn was slower
  // than not caching at all (cold 1M stream measured 24K topics/s
  // thrashing vs 42K without the churn)
  Py_ssize_t cache_hits = 0;
  Py_ssize_t icache_hits = 0;
  Py_ssize_t cache_skips = 0;   // admissions refused since last clear
  Py_ssize_t icache_skips = 0;
  std::vector<PyObject *> key, cid, sub;  // borrowed from the lists
  // intents union scratch: per-action interned client index + an
  // epoch-stamped per-client slot map (no per-topic clearing). Epoch
  // and slot PACK into one uint64 per client — at 1M clients the
  // scratch lives in DRAM and the random per-action lookup is the
  // cold-union wall, so one cache miss per action beats two. The
  // scratch is SINGLE-BUILDER: merge_subscription callbacks (and any
  // allocation-triggered GC) can release the GIL mid-build, letting a
  // second executor thread enter cached_intents_result on the same
  // table — scratch_busy hands that builder a local-map fallback so
  // the stamps cannot be corrupted into duplicate deliveries.
  std::vector<int32_t> act_cidx;  // [A]; -1 for shared actions
  std::vector<uint64_t> mark;     // [n_clients] (epoch32 << 32) | slot
  int64_t epoch = 0;
  bool scratch_busy = false;
  // per-row prebuilt shared-group maps, built lazily ONCE per table:
  // a row's shared candidates are static table data, so the per-topic
  // shared assembly is a Py_NewRef (one shared row) or a bulk
  // PyDict_Copy + per-row inserts (several) instead of 2 dict ops per
  // (group, member) pair per topic — the measured wall of the cold
  // intents union on $share-heavy corpora (plain entries are pointer
  // writes; shared entries were ~300ns of hashing each). The maps are
  // immutable once published (the same aliased-inner-dict contract
  // to_set() already imposes on consumers).
  std::vector<PyObject *> rshared;  // [R]; nullptr until first touch
  std::vector<int32_t> shcount;     // [R] shared pairs in row's stream
  PyObject *empty_intents = nullptr;  // shared zero-entry result
  // chained-intents base support: per fat row, client index ->
  // (slot in the row's single-row intents, index of the row's action
  // for that client). The slot addresses iteration overrides; the
  // action index lets an override replay the base contribution through
  // merge_subscription exactly where the ascending-row-order union
  // would have applied it. Built lazily the first time a row anchors a
  // chain; rows that qualify are the few hundred-entry shallow-'#'
  // buckets, so the maps are small and live as long as the table (and
  // are dropped by table_release on rotation). slot_entries caps total
  // memory against pathological corpora (past it, new rows fall back
  // to the full union — correctness is unaffected).
  struct BaseSlot {
    int32_t slot;
    int64_t act;
  };
  std::unordered_map<int32_t, std::unordered_map<int32_t, BaseSlot>>
      row_slot;
  Py_ssize_t slot_entries = 0;
  // strong ref per fat row to its single-row intents: chains fetch the
  // base by one map probe instead of a key-bytes + icache round trip
  // per topic, and the base survives icache churn. Same
  // capsule<->cache cycle class as icache; table_release breaks it.
  std::unordered_map<int32_t, PyObject *> row_base;
  // multi-base composition: per-row purity flag (0 = none of the row's
  // plain clients appears in any other row), computed once at
  // table_new. Pure rows are pairwise disjoint with everything.
  std::vector<uint8_t> row_impure;
  Py_ssize_t R, W, A;
};

// A full cache whose entries earn no hits refuses new admissions (a
// unique-topic stream would otherwise clear+refill wholesale — measured
// SLOWER than not caching), but refusal is not forever: after
// kAdmissionRetry refused misses the cache clears and rebuilds anyway,
// so a hot set that shifted to uncached topics gets in within one
// bounded window instead of being locked out.
constexpr Py_ssize_t kAdmissionRetry = 65536;
// ... and a full cache clears ONLY when its entries were genuinely
// earning (a shifted hot set racks hits up fast). Requiring a single
// hit was enough for round-3's fat entries, but true-cost charging
// admits ~250K chains per budget — a mostly-cold stream with a few
// incidental repeats then cleared + rebuilt hundreds of thousands of
// GC-tracked objects at every fill (measured as a recurring ~40x
// whole-batch stall: the alloc/dealloc storm drives repeated full GC
// passes over a millions-of-objects heap).
constexpr Py_ssize_t kClearMinHits = 4096;

// Each cache (fragments, row-set unions) is bounded by the TOTAL
// subscriber entries it physically holds (hot corpora cache few, fat
// sets — a per-key cap would let 100K x 400-entry sets grow to GBs);
// past the cap that dict is dropped. The budgets are SEPARATE: a
// multi-row union is a real dict copy of its base fragment plus the
// delta (PyDict_Copy allocates fresh slots; only the Subscription
// values are shared), so it is charged its full pair count against the
// row-set budget — while fragment storage, charged once to its own
// budget, no longer halves the row-set cache's effective capacity
// (ADVICE r03 low). The table rotates on every subscription change.
constexpr Py_ssize_t kDecodeCachePairsCap = 4 << 20;

void table_destroy(PyObject *capsule) {
  auto *t = static_cast<DecodeTable *>(
      PyCapsule_GetPointer(capsule, "maxmq_decode.table"));
  if (!t) return;
  for (auto &kv : t->row_base) Py_XDECREF(kv.second);
  for (PyObject *d : t->rshared) Py_XDECREF(d);
  PyBuffer_Release(&t->tok);
  PyBuffer_Release(&t->min_depth);
  PyBuffer_Release(&t->flags);
  PyBuffer_Release(&t->offsets);
  PyBuffer_Release(&t->kinds);
  Py_XDECREF(t->keys);
  Py_XDECREF(t->cids);
  Py_XDECREF(t->subs);
  Py_XDECREF(t->cache);
  Py_XDECREF(t->frag);
  Py_XDECREF(t->icache);
  Py_XDECREF(t->empty_intents);
  delete t;
}

// table_new(tok, min_depth, flags, offsets, kinds, keys, cids, subs)
//   -> capsule
PyObject *table_new(PyObject *, PyObject *args) {
  PyObject *tok_o, *md_o, *fl_o, *off_o, *kind_o;
  PyObject *keys, *cids, *subs;
  if (!PyArg_ParseTuple(args, "OOOOOOOO", &tok_o, &md_o, &fl_o, &off_o,
                        &kind_o, &keys, &cids, &subs))
    return nullptr;
  if (!g_merge_fn) {
    PyErr_SetString(PyExc_RuntimeError, "configure() not called");
    return nullptr;
  }
  auto t = new DecodeTable();
  t->tok.obj = t->min_depth.obj = t->flags.obj = nullptr;
  t->offsets.obj = t->kinds.obj = nullptr;
  t->keys = t->cids = t->subs = t->cache = t->frag = nullptr;
  PyObject *capsule = PyCapsule_New(t, "maxmq_decode.table",
                                    table_destroy);
  if (!capsule) {
    delete t;
    return nullptr;
  }
  auto fail = [&](const char *msg) -> PyObject * {
    if (msg) PyErr_SetString(PyExc_ValueError, msg);
    Py_DECREF(capsule);  // destructor releases whatever was acquired
    return nullptr;
  };
  if (PyObject_GetBuffer(tok_o, &t->tok, PyBUF_SIMPLE) < 0 ||
      PyObject_GetBuffer(md_o, &t->min_depth, PyBUF_SIMPLE) < 0 ||
      PyObject_GetBuffer(fl_o, &t->flags, PyBUF_SIMPLE) < 0 ||
      PyObject_GetBuffer(off_o, &t->offsets, PyBUF_SIMPLE) < 0 ||
      PyObject_GetBuffer(kind_o, &t->kinds, PyBUF_SIMPLE) < 0)
    return fail(nullptr);
  if (!PyList_Check(keys) || !PyList_Check(cids) || !PyList_Check(subs))
    return fail("keys/cids/subs must be lists");
  t->R = (Py_ssize_t)t->flags.len;
  t->A = PyList_GET_SIZE(keys);
  if ((Py_ssize_t)t->min_depth.len != t->R * 4 ||
      (Py_ssize_t)t->offsets.len != (t->R + 1) * 8 ||
      (Py_ssize_t)t->kinds.len != t->A ||
      PyList_GET_SIZE(cids) != t->A || PyList_GET_SIZE(subs) != t->A ||
      (t->R && t->tok.len % (t->R * 4) != 0))
    return fail("table array lengths disagree");
  const auto *off = static_cast<const int64_t *>(t->offsets.buf);
  if (off[0] != 0 || off[t->R] != t->A)
    return fail("offsets do not span the action stream");
  for (Py_ssize_t r = 0; r < t->R; r++)
    if (off[r] > off[r + 1]) return fail("offsets not monotonic");
  t->W = t->R ? t->tok.len / (t->R * 4) : 0;
  t->keys = Py_NewRef(keys);
  t->cids = Py_NewRef(cids);
  t->subs = Py_NewRef(subs);
  t->cache = PyDict_New();
  t->frag = PyDict_New();
  t->icache = PyDict_New();
  if (!t->cache || !t->frag || !t->icache) return fail(nullptr);
  t->key.resize(t->A);
  t->cid.resize(t->A);
  t->sub.resize(t->A);
  for (Py_ssize_t a = 0; a < t->A; a++) {
    t->key[a] = PyList_GET_ITEM(keys, a);  // borrowed; lists hold refs
    t->cid[a] = PyList_GET_ITEM(cids, a);
    t->sub[a] = PyList_GET_ITEM(subs, a);
  }
  // intern client ids to dense indices for the intents union scratch
  {
    const auto *kind = static_cast<const uint8_t *>(t->kinds.buf);
    t->act_cidx.resize(t->A);
    PyObject *interned = PyDict_New();
    if (!interned) return fail(nullptr);
    Py_ssize_t C = 0;
    for (Py_ssize_t a = 0; a < t->A; a++) {
      if (kind[a] == ACT_SHARED) {
        t->act_cidx[a] = -1;
        continue;
      }
      PyObject *idx = PyDict_GetItemWithError(interned, t->cid[a]);
      if (idx) {
        t->act_cidx[a] = static_cast<int32_t>(PyLong_AsSsize_t(idx));
      } else {
        if (PyErr_Occurred()) {
          Py_DECREF(interned);
          return fail(nullptr);
        }
        PyObject *nv = PyLong_FromSsize_t(C);
        if (!nv || PyDict_SetItem(interned, t->cid[a], nv) < 0) {
          Py_XDECREF(nv);
          Py_DECREF(interned);
          return fail(nullptr);
        }
        Py_DECREF(nv);
        t->act_cidx[a] = static_cast<int32_t>(C++);
      }
    }
    Py_DECREF(interned);
    t->mark.assign(C, 0);
  }
  {
    const auto *kind = static_cast<const uint8_t *>(t->kinds.buf);
    const auto *offs = static_cast<const int64_t *>(t->offsets.buf);
    t->rshared.assign(t->R, nullptr);
    t->shcount.assign(t->R, 0);
    for (Py_ssize_t r = 0; r < t->R; r++) {
      int32_t c = 0;
      for (int64_t a = offs[r]; a < offs[r + 1]; a++)
        c += kind[a] == ACT_SHARED;
      t->shcount[r] = c;
    }
    // row purity for multi-base chaining: a client delivering plainly
    // from >= 2 rows makes every such row IMPURE. Pure rows share no
    // client with any other row, so any set of pure rows (plus at most
    // one impure row) is pairwise disjoint by construction — an O(1)
    // verdict at chain time instead of per-pair stream probes (pairs,
    // like subsets, almost never repeat on cold streams).
    {
      std::vector<uint8_t> cnt(t->mark.size(), 0);
      for (Py_ssize_t a = 0; a < t->A; a++)
        if (kind[a] != ACT_SHARED && t->act_cidx[a] >= 0) {
          uint8_t &x = cnt[t->act_cidx[a]];
          if (x < 2) x++;
        }
      t->row_impure.assign(t->R, 0);
      for (Py_ssize_t r = 0; r < t->R; r++)
        for (int64_t a = offs[r]; a < offs[r + 1]; a++)
          if (kind[a] != ACT_SHARED && t->act_cidx[a] >= 0 &&
              cnt[t->act_cidx[a]] >= 2) {
            t->row_impure[r] = 1;
            break;
          }
    }
  }
  return capsule;
}

// table_release(capsule) — break the table->caches->intents->capsule
// reference cycle when the python side drops a compiled snapshot.
// Capsules are not GC-tracked, so without this the whole table (token
// arrays, entry lists, every cached result) would leak on rotation.
// Outstanding handed-out results still hold the capsule and stay valid;
// only the table-held caches are dropped.
PyObject *table_release(PyObject *, PyObject *cap) {
  auto *t = static_cast<DecodeTable *>(
      PyCapsule_GetPointer(cap, "maxmq_decode.table"));
  if (!t) return nullptr;
  if (t->cache) PyDict_Clear(t->cache);
  if (t->frag) PyDict_Clear(t->frag);
  if (t->icache) PyDict_Clear(t->icache);
  Py_CLEAR(t->empty_intents);
  for (PyObject *&d : t->rshared) Py_CLEAR(d);
  t->cache_pairs = t->frag_pairs = t->icache_pairs = 0;
  t->cache_hits = t->icache_hits = 0;
  t->cache_skips = t->icache_skips = 0;
  t->row_slot.clear();
  t->slot_entries = 0;
  for (auto &kv : t->row_base) Py_DECREF(kv.second);
  t->row_base.clear();
  Py_RETURN_NONE;
}

inline int32_t topic_tok(const void *base, int mode, int32_t pad,
                         Py_ssize_t t, Py_ssize_t W, Py_ssize_t i) {
  int32_t v;
  switch (mode) {
    case 1: v = static_cast<const uint8_t *>(base)[t * W + i]; break;
    case 2: v = static_cast<const uint16_t *>(base)[t * W + i]; break;
    default: v = static_cast<const int32_t *>(base)[t * W + i]; break;
  }
  return v == pad ? -1 : v;
}

// result[t] as a SubscriberSet, materialized on first touch
inline SubSetObject *lazy_set(PyObject *list, Py_ssize_t t) {
  PyObject *s = PyList_GET_ITEM(list, t);
  if (s != Py_None) return reinterpret_cast<SubSetObject *>(s);
  auto *n = subset_new_fast(nullptr, nullptr);
  if (!n) return nullptr;
  PyList_SetItem(list, t, reinterpret_cast<PyObject *>(n));  // steals
  return n;
}

// replay row r's action stream into res; -1 on python error
int apply_row_actions(DecodeTable *t, SubSetObject *res, int64_t r) {
  const auto *off = static_cast<const int64_t *>(t->offsets.buf);
  const auto *kind = static_cast<const uint8_t *>(t->kinds.buf);
  for (int64_t a = off[r]; a < off[r + 1]; a++) {
    switch (kind[a]) {
      case ACT_PLAIN: {
        PyObject *cur =
            PyDict_GetItemWithError(res->subscriptions, t->cid[a]);
        if (!cur) {
          if (PyErr_Occurred() ||
              PyDict_SetItem(res->subscriptions, t->cid[a],
                             t->sub[a]) < 0)
            return -1;
        } else if (cur != t->sub[a]) {  // same-client collision
          PyObject *mg = PyObject_CallFunctionObjArgs(
              g_merge_fn, cur, t->sub[a], t->key[a], nullptr);
          if (!mg ||
              PyDict_SetItem(res->subscriptions, t->cid[a], mg) < 0) {
            Py_XDECREF(mg);
            return -1;
          }
          Py_DECREF(mg);
        }
        break;
      }
      case ACT_MERGE: {  // v5 identifiers: copy semantics via python
        PyObject *cur =
            PyDict_GetItemWithError(res->subscriptions, t->cid[a]);
        if (!cur && PyErr_Occurred()) return -1;
        PyObject *mg = PyObject_CallFunctionObjArgs(
            g_merge_fn, cur ? cur : Py_None, t->sub[a], t->key[a],
            nullptr);
        if (!mg ||
            PyDict_SetItem(res->subscriptions, t->cid[a], mg) < 0) {
          Py_XDECREF(mg);
          return -1;
        }
        Py_DECREF(mg);
        break;
      }
      default: {  // ACT_SHARED
        PyObject *g = PyDict_GetItemWithError(res->shared, t->key[a]);
        if (!g) {
          if (PyErr_Occurred()) return -1;
          g = PyDict_New();
          if (!g || PyDict_SetItem(res->shared, t->key[a], g) < 0) {
            Py_XDECREF(g);
            return -1;
          }
          Py_DECREF(g);  // res->shared holds the ref now
        }
        if (PyDict_SetItem(g, t->cid[a], t->sub[a]) < 0) return -1;
        break;
      }
    }
  }
  return 0;
}

// pairs held by one set (for the cache budget)
Py_ssize_t subset_pairs(SubSetObject *res) {
  Py_ssize_t pairs = PyDict_GET_SIZE(res->subscriptions);
  PyObject *gk, *gv;
  for (Py_ssize_t pos = 0; PyDict_Next(res->shared, &pos, &gk, &gv);)
    pairs += PyDict_GET_SIZE(gv);
  return pairs;
}

// build-or-fetch the single-row fragment for row r; BORROWED reference
// (owned by t->frag). Fragments are reused across topics even when
// their row-set combinations differ, so a multi-row cache miss costs a
// dict copy + the smaller rows' inserts instead of a full rebuild.
SubSetObject *fragment_for_row(DecodeTable *t, int32_t r) {
  PyObject *rk = PyLong_FromLong(r);
  if (!rk) return nullptr;
  PyObject *hit = PyDict_GetItemWithError(t->frag, rk);
  if (hit) {
    Py_DECREF(rk);
    return reinterpret_cast<SubSetObject *>(hit);
  }
  if (PyErr_Occurred()) {
    Py_DECREF(rk);
    return nullptr;
  }
  auto *res = subset_new_fast(nullptr, nullptr);
  if (!res || apply_row_actions(t, res, r) < 0) {
    Py_DECREF(rk);
    Py_XDECREF(res);
    return nullptr;
  }
  const Py_ssize_t pairs = subset_pairs(res);
  if (t->frag_pairs + pairs > kDecodeCachePairsCap) {
    // clear BOTH dicts: single-row entries in t->cache alias fragment
    // objects with pairs=0 charged, so dropping only t->frag would
    // leave up to a full cap of fragment storage alive-but-uncounted
    // through those aliases (resident could reach 3x cap); clearing
    // both restores the documented 2x-cap bound
    PyDict_Clear(t->frag);
    PyDict_Clear(t->cache);
    t->frag_pairs = 0;
    t->cache_pairs = 0;
    t->cache_hits = 0;
    t->cache_skips = 0;
  }
  const int rc = PyDict_SetItem(t->frag, rk,
                                reinterpret_cast<PyObject *>(res));
  Py_DECREF(rk);
  Py_DECREF(res);  // t->frag holds the ref; borrowed below
  if (rc < 0) return nullptr;
  t->frag_pairs += pairs;
  return res;
}

// build-or-fetch the merged SubscriberSet for one verified, sorted,
// deduped row set; returns a NEW reference (cached object shared across
// topics — callers treat results as immutable, deep_copy before
// mutating, the same discipline the broker's match cache imposes)
PyObject *cached_rowset_result(DecodeTable *t, const int32_t *rows,
                               Py_ssize_t n_rows) {
  PyObject *key = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(rows),
      n_rows * (Py_ssize_t)sizeof(int32_t));
  if (!key) return nullptr;
  PyObject *hit = PyDict_GetItemWithError(t->cache, key);
  if (hit) {
    t->cache_hits++;
    Py_DECREF(key);
    return Py_NewRef(hit);
  }
  if (PyErr_Occurred()) {
    Py_DECREF(key);
    return nullptr;
  }
  // base the union on the FATTEST row: its fragment is bulk-copied
  // (PyDict_Copy clones the hash table without re-hashing) while the
  // other rows replay per-entry. On fan-out-heavy corpora one shallow
  // '#'-bucket row carries hundreds of entries and the rest a handful,
  // so base choice is the difference between a memcpy-ish copy and
  // hundreds of dict inserts per topic. Merge-order effects are
  // confined to which overlapping filter donates the RAP/RH flags —
  // arbitrary in the reference too (its trie iteration order); qos is
  // max and identifier union is commutative.
  const auto *off_b = static_cast<const int64_t *>(t->offsets.buf);
  Py_ssize_t bi = 0;
  for (Py_ssize_t i = 1; i < n_rows; i++)
    if (off_b[rows[i] + 1] - off_b[rows[i]] >
        off_b[rows[bi] + 1] - off_b[rows[bi]])
      bi = i;
  SubSetObject *res;
  SubSetObject *base = fragment_for_row(t, rows[bi]);
  if (!base) {
    Py_DECREF(key);
    return nullptr;
  }
  if (n_rows == 1) {
    res = reinterpret_cast<SubSetObject *>(
        Py_NewRef(reinterpret_cast<PyObject *>(base)));
  } else {
    // union = copy of the base fragment + the remaining rows' action
    // streams. Inner shared-group dicts must be copied too —
    // apply_row_actions mutates them on group collisions and
    // fragments are shared.
    PyObject *subs = PyDict_Copy(base->subscriptions);
    PyObject *shared =
        PyDict_GET_SIZE(base->shared) ? PyDict_Copy(base->shared)
                                      : nullptr;
    if (!subs || (PyDict_GET_SIZE(base->shared) && !shared)) {
      Py_XDECREF(subs);
      Py_XDECREF(shared);
      Py_DECREF(key);
      return nullptr;
    }
    if (shared) {
      PyObject *gk, *gv;
      for (Py_ssize_t pos = 0; PyDict_Next(shared, &pos, &gk, &gv);) {
        PyObject *cp = PyDict_Copy(gv);
        if (!cp || PyDict_SetItem(shared, gk, cp) < 0) {
          Py_XDECREF(cp);
          Py_DECREF(subs);
          Py_DECREF(shared);
          Py_DECREF(key);
          return nullptr;
        }
        Py_DECREF(cp);
      }
    }
    res = subset_new_fast(subs, shared);
    Py_DECREF(subs);
    Py_XDECREF(shared);
    if (!res) {
      Py_DECREF(key);
      return nullptr;
    }
    for (Py_ssize_t i = 0; i < n_rows; i++) {
      if (i == bi) continue;  // the base fragment already carries it
      if (apply_row_actions(t, res, rows[i]) < 0) {
        Py_DECREF(key);
        Py_DECREF(res);
        return nullptr;
      }
    }
  }
  // a single-row result ALIASES its fragment (no new dict storage —
  // its pairs live in the fragment budget); a multi-row union owns a
  // real copied dict and is charged in full against the row-set budget
  const Py_ssize_t pairs = n_rows == 1 ? 0 : subset_pairs(res);
  if (t->cache_pairs + pairs > kDecodeCachePairsCap) {
    if (t->cache_hits < kClearMinHits &&
        ++t->cache_skips < kAdmissionRetry) {
      Py_DECREF(key);              // cold stream: stop churning
      return reinterpret_cast<PyObject *>(res);
    }
    PyDict_Clear(t->cache);
    t->cache_pairs = 0;
    t->cache_hits = 0;
    t->cache_skips = 0;
  }
  int rc = PyDict_SetItem(t->cache, key, reinterpret_cast<PyObject *>(res));
  Py_DECREF(key);
  if (rc < 0) {
    Py_DECREF(res);
    return nullptr;
  }
  t->cache_pairs += pairs;
  return reinterpret_cast<PyObject *>(res);
}

// build-or-fetch row r's prebuilt shared-group map {(group, filter) ->
// {cid: sub}}; BORROWED reference (the table owns it). Built fully
// into a local dict and only then published: dict allocation can
// trigger GC, GC can run arbitrary finalizers, and a finalizer can
// re-enter this builder on another thread's behalf — publish-once
// keeps the cached map single and complete.
PyObject *row_shared(DecodeTable *t, Py_ssize_t r) {
  TimeAcc time_shared(&g_timing.shared_ns);
  if (t->rshared[r]) return t->rshared[r];
  const auto *off = static_cast<const int64_t *>(t->offsets.buf);
  const auto *kind = static_cast<const uint8_t *>(t->kinds.buf);
  PyObject *d = PyDict_New();
  if (!d) return nullptr;
  for (int64_t a = off[r]; a < off[r + 1]; a++) {
    if (kind[a] != ACT_SHARED) continue;
    PyObject *g = PyDict_GetItemWithError(d, t->key[a]);
    if (!g) {
      if (PyErr_Occurred()) {
        Py_DECREF(d);
        return nullptr;
      }
      g = PyDict_New();
      if (!g || PyDict_SetItem(d, t->key[a], g) < 0) {
        Py_XDECREF(g);
        Py_DECREF(d);
        return nullptr;
      }
      Py_DECREF(g);
    }
    if (PyDict_SetItem(g, t->cid[a], t->sub[a]) < 0) {
      Py_DECREF(d);
      return nullptr;
    }
  }
  if (!t->rshared[r]) {
    t->rshared[r] = d;          // publish; table owns the ref
  } else {
    Py_DECREF(d);               // lost a re-entrant race: use the winner
  }
  return t->rshared[r];
}

// total per-table slot-map entry budget; a mutable global so the test
// suite can shrink it to exercise the prewarm budget paths without
// building hundred-thousand-entry corpora
Py_ssize_t g_slot_map_cap = 512 * 1024;

PyObject *cached_intents_result(DecodeTable *t, PyObject *cap,
                                const int32_t *rows, Py_ssize_t n_rows,
                                bool allow_chain = true);

// build-or-fetch row r's slot map and pinned single-row base intents
// (shared by the chain resolution loop and prewarm_bases). Returns the
// slot map, or nullptr when the map budget declines the row; *base_out
// gets a NEW reference to the base intents, or nullptr on a python
// error (PyErr set).
std::unordered_map<int32_t, DecodeTable::BaseSlot> *
ensure_row_base(DecodeTable *t, PyObject *cap, int32_t r, Py_ssize_t p,
                PyObject **base_out) {
  const auto *off = static_cast<const int64_t *>(t->offsets.buf);
  const auto *kind = static_cast<const uint8_t *>(t->kinds.buf);
  *base_out = nullptr;
  std::unordered_map<int32_t, DecodeTable::BaseSlot> *m;
  auto found = t->row_slot.find(r);
  if (found != t->row_slot.end()) {
    m = &found->second;
  } else if (t->slot_entries + p <= g_slot_map_cap) {
    m = &t->row_slot[r];
    m->reserve(static_cast<size_t>(p) * 2);
    int32_t slot = 0;
    for (int64_t a = off[r]; a < off[r + 1]; a++) {
      if (kind[a] == ACT_SHARED) continue;
      m->emplace(t->act_cidx[a], DecodeTable::BaseSlot{slot++, a});
    }
    t->slot_entries += p;
  } else {
    return nullptr;              // budget: row unions in the tail
  }
  PyObject *b;
  auto fb = t->row_base.find(r);
  if (fb != t->row_base.end()) {
    b = Py_NewRef(fb->second);
  } else {
    g_timing_depth++;            // nested build: outer TimeAcc owns it
    int32_t one = r;
    b = cached_intents_result(t, cap, &one, 1, true);
    g_timing_depth--;
    if (!b) return m;            // PyErr set; *base_out stays null
    // the recursive build can run Python (merge callbacks, GC
    // finalizers) and re-enter this builder; only the emplace WINNER
    // may deposit a reference, like row_shared's publish-once
    // discipline
    auto ins = t->row_base.emplace(r, nullptr);
    if (ins.second) ins.first->second = Py_NewRef(b);
  }
  *base_out = b;
  return m;
}

// build-or-fetch DeliveryIntents for one verified, sorted, deduped row
// set; NEW reference. The union is an epoch-stamped dedupe over the
// rows' action streams — int32/pointer writes only; merge_subscription
// runs solely on same-client collisions and v5-identifier entries.
PyObject *cached_intents_result(DecodeTable *t, PyObject *cap,
                                const int32_t *rows, Py_ssize_t n_rows,
                                bool allow_chain) {
  PyObject *key = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(rows),
      n_rows * (Py_ssize_t)sizeof(int32_t));
  if (!key) return nullptr;
  PyObject *hit = PyDict_GetItemWithError(t->icache, key);
  if (hit) {
    t->icache_hits++;
    Py_DECREF(key);
    return Py_NewRef(hit);
  }
  if (PyErr_Occurred()) {
    Py_DECREF(key);
    return nullptr;
  }
  TimeAcc time_construct(&g_timing.construct_ns);
  if (time_construct.armed) g_timing.constructs++;
  const auto *off = static_cast<const int64_t *>(t->offsets.buf);
  const auto *kind = static_cast<const uint8_t *>(t->kinds.buf);
  Py_ssize_t total = 0;
  Py_ssize_t sh_pairs = 0;
  for (Py_ssize_t i = 0; i < n_rows; i++) {
    total += off[rows[i] + 1] - off[rows[i]];
    sh_pairs += t->shcount[rows[i]];
  }
  // chain decision (round-5 multi-base form): the union anchors on a
  // LIST of cached per-row base intents and builds only the thin
  // remainder — O(tail) per topic instead of O(total), the whole
  // cold-stream game on shallow-'#' corpora. Heavy cold sets look like
  // [~280, 63, 61, 50, thin...]: their fat-row COMBINATIONS almost
  // never repeat (measured: 2,781 distinct subsets across 2,783
  // multi-fat topics at 1M subs — a flattened per-subset base can
  // never amortize and measured strictly slower), but each ROW repeats
  // across many topics, so every row at or above base_min_row becomes
  // its own base. Bases must be pairwise client-disjoint (exact
  // verdicts cached per row pair); an overlapping row drops to the
  // tail, which keeps the fold semantics single-act per client.
  constexpr int kMaxBases = 8;
  const Py_ssize_t base_min_row =
      g_multi_base ? std::max<Py_ssize_t>(16, g_chain_min_base / 4)
                   : g_chain_min_base;
  Py_ssize_t total_plain = 0, sum_base = 0;
  Py_ssize_t cand[kMaxBases], cand_p[kMaxBases];
  int n_cand = 0;
  if (n_rows > 1 && g_chain_enabled && allow_chain) {
    for (Py_ssize_t i = 0; i < n_rows; i++) {
      const Py_ssize_t p =
          (off[rows[i] + 1] - off[rows[i]]) - t->shcount[rows[i]];
      total_plain += p;
      if (p >= base_min_row) {
        if (n_cand < kMaxBases) {
          cand[n_cand] = i;
          cand_p[n_cand] = p;
          n_cand++;
          sum_base += p;
        } else {
          // keep the FATTEST kMaxBases candidates: replace the
          // smallest (the fat anchor must never fall to the tail)
          int sm = 0;
          for (int cj = 1; cj < kMaxBases; cj++)
            if (cand_p[cj] < cand_p[sm]) sm = cj;
          if (p > cand_p[sm]) {
            sum_base += p - cand_p[sm];
            cand[sm] = i;
            cand_p[sm] = p;
          }
        }
      }
    }
    if (!g_multi_base && n_cand > 1) {
      // legacy form: only the fattest candidate anchors
      int best = 0;
      for (int ci = 1; ci < n_cand; ci++)
        if (cand_p[ci] > cand_p[best]) best = ci;
      cand[0] = cand[best];
      cand_p[0] = cand_p[best];
      n_cand = 1;
      sum_base = cand_p[0];
    }
    if (sum_base < g_chain_min_base ||
        (total_plain - sum_base) * g_chain_tail_den >
            sum_base * g_chain_tail_num) {
      if (time_construct.armed) {
        if (sum_base < g_chain_min_base)
          g_timing.decl_minbase++;
        else
          g_timing.decl_ratio++;
      }
      n_cand = 0;
    }
  } else if (time_construct.armed && n_rows == 1) {
    g_timing.single_row++;
  }

  // resolve candidates (ascending row order) into accepted bases:
  // slot map + pairwise disjointness + pinned single-row intents
  IntentsObject *bases_acc[kMaxBases];
  std::unordered_map<int32_t, DecodeTable::BaseSlot> *maps_acc[kMaxBases];
  int32_t base_rows[kMaxBases];
  Py_ssize_t base_ci[kMaxBases];  // candidate's index into rows[]
  int k = 0;
  Py_ssize_t kept_mass = 0;
  bool have_impure = false;
  auto drop_bases = [&]() {
    for (int j = 0; j < k; j++)
      Py_DECREF(reinterpret_cast<PyObject *>(bases_acc[j]));
    k = 0;
    kept_mass = 0;
  };
  // ascending row order (slot/base/fold invariants); the fattest-8
  // replacement above can leave cand[] unordered
  for (int a2 = 1; a2 < n_cand; a2++)
    for (int b2 = a2; b2 > 0 && cand[b2] < cand[b2 - 1]; b2--) {
      std::swap(cand[b2], cand[b2 - 1]);
      std::swap(cand_p[b2], cand_p[b2 - 1]);
    }
  TimeAcc time_resolve(&g_timing.resolve_ns);
  for (int ci = 0; ci < n_cand; ci++) {
    const int32_t r = rows[cand[ci]];
    const Py_ssize_t p = cand_p[ci];
    // purity rule (O(1)): pure rows share no client with ANY other
    // row; an impure row may only be the single impure base
    if (t->row_impure[r] && have_impure)
      continue;                 // could overlap a kept base: tail it
    PyObject *b = nullptr;
    auto *m = ensure_row_base(t, cap, r, p, &b);
    if (!m) {
      if (time_construct.armed) g_timing.decl_budget++;
      continue;                 // budget: this row unions in the tail
    }
    if (!b) {
      drop_bases();
      Py_DECREF(key);
      return nullptr;
    }
    if (t->row_impure[r]) have_impure = true;
    bases_acc[k] = reinterpret_cast<IntentsObject *>(b);
    maps_acc[k] = m;
    base_rows[k] = r;
    base_ci[k] = cand[ci];
    kept_mass += p;
    k++;
  }
  if (time_resolve.armed) {
    g_timing.resolve_ns += now_ns() - time_resolve.t0;
    time_resolve.armed = false;
  }
  // dropped candidates grew the tail: the chain must still win
  if (k && (kept_mass < g_chain_min_base ||
            (total_plain - kept_mass) * g_chain_tail_den >
                kept_mass * g_chain_tail_num)) {
    if (time_construct.armed) {
      if (kept_mass < g_chain_min_base)
        g_timing.decl_minbase++;
      else
        g_timing.decl_ratio++;
    }
    drop_bases();
  }

  const bool chained = k > 0;
  const Py_ssize_t tail_n = chained ? total_plain - kept_mass : 0;
  IntentsObject *it =
      intents_alloc(cap, chained ? tail_n : total - sh_pairs);
  if (!it) {
    drop_bases();
    Py_DECREF(key);
    return nullptr;
  }
  if (time_construct.armed) {
    if (chained) g_timing.chained++;
    if (k > 1) g_timing.multi_base++;
    g_timing.entries_built += chained ? tail_n : total - sh_pairs;
  }
  std::vector<char> is_base_i;
  if (chained) {
    char *blk = static_cast<char *>(PyMem_Malloc(
        k * sizeof(IntentsObject *) + (k + 1) * sizeof(int32_t)));
    if (!blk) {
      drop_bases();
      Py_DECREF(key);
      Py_DECREF(it);
      PyErr_NoMemory();
      return nullptr;
    }
    it->bases = reinterpret_cast<IntentsObject **>(blk);
    it->base_off = reinterpret_cast<int32_t *>(
        blk + k * sizeof(IntentsObject *));
    it->base_off[0] = 0;
    for (int j = 0; j < k; j++) {
      it->bases[j] = bases_acc[j];  // ref transferred
      it->base_off[j + 1] =
          it->base_off[j] + static_cast<int32_t>(bases_acc[j]->n);
    }
    it->n_bases = k;
    is_base_i.assign(n_rows, 0);
    for (int j = 0; j < k; j++) is_base_i[base_ci[j]] = 1;
    if (tail_n) {
      // one block: PyObject* array first (alignment), slots after
      char *ob = static_cast<char *>(PyMem_Malloc(
          tail_n * (sizeof(PyObject *) + sizeof(int32_t))));
      if (!ob) {
        Py_DECREF(key);
        Py_DECREF(it);
        PyErr_NoMemory();
        return nullptr;
      }
      it->ovr_subs = reinterpret_cast<PyObject **>(ob);
      it->ovr_slots = reinterpret_cast<int32_t *>(
          ob + tail_n * sizeof(PyObject *));
    }
  }
  // override build state: a chained union must produce EXACTLY what
  // the ascending-row-order union produces for a client present in
  // both a base row and tail rows — qos max and identifier union
  // are order-free, but merge_subscription takes flags from the NEWER
  // (= higher row id) filter, so the base contribution is folded in
  // at its ordered position via its raw action, not merged
  // first-come. Bases are pairwise disjoint, so each client has at
  // most ONE base act.
  struct OvrBuild {
    int32_t slot;      // GLOBAL base slot shadowed
    int64_t base_act;  // the base row's action for this client
    int32_t base_row;  // its row (fold ordering)
    PyObject *cur;     // accumulated entry; owned iff owned
    bool owned;
    bool folded;       // base contribution already applied
  };
  std::vector<OvrBuild> ovr_build;
  std::unordered_map<int32_t, size_t> ovr_index;  // slot -> build idx
  auto bail = [&]() -> PyObject * {
    for (auto &ob : ovr_build)
      if (ob.owned) Py_XDECREF(ob.cur);
    Py_DECREF(key);
    Py_DECREF(it);
    return nullptr;
  };
  // shared-group map: assembled from the prebuilt per-row maps — one
  // Py_NewRef when a single row carries shared members, else a bulk
  // copy of the fattest row's map + per-group inserts (inner maps
  // merged copy-on-write on the rare duplicate-filter-row collision)
  Py_ssize_t sh_owned_pairs = 0;  // shared pairs this result STORES
                                  // (an aliased per-row map costs 0)
  if (sh_pairs) {
    Py_ssize_t sh_n = 0, base_i = -1;
    for (Py_ssize_t i = 0; i < n_rows; i++)
      if (t->shcount[rows[i]]) {
        sh_n++;
        if (base_i < 0 ||
            t->shcount[rows[i]] > t->shcount[rows[base_i]])
          base_i = i;
      }
    PyObject *b = row_shared(t, rows[base_i]);
    if (!b) return bail();
    if (sh_n == 1) {
      it->shared = Py_NewRef(b);  // aliased: no storage of its own
    } else {
      sh_owned_pairs = sh_pairs;
      PyObject *d = PyDict_Copy(b);
      if (!d) return bail();
      it->shared = d;            // owned; set before merging so a
                                 // failed merge frees it via bail
      for (Py_ssize_t i = 0; i < n_rows; i++) {
        if (i == base_i || !t->shcount[rows[i]]) continue;
        PyObject *rs = row_shared(t, rows[i]);
        if (!rs) return bail();
        PyObject *gk, *gv;
        for (Py_ssize_t pos = 0; PyDict_Next(rs, &pos, &gk, &gv);) {
          PyObject *cur = PyDict_GetItemWithError(d, gk);
          if (cur) {
            PyObject *cp = PyDict_Copy(cur);
            if (!cp || PyDict_Update(cp, gv) < 0 ||
                PyDict_SetItem(d, gk, cp) < 0) {
              Py_XDECREF(cp);
              return bail();
            }
            Py_DECREF(cp);
          } else {
            if (PyErr_Occurred()) return bail();
            if (PyDict_SetItem(d, gk, gv) < 0) return bail();
          }
        }
      }
    }
  }
  // single-builder fast scratch, local-map fallback for a concurrent
  // builder that entered while a Python callback had the GIL released
  struct ScratchGuard {
    DecodeTable *t;
    bool owned;
    explicit ScratchGuard(DecodeTable *tt)
        : t(tt), owned(!tt->scratch_busy) {
      if (owned) t->scratch_busy = true;
    }
    ~ScratchGuard() {
      if (owned) t->scratch_busy = false;
    }
  } guard(t);
  std::unordered_map<int32_t, Py_ssize_t> local_slot;
  // a SINGLE row's non-shared actions are distinct clients by
  // construction (one entry per (client, filter)), so the whole
  // dedupe apparatus — marks, epochs, prefetch — is skipped and the
  // union degenerates to a straight sequential copy of the stream.
  // A chained build unions only the tail rows, so the same shortcut
  // applies when the tail is a single row.
  const Py_ssize_t n_union_rows = n_rows - k;
  const bool dedupe = n_union_rows > 1;
  const bool fast = dedupe && guard.owned;
  uint32_t e32 = 0;
  if (fast) {
    ++t->epoch;
    if ((t->epoch & 0xFFFFFFFFll) == 0) {
      // epoch32 wrapped: a mark stamped exactly 2^32 unions ago would
      // falsely read as current — clear and skip the zero epoch
      std::fill(t->mark.begin(), t->mark.end(), 0);
      ++t->epoch;
    }
    e32 = static_cast<uint32_t>(t->epoch & 0xFFFFFFFFll);
  }
  auto slot_of = [&](int32_t c) -> Py_ssize_t {
    if (!dedupe) return -1;
    if (fast) {
      const uint64_t m = t->mark[c];
      return static_cast<uint32_t>(m >> 32) == e32
                 ? (Py_ssize_t)(uint32_t)m
                 : -1;
    }
    auto f = local_slot.find(c);
    return f == local_slot.end() ? -1 : f->second;
  };
  auto record_slot = [&](int32_t c, Py_ssize_t j) {
    if (!dedupe) return;
    if (fast) {
      t->mark[c] = (static_cast<uint64_t>(e32) << 32) |
                   static_cast<uint32_t>(j);
    } else {
      local_slot[c] = j;
    }
  };
  // fold the base row's contribution into an override at its ordered
  // position (no-op pointer-equality skip mirrors the union's
  // duplicate-filter-row shortcut)
  auto fold_base = [&](OvrBuild &ob) -> bool {
    if (ob.folded) return true;
    ob.folded = true;
    if (!ob.cur) {
      // base is this client's first contribution: the entry form the
      // union would hold after the base row (ACT_MERGE base actions
      // are already pre-merged inside the base intents)
      ob.cur = base_sub_at(it, ob.slot);
      ob.owned = false;
      return true;
    }
    if (kind[ob.base_act] == ACT_PLAIN && ob.cur == t->sub[ob.base_act])
      return true;  // same record twice (duplicate filter rows)
    PyObject *mg = PyObject_CallFunctionObjArgs(
        g_merge_fn, ob.cur, t->sub[ob.base_act], t->key[ob.base_act],
        nullptr);
    if (!mg) return false;
    if (ob.owned) Py_DECREF(ob.cur);
    ob.cur = mg;
    ob.owned = true;
    return true;
  };
  // Tail-collision probe gating: a client can sit in both a tail row
  // and a base row only if BOTH rows are impure (that is the purity
  // definition), and at most one kept base is impure — so pure tail
  // rows probe nothing, and impure ones probe exactly one map.
  int impure_j = -1;
  for (int j = 0; j < k; j++)
    if (t->row_impure[base_rows[j]]) impure_j = j;
  Py_ssize_t n = 0;
  // The union is DRAM-latency-bound: every action's mark[] slot is a
  // random 8-byte access into a table that is tens of MB at 1M clients
  // (measured 128ns/pair cold = one full miss each). Prefetching the
  // slot kPrefetch actions ahead (spilling into the next row's stream
  // at a segment boundary) overlaps the misses; the hardware sustains
  // ~10 in flight, turning the wall from latency- to bandwidth-bound.
  constexpr int64_t kPrefetch = 24;
  auto prefetch_at = [&](Py_ssize_t i, int64_t a) {
    int64_t pa = a + kPrefetch;
    int64_t pe = off[rows[i] + 1];
    if (pa >= pe) {
      if (i + 1 >= n_rows) return;
      const int64_t r2 = rows[i + 1];
      pa = off[r2] + (pa - pe);
      pe = off[r2 + 1];
      if (pa >= pe) return;
    }
    const int32_t pc = t->act_cidx[pa];
    if (pc >= 0) PREFETCH_W(&t->mark[pc]);
  };
  for (Py_ssize_t i = 0; i < n_rows; i++) {
    if (chained && is_base_i[i]) continue;  // bases carry these rows
    const int64_t r = rows[i];
    for (int64_t a = off[r]; a < off[r + 1]; a++) {
      if (fast) prefetch_at(i, a);
      const uint8_t kk = kind[a];
      if (kk == ACT_SHARED) continue;  // prebuilt per-row maps above
      const int32_t c = t->act_cidx[a];
      if (chained && impure_j >= 0 && t->row_impure[r]) {
        // same client also in a base row: only possible when both the
        // tail row and a base row are impure, and at most one kept
        // base is — probe exactly that one map
        const DecodeTable::BaseSlot *hit = nullptr;
        const int hit_j = impure_j;
        {
          auto f = maps_acc[hit_j]->find(c);
          if (f != maps_acc[hit_j]->end()) hit = &f->second;
        }
        if (hit) {
          const int32_t gslot = it->base_off[hit_j] + hit->slot;
          size_t oi;
          auto fi = ovr_index.find(gslot);
          if (fi != ovr_index.end()) {
            oi = fi->second;
          } else {
            oi = ovr_build.size();
            ovr_index.emplace(gslot, oi);
            ovr_build.push_back({gslot, hit->act, base_rows[hit_j],
                                 nullptr, false, false});
          }
          OvrBuild &ob = ovr_build[oi];
          if (ob.base_row < r && !fold_base(ob)) return bail();
          if (!ob.cur) {
            // first contribution, base row not yet due (r < base row)
            if (kk == ACT_MERGE) {
              PyObject *mg = PyObject_CallFunctionObjArgs(
                  g_merge_fn, Py_None, t->sub[a], t->key[a], nullptr);
              if (!mg) return bail();
              ob.cur = mg;
              ob.owned = true;
            } else {
              ob.cur = t->sub[a];
              ob.owned = false;
            }
          } else if (kk == ACT_PLAIN && ob.cur == t->sub[a]) {
            // same record twice (duplicate filter rows)
          } else {
            PyObject *mg = PyObject_CallFunctionObjArgs(
                g_merge_fn, ob.cur, t->sub[a], t->key[a], nullptr);
            if (!mg) return bail();
            if (ob.owned) Py_DECREF(ob.cur);
            ob.cur = mg;
            ob.owned = true;
          }
          continue;
        }
      }
      const Py_ssize_t j = slot_of(c);
      if (j < 0) {
        record_slot(c, n);
        it->cids[n] = t->cid[a];
        if (kk == ACT_MERGE) {
          // v5 identifiers: ALWAYS through merge_subscription so the
          // identifier-union copy semantics hold from the first insert
          PyObject *mg = PyObject_CallFunctionObjArgs(
              g_merge_fn, Py_None, t->sub[a], t->key[a], nullptr);
          if (!mg) return bail();
          it->subs[n] = mg;
          it->owned[n] = 1;
        } else {
          it->subs[n] = t->sub[a];  // borrowed; table keeps it alive
          it->owned[n] = 0;
        }
        it->n = ++n;  // keep n consistent for dealloc on error
      } else {
        if (kk == ACT_PLAIN && it->subs[j] == t->sub[a])
          continue;  // same record twice (duplicate filter rows)
        PyObject *mg = PyObject_CallFunctionObjArgs(
            g_merge_fn, it->subs[j], t->sub[a], t->key[a], nullptr);
        if (!mg) return bail();
        if (it->owned[j]) Py_DECREF(it->subs[j]);
        it->subs[j] = mg;
        it->owned[j] = 1;
      }
    }
  }
  // finalize overrides: fold any still-pending base contribution (all
  // of that client's tail rows preceded the base row), drop no-op
  // overrides that resolved back to the base entry, and emit the
  // arrays ascending by slot for the iterator's single-cursor pass
  if (!ovr_build.empty()) {
    for (auto &ob : ovr_build)
      if (!fold_base(ob)) return bail();
    std::sort(ovr_build.begin(), ovr_build.end(),
              [](const OvrBuild &x, const OvrBuild &y) {
                return x.slot < y.slot;
              });
    for (auto &ob : ovr_build) {
      if (ob.cur == base_sub_at(it, ob.slot)) {
        if (ob.owned) Py_DECREF(ob.cur);
        ob.cur = nullptr;
        ob.owned = false;
        continue;  // identical to the base entry: not an override
      }
      if (!ob.owned) Py_INCREF(ob.cur);
      it->ovr_slots[it->n_ovr] = ob.slot;
      it->ovr_subs[it->n_ovr] = ob.cur;
      it->n_ovr++;
      ob.cur = nullptr;  // ref transferred to the intents object
      ob.owned = false;
    }
  }
  // charge the icache at TRUE storage cost (ADVICE r03 discipline):
  // own entries + overrides + a COPIED shared map's pairs. Chains and
  // single-shared-row results that alias immutable per-row structures
  // cost the budget nothing for the aliased part — on $share-heavy
  // corpora this is the difference between ~12K cacheable row sets
  // and several hundred thousand. The floor prices the fixed per-entry
  // overhead (object header + arrays + key bytes + dict slot ≈ 300B ≈
  // 16 pair-equivalents) so tiny chains cannot balloon the dict.
  const Py_ssize_t charge =
      std::max<Py_ssize_t>(n + it->n_ovr + sh_owned_pairs, 16);
  if (t->icache_pairs + charge > kDecodeCachePairsCap) {
    if (t->icache_hits < kClearMinHits &&
        ++t->icache_skips < kAdmissionRetry) {
      Py_DECREF(key);              // cold stream: stop churning
      return reinterpret_cast<PyObject *>(it);
    }
    PyDict_Clear(t->icache);
    t->icache_pairs = 0;
    t->icache_hits = 0;
    t->icache_skips = 0;
  }
  const int rc =
      PyDict_SetItem(t->icache, key, reinterpret_cast<PyObject *>(it));
  Py_DECREF(key);
  if (rc < 0) {
    Py_DECREF(it);
    return nullptr;
  }
  t->icache_pairs += charge;
  return reinterpret_cast<PyObject *>(it);
}

// the shared zero-entry intents for unmatched topics (one per table)
PyObject *empty_intents_for(DecodeTable *t, PyObject *cap) {
  if (!t->empty_intents) {
    auto *it = intents_alloc(cap, 0);
    if (!it) return nullptr;
    t->empty_intents = reinterpret_cast<PyObject *>(it);
  }
  return Py_NewRef(t->empty_intents);
}

// decode_batch(table, toks, mode, pad, lens_enc, B, ti, rw)
//   -> list[SubscriberSet] of length B (every slot populated)
//
// toks: [B, Wt] tokens in the compact dtype (mode 1/2/4 = u8/u16/i32),
// pad: that dtype's pad value. ti/rw: int64 UNVERIFIED candidate pair
// arrays (fallback topics and out-of-table rows already dropped by
// _candidate_pairs). Unverified pairs are discarded; verified rows'
// action streams are applied.
PyObject *decode_batch_impl(PyObject *args, const bool intents) {
  PyObject *cap, *toks_o, *lens_o, *ti_o, *rw_o;
  int mode;
  long pad_l;
  Py_ssize_t B;
  if (!PyArg_ParseTuple(args, "OOilOnOO", &cap, &toks_o, &mode, &pad_l,
                        &lens_o, &B, &ti_o, &rw_o))
    return nullptr;
  auto *t = static_cast<DecodeTable *>(
      PyCapsule_GetPointer(cap, "maxmq_decode.table"));
  if (!t) return nullptr;

  Py_buffer bufs[4];
  PyObject *objs[4] = {toks_o, lens_o, ti_o, rw_o};
  int n_buf = 0;
  struct Rel {
    Py_buffer *b;
    int *n;
    ~Rel() {
      for (int i = 0; i < *n; i++) PyBuffer_Release(&b[i]);
    }
  } rel{bufs, &n_buf};
  while (n_buf < 4) {
    if (PyObject_GetBuffer(objs[n_buf], &bufs[n_buf], PyBUF_SIMPLE) < 0)
      return nullptr;
    n_buf++;
  }
  const Py_buffer &toks = bufs[0], &lens = bufs[1];
  const Py_buffer &ti_b = bufs[2], &rw_b = bufs[3];

  const Py_ssize_t N = ti_b.len / 8;
  const Py_ssize_t Wt = B ? toks.len / (B * mode) : 0;
  const Py_ssize_t W = t->W < Wt ? t->W : Wt;
  if ((Py_ssize_t)rw_b.len / 8 < N || (Py_ssize_t)lens.len < B) {
    PyErr_SetString(PyExc_ValueError, "batch array lengths disagree");
    return nullptr;
  }
  const auto *ti = static_cast<const int64_t *>(ti_b.buf);
  const auto *rw = static_cast<const int64_t *>(rw_b.buf);
  const auto *lens_enc = static_cast<const int8_t *>(lens.buf);
  const auto *tok = static_cast<const int32_t *>(t->tok.buf);
  const auto *md = static_cast<const int32_t *>(t->min_depth.buf);
  const auto *fl = static_cast<const uint8_t *>(t->flags.buf);
  const int32_t pad = static_cast<int32_t>(pad_l);

  PyObject *out = PyList_New(B);
  if (!out) return nullptr;
  for (Py_ssize_t i = 0; i < B; i++)
    PyList_SET_ITEM(out, i, Py_NewRef(Py_None));
  auto bail = [&]() -> PyObject * {
    Py_DECREF(out);
    return nullptr;
  };

  // pass 1 — verify (pure C): token windows against the row's verify
  // array; survivors keep their (topic, row) pair
  std::vector<int64_t> v_tp;
  std::vector<int32_t> v_rw;
  v_tp.reserve(N);
  v_rw.reserve(N);
  {
    TimeAcc time_pass1(&g_timing.pass1_ns);
    for (Py_ssize_t k = 0; k < N; k++) {
    const int64_t tp = ti[k], r = rw[k];
    if (tp < 0 || tp >= B || r < 0 || r >= t->R) continue;
    const uint8_t f = fl[r];
    if (!(f & FLAG_VALID)) continue;
    const int8_t le = lens_enc[tp];
    const int32_t ln = le < 0 ? -static_cast<int32_t>(le) : le;
    const int32_t m = md[r];
    if ((f & FLAG_EXACT) ? (ln != m) : (ln < m)) continue;
    if (le < 0 && (f & FLAG_WILDF)) continue;
    const int32_t *rt = tok + r * t->W;
    bool ok = true;
    for (Py_ssize_t i = 0; i < W; i++) {
      const int32_t rv = rt[i];
      if (rv == VER_ANY || rv == VER_PLUS) continue;
      if (rv != topic_tok(toks.buf, mode, pad, tp, Wt, i)) {
        ok = false;
        break;
      }
    }
    // window positions past the topic matrix (t->W > Wt) would read
    // topic token -1; only ANY/'+'/pad-literal can match there
    for (Py_ssize_t i = W; ok && i < t->W; i++) {
      const int32_t rv = rt[i];
      if (rv != VER_ANY && rv != VER_PLUS && rv != -1) ok = false;
    }
    if (!ok) continue;
    v_tp.push_back(tp);
    v_rw.push_back(static_cast<int32_t>(r));
    }
  }

  TimeAcc time_pass2(&g_timing.pass2_ns);
  // pass 2 — counting-sort the survivors by topic (pairs may interleave
  // device and host-probe streams), then resolve each topic's row SET
  // through the table's result cache: topics overwhelmingly repeat a
  // small number of row sets (shallow-'#' buckets), so the expensive
  // union runs once per distinct set, not once per topic.
  const Py_ssize_t M = (Py_ssize_t)v_tp.size();
  std::vector<int64_t> t_cnt(B + 1, 0);
  for (Py_ssize_t k = 0; k < M; k++) t_cnt[v_tp[k] + 1]++;
  for (Py_ssize_t i = 0; i < B; i++) t_cnt[i + 1] += t_cnt[i];
  std::vector<int32_t> sorted_rw(M);
  {
    std::vector<int64_t> cur(t_cnt.begin(), t_cnt.end() - 1);
    for (Py_ssize_t k = 0; k < M; k++)
      sorted_rw[cur[v_tp[k]]++] = v_rw[k];
  }
  std::vector<int32_t> rowbuf;
  for (Py_ssize_t tp = 0; tp < B; tp++) {
    const int64_t lo = t_cnt[tp], hi = t_cnt[tp + 1];
    if (lo == hi) continue;
    rowbuf.assign(sorted_rw.begin() + lo, sorted_rw.begin() + hi);
    std::sort(rowbuf.begin(), rowbuf.end());
    rowbuf.erase(std::unique(rowbuf.begin(), rowbuf.end()),
                 rowbuf.end());
    PyObject *res =
        intents ? cached_intents_result(t, cap, rowbuf.data(),
                                        (Py_ssize_t)rowbuf.size())
                : cached_rowset_result(t, rowbuf.data(),
                                       (Py_ssize_t)rowbuf.size());
    if (!res) return bail();
    PyList_SetItem(out, tp, res);  // steals; replaces the None
  }
  // fill the untouched slots so every consumer sees a real result
  // object. NOTE: populated slots may be SHARED (cache hits alias one
  // object across topics and calls) — callers must treat results as
  // immutable and deep_copy()/to_set() before mutating
  // (see SigEngine.decode_pairs' contract)
  for (Py_ssize_t i = 0; i < B; i++) {
    if (PyList_GET_ITEM(out, i) != Py_None) continue;
    PyObject *n;
    if (intents) {
      n = empty_intents_for(t, cap);
    } else {
      n = reinterpret_cast<PyObject *>(subset_new_fast(nullptr, nullptr));
    }
    if (!n) return bail();
    PyList_SetItem(out, i, n);
  }
  return out;
}

PyObject *decode_batch(PyObject *, PyObject *args) {
  return decode_batch_impl(args, false);
}

// prewarm_bases(capsule, start_row, max_builds) -> next_row.
// Builds the chained-decode anchors (slot map + pinned single-row
// intents) for every row at or above the LIVE runtime base bar,
// starting at start_row, until max_builds rows were built or the
// prewarm budget closes (3/4 of the slot-map cap: the remainder stays
// free for traffic-driven population of rows this row-order sweep
// would otherwise starve on over-budget tables). Returns the row to
// resume from (== the table's row count when finished), so engines can
// populate the anchors in bounded chunks at compile/boot time instead
// of paying the ramp across the first few hundred thousand cold
// topics.
PyObject *prewarm_bases(PyObject *, PyObject *args) {
  PyObject *cap;
  Py_ssize_t start, max_builds;
  if (!PyArg_ParseTuple(args, "Onn", &cap, &start, &max_builds))
    return nullptr;
  auto *t = static_cast<DecodeTable *>(
      PyCapsule_GetPointer(cap, "maxmq_decode.table"));
  if (!t) return nullptr;
  const auto *off = static_cast<const int64_t *>(t->offsets.buf);
  const Py_ssize_t bar =
      g_multi_base ? std::max<Py_ssize_t>(16, g_chain_min_base / 4)
                   : g_chain_min_base;
  Py_ssize_t built = 0;
  Py_ssize_t r = start < 0 ? 0 : start;
  for (; r < t->R && built < max_builds; r++) {
    const Py_ssize_t p = (off[r + 1] - off[r]) - t->shcount[r];
    if (p < bar) continue;
    // anchor-eligible $share rows: prebuild the per-row shared map
    // too (same first-touch class, same eligibility bar — sub-bar
    // rows keep building theirs lazily on first touch)
    if (t->shcount[r] && !t->rshared[r]) {
      if (!row_shared(t, r)) return nullptr;
      built++;
    }
    if (t->row_slot.count(static_cast<int32_t>(r))) continue;
    if (t->slot_entries + p > g_slot_map_cap / 4 * 3) {
      continue;                  // over-budget ROW, not a closed sweep:
                                 // smaller later rows may still fit
                                 // (the skip is one hash probe, so a
                                 // fully-spent budget costs ms of scan
                                 // once, bounded by R)
    }
    PyObject *b = nullptr;
    auto *m = ensure_row_base(t, cap, static_cast<int32_t>(r), p, &b);
    if (!m) continue;            // hard-cap decline for THIS row only
    if (!b) return nullptr;      // python error from the base build
    Py_DECREF(b);
    built++;
  }
  return PyLong_FromSsize_t(r);
}

PyObject *decode_batch_intents(PyObject *, PyObject *args) {
  return decode_batch_impl(args, true);
}

PyObject *set_slot_map_cap(PyObject *, PyObject *arg) {
  const Py_ssize_t v = PyLong_AsSsize_t(arg);
  if (v == -1 && PyErr_Occurred()) return nullptr;
  if (v < 1) {
    PyErr_SetString(PyExc_ValueError, "slot map cap must be positive");
    return nullptr;
  }
  g_slot_map_cap = v;
  Py_RETURN_NONE;
}

PyObject *get_slot_map_cap(PyObject *, PyObject *) {
  return PyLong_FromSsize_t(g_slot_map_cap);
}

// _slot_map_stats(capsule) -> (rows_with_slot_maps, slot_entries):
// observability for the chained-decode anchor budget (metrics + the
// prewarm tests assert population through it).
PyObject *slot_map_stats(PyObject *, PyObject *arg) {
  auto *t = static_cast<DecodeTable *>(
      PyCapsule_GetPointer(arg, "maxmq_decode.table"));
  if (!t) return nullptr;
  return Py_BuildValue("(nn)",
                       static_cast<Py_ssize_t>(t->row_slot.size()),
                       t->slot_entries);
}

// ADR 019: the per-subscriber PUBLISH frame head — fixed-header flags
// byte, remaining-length varint, topic segment, optional packet id,
// optional property-length varint. The one fresh allocation a patched
// template delivery makes; must stay byte-identical to the Python
// builder in protocol/wire.py (_encode_head_py), which the
// differential tests pin. props_len < 0 means a v3 frame (no
// properties block); tail_len is the payload byte count following the
// head and properties on the wire.
inline int head_varint(uint8_t *dst, Py_ssize_t v) {
  int n = 0;
  do {
    uint8_t b = static_cast<uint8_t>(v & 0x7f);
    v >>= 7;
    if (v) b |= 0x80;
    dst[n++] = b;
  } while (v);
  return n;
}

PyObject *encode_publish_template(PyObject *, PyObject *args) {
  int flags;
  Py_buffer topic;
  Py_ssize_t packet_id, props_len, tail_len;
  if (!PyArg_ParseTuple(args, "iy*nnn", &flags, &topic, &packet_id,
                        &props_len, &tail_len))
    return nullptr;
  Py_ssize_t remaining = topic.len + (packet_id ? 2 : 0) + tail_len;
  uint8_t pbuf[5];
  int pn = 0;
  if (props_len >= 0) {
    pn = head_varint(pbuf, props_len);
    remaining += pn + props_len;
  }
  if (remaining > 268435455) {  // varint ceiling [MQTT-2.2.3]
    PyBuffer_Release(&topic);
    PyErr_SetString(PyExc_ValueError, "frame exceeds varint ceiling");
    return nullptr;
  }
  uint8_t rbuf[5];
  const int rn = head_varint(rbuf, remaining);
  const Py_ssize_t total =
      1 + rn + topic.len + (packet_id ? 2 : 0) + pn;
  PyObject *out = PyBytes_FromStringAndSize(nullptr, total);
  if (!out) {
    PyBuffer_Release(&topic);
    return nullptr;
  }
  auto *w =
      reinterpret_cast<uint8_t *>(PyBytes_AS_STRING(out));
  *w++ = static_cast<uint8_t>(flags);
  std::memcpy(w, rbuf, rn);
  w += rn;
  std::memcpy(w, topic.buf, topic.len);
  w += topic.len;
  if (packet_id) {
    *w++ = static_cast<uint8_t>((packet_id >> 8) & 0xff);
    *w++ = static_cast<uint8_t>(packet_id & 0xff);
  }
  std::memcpy(w, pbuf, pn);
  PyBuffer_Release(&topic);
  return out;
}

PyMethodDef methods[] = {
    {"configure", configure, METH_VARARGS,
     "Register merge_subscription and the Subscription copy helper."},
    {"table_new", table_new, METH_VARARGS,
     "Register a compiled-snapshot decode table; returns a capsule."},
    {"decode_batch", decode_batch, METH_VARARGS,
     "Verify candidate pairs and union their subscriber entries into "
     "per-topic SubscriberSets."},
    {"decode_batch_intents", decode_batch_intents, METH_VARARGS,
     "Verify candidate pairs and union their subscriber entries into "
     "per-topic DeliveryIntents (the fan-out hot-path form)."},
    {"table_release", table_release, METH_O,
     "Drop a snapshot table's caches, breaking the intents->capsule "
     "reference cycle (call when the snapshot is dropped)."},
    {"_set_chain_enabled", set_chain_enabled, METH_O,
     "TEST ONLY: disable/enable the chained-union fast path so the "
     "suite can A/B chained vs full unions of the same row sets."},
    {"prewarm_bases", prewarm_bases, METH_VARARGS,
     "Build chained-decode row anchors in bounded chunks "
     "(capsule, start_row, max_builds) -> next_row."},
    {"_timing_reset", timing_reset, METH_O,
     "PROFILING: reset and enable(1)/disable(0) decode section timers."},
    {"_timing_get", timing_get, METH_NOARGS,
     "PROFILING: accumulated decode section times (ns) since reset."},
    {"_set_multi_base", set_multi_base, METH_O,
     "TEST/TUNING: enable/disable multi-row base composition (off = "
     "legacy single-fattest-row chaining)."},
    {"_set_chain_params", set_chain_params, METH_VARARGS,
     "TEST/TUNING: (min_base, tail_num, tail_den) — chain when the "
     "fattest row has >= min_base plain entries and tail <= "
     "fat*tail_num/tail_den."},
    {"_get_chain_params", get_chain_params, METH_NOARGS,
     "The live (min_base, tail_num, tail_den) — so A/B harnesses and "
     "test finally blocks restore the values actually in effect."},
    {"_set_slot_map_cap", set_slot_map_cap, METH_O,
     "TEST ONLY: shrink the per-table slot-map entry budget so the "
     "prewarm budget paths are exercisable at test scale."},
    {"_get_slot_map_cap", get_slot_map_cap, METH_NOARGS,
     "The live slot-map entry budget — restore the saved value, not a "
     "hardcoded default."},
    {"_slot_map_stats", slot_map_stats, METH_O,
     "(rows_with_slot_maps, slot_entries) for a table capsule — "
     "chained-decode anchor population observability."},
    {"encode_publish_template", encode_publish_template, METH_VARARGS,
     "Assemble one subscriber's PUBLISH frame head (ADR 019): "
     "(flags, topic_seg, packet_id, props_len, tail_len) -> bytes."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef mod = {PyModuleDef_HEAD_INIT, "maxmq_decode",
                   "Native verify + subscriber-union decode.", -1,
                   methods, nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit_maxmq_decode(void) {
  PyObject *m = PyModule_Create(&mod);
  if (!m) return nullptr;
  auto *tp = reinterpret_cast<PyTypeObject *>(
      PyType_FromSpec(&subset_spec));
  if (!tp || PyModule_AddObject(m, "SubscriberSet",
                                reinterpret_cast<PyObject *>(tp)) < 0) {
    Py_XDECREF(reinterpret_cast<PyObject *>(tp));
    Py_DECREF(m);
    return nullptr;
  }
  g_subset_type = tp;  // module holds the ref
  auto *ip = reinterpret_cast<PyTypeObject *>(
      PyType_FromSpec(&intents_spec));
  if (!ip || PyModule_AddObject(m, "DeliveryIntents",
                                reinterpret_cast<PyObject *>(ip)) < 0) {
    Py_XDECREF(reinterpret_cast<PyObject *>(ip));
    Py_DECREF(m);
    return nullptr;
  }
  g_intents_type = ip;
  auto *itp = reinterpret_cast<PyTypeObject *>(
      PyType_FromSpec(&intents_iter_spec));
  if (!itp) {
    Py_DECREF(m);
    return nullptr;
  }
  g_intents_iter_type = itp;  // not exposed; module keeps the ref alive
  return m;
}
