// Native host runtime for maxmq-tpu: the host-side hot loops that feed the
// TPU matcher, in C++ behind a C ABI (loaded from Python via ctypes).
//
// Two components:
//   1. Batch topic tokenizer — splits topic strings on '/', interns levels
//      against the matcher vocabulary and emits the fixed-width int32 token
//      matrix the device kernels consume. Replaces the per-topic Python loop
//      in maxmq_tpu/matching/topics.py:tokenize_topics (the semantics MUST
//      stay identical — parity-tested from tests/test_native.py).
//   2. MQTT frame scanner — walks a byte buffer of concatenated MQTT control
//      packets (fixed header: type byte + variable-byte-integer remaining
//      length, MQTT 5.0 spec 2.1.1/1.5.5) and returns frame boundaries, so a
//      listener can slice a large read into packets without touching Python
//      per byte. Mirrors the framing rules of
//      maxmq_tpu/protocol/codec.py:FixedHeader/read_varint.
//
// The reference broker has no native components (SURVEY.md section 2: pure
// Go); these are the TPU build's native equivalents for its zero-alloc hot
// paths (vendor/github.com/mochi-co/mqtt/v2/packets/codec.go:15-19).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <atomic>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

inline uint64_t fnv1a(const char* s, size_t n) {
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<uint8_t>(s[i]);
    h *= 1099511628211ull;
  }
  return h;
}

// Open-addressing vocabulary (level bytes -> token id). The tokenizer
// runs on the single-core publish hot path, so lookups must not
// allocate (the previous unordered_map<string> find built a std::string
// per level) and should cost a couple of cache lines.
struct Vocab {
  struct Entry {
    uint64_t hash;
    uint32_t off, len;
    int32_t id;
  };
  std::string pool;             // concatenated key bytes
  std::vector<Entry> entries;
  std::vector<int32_t> slots;   // index into entries, -1 = empty
  uint64_t mask = 0;
  // Lazy-build synchronization: concurrent matcher threads share one
  // Vocab per compiled table (the churn suite storms exactly this),
  // and the FIRST batch after a rotation finds it dirty — without the
  // lock two threads would rebuild slots/mask under each other's
  // probes. dirty is atomic with release/acquire pairing so a reader
  // that sees dirty == false also sees the completed slots.
  std::atomic<bool> dirty{false};
  std::mutex build_mu;

  void add(const char* s, int64_t len, int32_t id) {
    entries.push_back({fnv1a(s, len), static_cast<uint32_t>(pool.size()),
                       static_cast<uint32_t>(len), id});
    pool.append(s, len);
    dirty.store(true, std::memory_order_release);
  }

  void ensure_built() {
    if (!dirty.load(std::memory_order_acquire)) return;
    std::lock_guard<std::mutex> g(build_mu);
    if (dirty.load(std::memory_order_relaxed)) build();
  }

  void build() {
    size_t cap = 16;
    while (cap < 2 * entries.size() + 1) cap <<= 1;
    mask = cap - 1;
    slots.assign(cap, -1);
    for (size_t e = 0; e < entries.size(); ++e) {
      uint64_t h = entries[e].hash & mask;
      while (slots[h] != -1) {
        const Entry& old = entries[slots[h]];
        if (old.hash == entries[e].hash && old.len == entries[e].len &&
            memcmp(pool.data() + old.off, pool.data() + entries[e].off,
                   old.len) == 0)
          break;  // duplicate key: first insertion wins (dict semantics)
        h = (h + 1) & mask;
      }
      if (slots[h] == -1) slots[h] = static_cast<int32_t>(e);
    }
    dirty.store(false, std::memory_order_release);
  }

  int32_t find(const char* s, size_t len) const {
    if (entries.empty()) return 0;
    const uint64_t hash = fnv1a(s, len);
    uint64_t h = hash & mask;
    while (slots[h] != -1) {
      const Entry& e = entries[slots[h]];
      if (e.hash == hash && e.len == len &&
          memcmp(pool.data() + e.off, s, len) == 0)
        return e.id;
      h = (h + 1) & mask;
    }
    return 0;  // UNK
  }
};

// One exact-shape signature group for the host probe: topics of exactly
// `depth` levels match a row iff the hashed signature over the group's
// literal positions equals the row's (collisions are re-verified in the
// Python decode, mirroring maxmq_tpu/matching/sig.py:HostPlusProbe).
// Probing is one open-addressing lookup (hkeys/hstart); equal-signature
// runs (collided filters, rare) walk the sorted array.
struct ProbeGroup {
  int32_t depth;
  bool wildf;                   // level 0 is '+': excluded for '$'-topics
  uint32_t dc;                  // depth-term addend (depth_coef * depth)
  std::vector<uint32_t> coef;   // [depth] multipliers, 0 at '+' positions
  std::vector<uint32_t> sigs;   // SORTED row signatures
  std::vector<int32_t> rows;    // row ids aligned with sigs
  std::vector<uint32_t> hkeys;  // open-addressing: signature keys
  std::vector<int32_t> hstart;  // -> first index in sigs, -1 = empty
  uint32_t hmask = 0;
  std::vector<uint64_t> bloom;  // 1-hash prefilter, ~8 bits/row: almost
                                // every (topic, group) pair misses, and
                                // the bloom bits stay cache-resident
                                // where the full tables do not
  uint32_t bshift = 0;

  void build_table() {
    size_t cap = 8;
    while (cap < 2 * sigs.size() + 1) cap <<= 1;
    hmask = static_cast<uint32_t>(cap - 1);
    hkeys.assign(cap, 0);
    hstart.assign(cap, -1);
    size_t mbits = 64;
    while (mbits < 8 * sigs.size()) mbits <<= 1;
    int lg = 6;
    while ((size_t{1} << lg) < mbits) ++lg;
    bshift = 32 - lg;
    bloom.assign(mbits / 64, 0);
    for (size_t i = 0; i < sigs.size(); ++i) {
      const uint32_t bb = (sigs[i] * 0xC2B2AE35u) >> bshift;
      bloom[bb >> 6] |= uint64_t{1} << (bb & 63);
      if (i > 0 && sigs[i] == sigs[i - 1]) continue;  // run: keep first
      uint32_t h = (sigs[i] * 0x9E3779B1u) & hmask;
      while (hstart[h] != -1) h = (h + 1) & hmask;
      hkeys[h] = sigs[i];
      hstart[h] = static_cast<int32_t>(i);
    }
  }

  inline int32_t probe(uint32_t sig) const {
    const uint32_t bb = (sig * 0xC2B2AE35u) >> bshift;
    if (!(bloom[bb >> 6] & (uint64_t{1} << (bb & 63)))) return -1;
    uint32_t h = (sig * 0x9E3779B1u) & hmask;
    while (hstart[h] != -1) {
      if (hkeys[h] == sig) return hstart[h];
      h = (h + 1) & hmask;
    }
    return -1;
  }
};

struct ProbeSet {
  std::vector<ProbeGroup> groups;
  std::vector<std::vector<int32_t>> by_depth;  // depth -> group indices
  // '#'-prefix mode (mq_probe_set_ge): a group applies to any topic of
  // depth >= its prefix depth (the trailing-'#' rule incl. the depth-d
  // parent match), not just == — groups iterate depth-ascending with an
  // early break instead of through by_depth
  bool ge_depth = false;
  std::vector<int32_t> ge_sorted;              // group ids by depth asc
};

inline uint32_t tok_at(const void* toks, int32_t mode, int64_t idx) {
  switch (mode) {
    case 1: return static_cast<const uint8_t*>(toks)[idx];
    case 2: return static_cast<const uint16_t*>(toks)[idx];
    default:
      return static_cast<uint32_t>(static_cast<const int32_t*>(toks)[idx]);
  }
}

}  // namespace

extern "C" {

void* mq_vocab_new() { return new Vocab(); }

void mq_vocab_free(void* v) { delete static_cast<Vocab*>(v); }

void mq_vocab_add(void* v, const char* s, int64_t len, int32_t tok) {
  static_cast<Vocab*>(v)->add(s, len, tok);
}

int64_t mq_vocab_size(void* v) {
  return static_cast<int64_t>(static_cast<Vocab*>(v)->entries.size());
}

// Tokenize n_topics topics stored concatenated in `buf` with boundaries
// `offsets` (length n_topics + 1, offsets[i]..offsets[i+1] is topic i).
// Outputs (caller-allocated):
//   toks    int32[n_topics * max_levels]  token ids, -1 padded
//   lengths int32[n_topics]               level count, -1 if > max_levels
//   dollar  uint8[n_topics]               1 if the topic starts with '$'
// Unknown levels get token 0 (UNK). Split keeps empty levels, matching
// topics.py:split_levels ("a//b" -> 3 levels).
void mq_tokenize(void* v, const char* buf, const int64_t* offsets,
                 int64_t n_topics, int64_t max_levels, int32_t* toks,
                 int32_t* lengths, uint8_t* dollar) {
  Vocab* vb = static_cast<Vocab*>(v);
  vb->ensure_built();
  const Vocab& map = *vb;
  for (int64_t i = 0; i < n_topics; ++i) {
    const char* start = buf + offsets[i];
    const int64_t tlen = offsets[i + 1] - offsets[i];
    dollar[i] = (tlen > 0 && start[0] == '$') ? 1 : 0;
    int32_t* row = toks + i * max_levels;
    for (int64_t j = 0; j < max_levels; ++j) row[j] = -1;

    int64_t n_levels = 0;
    int64_t level_start = 0;
    bool overflow = false;
    for (int64_t p = 0; p <= tlen; ++p) {
      if (p == tlen || start[p] == '/') {
        if (n_levels >= max_levels) {
          overflow = true;
          break;
        }
        row[n_levels] = map.find(start + level_start, p - level_start);
        ++n_levels;
        level_start = p + 1;
      }
    }
    if (overflow) {
      lengths[i] = -1;
      for (int64_t j = 0; j < max_levels; ++j) row[j] = -1;
    } else {
      lengths[i] = static_cast<int32_t>(n_levels);
    }
  }
}

// Like mq_tokenize, but topics arrive as ONE UTF-8 buffer separated by NUL
// bytes (U+0000 is forbidden inside MQTT topic names [MQTT-1.5.4-2], so the
// separator is unambiguous). Avoids per-topic Python string marshalling.
void mq_tokenize_joined(void* v, const char* buf, int64_t buf_len,
                        int64_t n_topics, int64_t max_levels, int32_t* toks,
                        int32_t* lengths, uint8_t* dollar) {
  Vocab* vb = static_cast<Vocab*>(v);
  vb->ensure_built();
  const Vocab& map = *vb;
  int64_t topic_start = 0;
  int64_t i = 0;
  for (int64_t end = 0; end <= buf_len && i < n_topics; ++end) {
    if (end != buf_len && buf[end] != '\0') continue;
    const char* start = buf + topic_start;
    const int64_t tlen = end - topic_start;
    dollar[i] = (tlen > 0 && start[0] == '$') ? 1 : 0;
    int32_t* row = toks + i * max_levels;
    for (int64_t j = 0; j < max_levels; ++j) row[j] = -1;
    int64_t n_levels = 0;
    int64_t level_start = 0;
    bool overflow = false;
    for (int64_t p = 0; p <= tlen; ++p) {
      if (p == tlen || start[p] == '/') {
        if (n_levels >= max_levels) {
          overflow = true;
          break;
        }
        row[n_levels] = map.find(start + level_start, p - level_start);
        ++n_levels;
        level_start = p + 1;
      }
    }
    if (overflow) {
      lengths[i] = -1;
      for (int64_t j = 0; j < max_levels; ++j) row[j] = -1;
    } else {
      lengths[i] = static_cast<int32_t>(n_levels);
    }
    topic_start = end + 1;
    ++i;
  }
}

// One-pass compact tokenizer for the signature matcher
// (maxmq_tpu/matching/sig.py:tokenize_compact semantics, which MUST stay
// identical — parity-tested from tests/test_native.py):
//   * topics arrive NUL-joined as in mq_tokenize_joined;
//   * toks_out: narrow window tokens [n, window] — uint8 (pad 255),
//     uint16 (pad 65535) or int32 (pad -1) per tok_mode in {1, 2, 4};
//   * lens_out: int8 — sign carries the '$'-flag, |value| = TRUE depth
//     (up to 63; deeper encodes ±127 = overflow);
//   * esig_out: uint32 — the host-exact-group signature
//     sum(coef[depth][pos] * tok[pos]) + dc[depth] * depth for topics
//     whose depth has a full-exact group (exact_present[depth]); 0
//     otherwise (callers mask by depth, 0 is not a sentinel).
// exact_coef is row-major [max_exact_d + 1, max_exact_d].
void mq_tokenize_sig(void* v, const char* buf, int64_t buf_len,
                     int64_t n_topics, int64_t window, int32_t tok_mode,
                     const uint32_t* exact_coef, const uint32_t* exact_dc,
                     const uint8_t* exact_present, int64_t max_exact_d,
                     void* toks_out, int8_t* lens_out, uint32_t* esig_out) {
  Vocab* vb = static_cast<Vocab*>(v);
  vb->ensure_built();
  const Vocab& map = *vb;
  constexpr int64_t kDepthCap = 63;
  uint8_t* t8 = static_cast<uint8_t*>(toks_out);
  uint16_t* t16 = static_cast<uint16_t*>(toks_out);
  int32_t* t32 = static_cast<int32_t*>(toks_out);
  int64_t topic_start = 0;
  int64_t i = 0;
  int32_t level_toks[kDepthCap];
  for (int64_t end = 0; end <= buf_len && i < n_topics; ++end) {
    if (end != buf_len && buf[end] != '\0') continue;
    const char* start = buf + topic_start;
    const int64_t tlen = end - topic_start;
    const bool dollar = tlen > 0 && start[0] == '$';

    int64_t n_levels = 0;
    int64_t level_start = 0;
    bool overflow = false;
    for (int64_t p = 0; p <= tlen; ++p) {
      if (p == tlen || start[p] == '/') {
        if (n_levels >= kDepthCap) {
          overflow = true;
          break;
        }
        level_toks[n_levels++] =
            map.find(start + level_start, p - level_start);
        level_start = p + 1;
      }
    }

    const int8_t depth8 =
        overflow ? int8_t{127} : static_cast<int8_t>(n_levels);
    lens_out[i] = dollar ? static_cast<int8_t>(-depth8) : depth8;

    for (int64_t j = 0; j < window; ++j) {
      const bool real = !overflow && j < n_levels;
      const int32_t tok = real ? level_toks[j] : -1;
      switch (tok_mode) {
        case 1: t8[i * window + j] = real ? static_cast<uint8_t>(tok) : 255;
                break;
        case 2: t16[i * window + j] =
                    real ? static_cast<uint16_t>(tok) : 65535;
                break;
        default: t32[i * window + j] = tok;
      }
    }

    uint32_t esig = 0;
    if (!overflow && n_levels <= max_exact_d && exact_present[n_levels]) {
      const uint32_t* coef = exact_coef + n_levels * max_exact_d;
      for (int64_t p = 0; p < n_levels; ++p)
        esig += coef[p] * static_cast<uint32_t>(level_toks[p]);
      esig += exact_dc[n_levels] * static_cast<uint32_t>(n_levels);
    }
    esig_out[i] = esig;

    topic_start = end + 1;
    ++i;
  }
}

// ---------------------------------------------------------------------
// Host probe: every exact-shape filter group (full-literal and '+') as a
// hashed-equality binary search. The device keeps only '#'-prefix groups;
// this is the host half of the transfer-optimal split
// (maxmq_tpu/matching/sig.py:host_plus_rows is the numpy twin).

void* mq_probe_new() { return new ProbeSet(); }

void mq_probe_free(void* h) { delete static_cast<ProbeSet*>(h); }

void mq_probe_add_group(void* h, int32_t depth, uint8_t wildf, uint32_t dc,
                        const uint32_t* coef, const uint32_t* sigs,
                        const int32_t* rows, int64_t n) {
  auto* set = static_cast<ProbeSet*>(h);
  ProbeGroup g;
  g.depth = depth;
  g.wildf = wildf != 0;
  g.dc = dc;
  g.coef.assign(coef, coef + depth);
  g.sigs.assign(sigs, sigs + n);
  g.rows.assign(rows, rows + n);
  g.build_table();
  if (static_cast<size_t>(depth) >= set->by_depth.size())
    set->by_depth.resize(depth + 1);
  set->by_depth[depth].push_back(static_cast<int32_t>(set->groups.size()));
  set->groups.push_back(std::move(g));
}

// Flip the set to '#'-prefix (depth >=) semantics. Call AFTER every
// add_group: the depth-ascending iteration order is frozen here.
void mq_probe_set_ge(void* h) {
  auto* set = static_cast<ProbeSet*>(h);
  set->ge_depth = true;
  set->ge_sorted.resize(set->groups.size());
  for (size_t i = 0; i < set->groups.size(); ++i)
    set->ge_sorted[i] = static_cast<int32_t>(i);
  std::sort(set->ge_sorted.begin(), set->ge_sorted.end(),
            [set](int32_t a, int32_t b) {
              return set->groups[a].depth < set->groups[b].depth;
            });
}

// Probe n topics (narrow tokens as in mq_tokenize_sig: tok_mode 1/2/4,
// row-major [n, window]; lens_enc int8 sign='$' |v|=depth, 127=overflow).
// Emits (topic id, row id) hit pairs in topic order. Returns the total
// hit count; pairs beyond `cap` are not written (the caller re-invokes
// with a larger buffer — hits average ~1/topic, so this is rare).
int64_t mq_probe_run(void* h, const void* toks, int32_t tok_mode,
                     const int8_t* lens_enc, int64_t n, int64_t window,
                     int64_t* out_ti, int32_t* out_row, int64_t cap,
                     int32_t n_threads) {
  const auto* set = static_cast<ProbeSet*>(h);
  if (n_threads <= 0) {
    n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 1;
    if (n_threads > 8) n_threads = 8;
  }
  if (n < 4096) n_threads = 1;

  std::vector<std::vector<int64_t>> ti(n_threads);
  std::vector<std::vector<int32_t>> rw(n_threads);
  auto worker = [&](int32_t t) {
    const int64_t lo = n * t / n_threads;
    const int64_t hi = n * (t + 1) / n_threads;
    auto& ti_t = ti[t];
    auto& rw_t = rw[t];
    for (int64_t i = lo; i < hi; ++i) {
      const int8_t le = lens_enc[i];
      const bool dollar = le < 0;
      const int32_t depth = le < 0 ? -le : le;
      if (depth >= 127)
        continue;  // overflow topics go to the CPU-trie fallback
      if (!set->ge_depth &&
          static_cast<size_t>(depth) >= set->by_depth.size())
        continue;
      const auto& gids =
          set->ge_depth ? set->ge_sorted : set->by_depth[depth];
      for (const int32_t gi : gids) {
        const ProbeGroup& g = set->groups[gi];
        if (set->ge_depth && g.depth > depth) break;  // depth-ascending
        if ((g.wildf && dollar) || g.depth > window) continue;
        uint32_t sig = g.dc;
        const int64_t base = i * window;
        for (int32_t p = 0; p < g.depth; ++p)
          sig += g.coef[p] * tok_at(toks, tok_mode, base + p);
        int32_t j = g.probe(sig);
        for (; j >= 0 && static_cast<size_t>(j) < g.sigs.size() &&
               g.sigs[j] == sig; ++j) {
          ti_t.push_back(i);
          rw_t.push_back(g.rows[j]);
        }
      }
    }
  };
  if (n_threads == 1) {
    worker(0);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(n_threads);
    for (int32_t t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
    for (auto& th : threads) th.join();
  }

  int64_t total = 0;
  for (const auto& v : ti) total += static_cast<int64_t>(v.size());
  if (total <= cap) {
    int64_t off = 0;
    for (int32_t t = 0; t < n_threads; ++t) {
      std::copy(ti[t].begin(), ti[t].end(), out_ti + off);
      std::copy(rw[t].begin(), rw[t].end(), out_row + off);
      off += static_cast<int64_t>(ti[t].size());
    }
  }
  return total;
}

// Fused single-pass host half of the signature match: tokenize (narrow
// window form, as mq_tokenize_sig) AND probe every exact-shape group of
// the topic's depth while the level tokens are still in registers. This
// is the publish-path entry on a single-core host — one pass over the
// topic bytes, no intermediate arrays re-read.
// Outputs: toks_out/lens_out as mq_tokenize_sig; (ti_out, row_out) hit
// pairs in topic order (up to cap — returns the total regardless, the
// caller re-invokes with a larger buffer when total > cap).
}  // extern "C" (the range worker below is a C++ template)

namespace {

// One contiguous topic range of the fused tokenize+probe (the worker
// body shared by the single-thread and threaded paths). ``tstarts``
// holds n_topics+1 byte offsets: topic i spans
// [tstarts[i], tstarts[i+1]-1) (the -1 drops the '\0' separator; the
// final sentinel is buf_len+1 so the last, unterminated topic spans to
// buf_len).
template <typename Sink>
void tokenize_probe_range(const Vocab& map, const ProbeSet* set,
                          const char* buf, const int64_t* tstarts,
                          int64_t lo, int64_t hi, int64_t window,
                          int32_t tok_mode, void* toks_out,
                          int8_t* lens_out, Sink&& emit) {
  constexpr int64_t kDepthCap = 63;
  uint8_t* t8 = static_cast<uint8_t*>(toks_out);
  uint16_t* t16 = static_cast<uint16_t*>(toks_out);
  int32_t* t32 = static_cast<int32_t*>(toks_out);
  int32_t level_toks[kDepthCap];
  for (int64_t i = lo; i < hi; ++i) {
    const char* start = buf + tstarts[i];
    const int64_t tlen = tstarts[i + 1] - 1 - tstarts[i];
    const bool dollar = tlen > 0 && start[0] == '$';

    int64_t n_levels = 0;
    int64_t level_start = 0;
    bool overflow = false;
    for (int64_t p = 0; p <= tlen; ++p) {
      if (p == tlen || start[p] == '/') {
        if (n_levels >= kDepthCap) {
          overflow = true;
          break;
        }
        level_toks[n_levels++] =
            map.find(start + level_start, p - level_start);
        level_start = p + 1;
      }
    }

    const int8_t depth8 =
        overflow ? int8_t{127} : static_cast<int8_t>(n_levels);
    lens_out[i] = dollar ? static_cast<int8_t>(-depth8) : depth8;

    for (int64_t j = 0; j < window; ++j) {
      const bool real = !overflow && j < n_levels;
      const int32_t tok = real ? level_toks[j] : -1;
      switch (tok_mode) {
        case 1: t8[i * window + j] = real ? static_cast<uint8_t>(tok) : 255;
                break;
        case 2: t16[i * window + j] =
                    real ? static_cast<uint16_t>(tok) : 65535;
                break;
        default: t32[i * window + j] = tok;
      }
    }

    if (!overflow &&
        static_cast<size_t>(n_levels) < set->by_depth.size()) {
      for (const int32_t gi : set->by_depth[n_levels]) {
        const ProbeGroup& g = set->groups[gi];
        if (g.wildf && dollar) continue;
        uint32_t sig = g.dc;
        for (int32_t p = 0; p < g.depth; ++p)
          sig += g.coef[p] * static_cast<uint32_t>(level_toks[p]);
        int32_t j = g.probe(sig);
        for (; j >= 0 && static_cast<size_t>(j) < g.sigs.size() &&
               g.sigs[j] == sig; ++j) {
          emit(i, g.rows[j]);
        }
      }
    }
  }
}

}  // namespace

extern "C" {

int64_t mq_tokenize_probe(void* v, void* h, const char* buf, int64_t buf_len,
                          int64_t n_topics, int64_t window, int32_t tok_mode,
                          void* toks_out, int8_t* lens_out, int64_t* ti_out,
                          int32_t* row_out, int64_t cap) {
  Vocab* vb = static_cast<Vocab*>(v);
  vb->ensure_built();
  const Vocab& map = *vb;
  const ProbeSet* set = static_cast<ProbeSet*>(h);
  if (n_topics <= 0) return 0;

  // topic boundaries ('\0'-joined buffer, exactly n_topics-1 separators)
  std::vector<int64_t> tstarts(n_topics + 1);
  tstarts[0] = 0;
  int64_t idx = 0;
  for (int64_t e = 0; e < buf_len && idx < n_topics - 1; ++e)
    if (buf[e] == '\0') tstarts[++idx] = e + 1;
  tstarts[n_topics] = buf_len + 1;

  int32_t n_threads =
      static_cast<int32_t>(std::thread::hardware_concurrency());
  if (n_threads <= 0) n_threads = 1;
  if (n_threads > 8) n_threads = 8;
  if (n_topics < 16384) n_threads = 1;

  if (n_threads == 1) {
    // publish hot path: write hits straight into the caller's buffers
    // (partial fill up to cap, total returned regardless) — no
    // per-call vectors beyond the boundary index
    int64_t hits = 0;
    tokenize_probe_range(map, set, buf, tstarts.data(), 0, n_topics,
                         window, tok_mode, toks_out, lens_out,
                         [&](int64_t i, int32_t r) {
                           if (hits < cap) {
                             ti_out[hits] = i;
                             row_out[hits] = r;
                           }
                           ++hits;
                         });
    return hits;
  }

  std::vector<std::vector<int64_t>> ti(n_threads);
  std::vector<std::vector<int32_t>> rw(n_threads);
  auto worker = [&](int32_t t) {
    auto& ti_t = ti[t];
    auto& rw_t = rw[t];
    tokenize_probe_range(map, set, buf, tstarts.data(),
                         n_topics * t / n_threads,
                         n_topics * (t + 1) / n_threads, window, tok_mode,
                         toks_out, lens_out,
                         [&](int64_t i, int32_t r) {
                           ti_t.push_back(i);
                           rw_t.push_back(r);
                         });
  };
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int32_t t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  int64_t total = 0;
  for (const auto& vv : ti) total += static_cast<int64_t>(vv.size());
  int64_t off = 0;
  for (int32_t t = 0; t < n_threads && off < cap; ++t) {
    const int64_t take = std::min<int64_t>(
        static_cast<int64_t>(ti[t].size()), cap - off);
    std::copy(ti[t].begin(), ti[t].begin() + take, ti_out + off);
    std::copy(rw[t].begin(), rw[t].begin() + take, row_out + off);
    off += take;
  }
  return total;
}

// Scan `buf` (len bytes) for complete MQTT control-packet frames.
// For each complete frame i < max_frames: starts[i] = offset of the fixed
// header byte, totals[i] = total frame size (header + varint + body).
// Returns the number of complete frames found (scanning stops at the first
// incomplete frame — its offset is *consumed_out), or -1 if a malformed
// variable-byte integer is encountered (more than 4 continuation bytes,
// MQTT-1.5.5) or a zero packet type.
int64_t mq_scan_frames(const uint8_t* buf, int64_t len, int64_t* starts,
                       int64_t* totals, int64_t max_frames,
                       int64_t* consumed_out) {
  int64_t pos = 0;
  int64_t count = 0;
  while (pos < len && count < max_frames) {
    if ((buf[pos] >> 4) == 0) {
      *consumed_out = pos;
      return -1;  // packet type 0 is reserved/invalid
    }
    // variable-byte integer remaining length
    int64_t rem = 0;
    int shift = 0;
    int64_t vpos = pos + 1;
    bool complete = false;
    while (vpos < len) {
      uint8_t b = buf[vpos++];
      rem |= static_cast<int64_t>(b & 0x7F) << shift;
      shift += 7;
      if ((b & 0x80) == 0) {
        complete = true;
        break;
      }
      if (shift > 21) {
        *consumed_out = pos;
        return -1;  // > 4 varint bytes is malformed [MQTT-1.5.5]
      }
    }
    if (!complete) break;  // header truncated: wait for more bytes
    const int64_t total = (vpos - pos) + rem;
    if (pos + total > len) break;  // body truncated
    starts[count] = pos;
    totals[count] = total;
    ++count;
    pos += total;
  }
  *consumed_out = pos;
  return count;
}

}  // extern "C"
