"""A minimal asyncio MQTT client (v3.1.1 / v5).

Fills the role the Eclipse Paho client plays in the reference's system tests
(tests/system/mqtt_test.go) and doubles as the benchmark load generator.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from .protocol.codec import FixedHeader, PacketType as PT
from .protocol.packets import Packet, Subscription, Will, parse_stream
from .protocol.properties import Properties


@dataclass
class Message:
    topic: str
    payload: bytes
    qos: int = 0
    retain: bool = False
    properties: Properties = field(default_factory=Properties)
    # ADR 017: cross-node trace identity ("<origin>:<id>") carried on
    # the delivery's ``mq-trace`` v5 user property when the publish
    # rode a sampled trace — one grep key across every node's logs,
    # /traces pages, and the bench subscribers
    trace: str = ""


class MQTTError(Exception):
    pass


class MQTTClient:
    """One client connection. Usage::

        c = MQTTClient("cl1", version=5)
        await c.connect("127.0.0.1", 1883)
        await c.subscribe("a/#", qos=1)
        await c.publish("a/b", b"hi", qos=1)
        msg = await c.next_message(timeout=1)
        await c.disconnect()
    """

    def __init__(self, client_id: str = "", version: int = 4,
                 clean_start: bool = True, keepalive: int = 60,
                 username: str = "", password: str = "",
                 will: Will | None = None,
                 session_expiry: int | None = None) -> None:
        self.client_id = client_id
        self.version = version
        self.clean_start = clean_start
        self.keepalive = keepalive
        self.username = username
        self.password = password
        self.will = will
        self.session_expiry = session_expiry
        self.reader: asyncio.StreamReader | None = None
        self.writer = None
        self.connack: Packet | None = None
        # CONNACK outcome surfaced to callers even when connect()
        # raises (bridge links log the broker's refusal reason instead
        # of a bare MQTTError, ADR 013)
        self.connack_reason: int | None = None
        self.session_present: bool | None = None
        # first fatal transport error; the read loop used to swallow
        # these silently (mirrors broker Client.write_error, ADR 012)
        self.transport_error: str | None = None
        self.messages: asyncio.Queue[Message] = asyncio.Queue()
        self.disconnect_packet: Packet | None = None
        self._acks: dict[tuple[int, int], asyncio.Future] = {}
        self._next_id = 0
        self._read_task: asyncio.Task | None = None
        self._closed = asyncio.Event()
        self._inbound_pubrel_pending: set[int] = set()

    # ------------------------------------------------------------------

    async def connect(self, host: str = "127.0.0.1", port: int = 1883,
                      timeout: float = 5.0, reader=None, writer=None,
                      path: str | None = None) -> Packet:
        """Open the transport (or adopt a provided stream pair) and perform
        the CONNECT/CONNACK handshake. ``path`` connects over a unix
        domain socket instead of TCP (the ADR-021 local bridge flavor)."""
        if reader is None:
            if path is not None:
                self.reader, self.writer = await asyncio.wait_for(
                    asyncio.open_unix_connection(path), timeout)
            else:
                self.reader, self.writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), timeout)
        else:
            self.reader, self.writer = reader, writer
        self.writer.write(self._connect_packet().encode())
        await self.writer.drain()

        buf = bytearray()
        while True:
            chunk = await asyncio.wait_for(self.reader.read(65536), timeout)
            if not chunk:
                raise MQTTError("connection closed before CONNACK")
            buf.extend(chunk)
            for fh, body in parse_stream(buf):
                if fh.type != PT.CONNACK:
                    raise MQTTError(f"expected CONNACK, got {fh.type}")
                self.connack = Packet.decode(fh, body, self.version)
                self.connack_reason = self.connack.reason_code
                self.session_present = self.connack.session_present
                if self.connack.reason_code >= 0x80 or (
                        self.version < 5 and self.connack.reason_code != 0):
                    raise MQTTError(
                        f"connect refused: {self.connack.reason_code:#x}")
                if self.connack.properties.assigned_client_id:
                    self.client_id = self.connack.properties.assigned_client_id
                self._read_task = asyncio.get_running_loop().create_task(
                    self._read_loop(bytes(buf)))
                return self.connack

    def _connect_packet(self) -> Packet:
        packet = Packet(fixed=FixedHeader(type=PT.CONNECT),
                        protocol_version=self.version,
                        clean_start=self.clean_start,
                        keepalive=self.keepalive,
                        client_id=self.client_id,
                        will=self.will)
        if self.username:
            packet.username = self.username.encode()
            packet.username_flag = True
        if self.password:
            packet.password = self.password.encode()
            packet.password_flag = True
        if self.version >= 5 and self.session_expiry is not None:
            packet.properties.session_expiry = self.session_expiry
        return packet

    async def _read_loop(self, initial: bytes = b"") -> None:
        buf = self._read_buf = bytearray(initial)
        try:
            while True:
                for fh, body in parse_stream(buf):
                    await self._handle(Packet.decode(fh, body, self.version))
                chunk = await self.reader.read(65536)
                if not chunk:
                    break
                buf.extend(chunk)
        except asyncio.CancelledError:
            pass
        except (ConnectionError, OSError) as exc:
            # swallowed (the loop must end either way), but recorded:
            # a bridge supervisor reports WHY its link died, and tests
            # can assert on it instead of guessing (ADR 013)
            self.transport_error = self.transport_error or repr(exc)
        finally:
            self._closed.set()
            for fut in self._acks.values():
                if not fut.done():
                    fut.set_exception(MQTTError("connection closed"))

    async def pause_reading(self) -> bytes:
        """Stop the internal read task and return any unconsumed buffered
        bytes; the caller then owns ``self.reader`` (raw-socket
        harnesses that count frames without per-message decode)."""
        if self._read_task is not None:
            self._read_task.cancel()
            try:
                await self._read_task
            except asyncio.CancelledError:
                pass
            self._read_task = None
        return bytes(getattr(self, "_read_buf", b""))

    async def _handle(self, packet: Packet) -> None:
        t = packet.type
        if t == PT.PUBLISH:
            await self._handle_publish(packet)
        elif t in (PT.PUBACK, PT.PUBCOMP, PT.SUBACK, PT.UNSUBACK):
            fut = self._acks.pop((t, packet.packet_id), None)
            if fut is not None and not fut.done():
                fut.set_result(packet)
        elif t == PT.PUBREC:
            rel = Packet(fixed=FixedHeader(type=PT.PUBREL),
                         protocol_version=self.version,
                         packet_id=packet.packet_id)
            self.writer.write(rel.encode())
            await self.writer.drain()
        elif t == PT.PUBREL:
            self._inbound_pubrel_pending.discard(packet.packet_id)
            comp = Packet(fixed=FixedHeader(type=PT.PUBCOMP),
                          protocol_version=self.version,
                          packet_id=packet.packet_id)
            self.writer.write(comp.encode())
            await self.writer.drain()
        elif t == PT.PINGRESP:
            fut = self._acks.pop((t, 0), None)
            if fut is not None and not fut.done():
                fut.set_result(packet)
        elif t == PT.DISCONNECT:
            self.disconnect_packet = packet

    async def _handle_publish(self, packet: Packet) -> None:
        msg = Message(topic=packet.topic, payload=packet.payload,
                      qos=packet.fixed.qos, retain=packet.fixed.retain,
                      properties=packet.properties,
                      trace=next((v for k, v in
                                  packet.properties.user_properties
                                  if k == "mq-trace"), ""))
        if packet.fixed.qos == 1:
            ack = Packet(fixed=FixedHeader(type=PT.PUBACK),
                         protocol_version=self.version,
                         packet_id=packet.packet_id)
            self.writer.write(ack.encode())
            await self.writer.drain()
        elif packet.fixed.qos == 2:
            dup = packet.packet_id in self._inbound_pubrel_pending
            self._inbound_pubrel_pending.add(packet.packet_id)
            rec = Packet(fixed=FixedHeader(type=PT.PUBREC),
                         protocol_version=self.version,
                         packet_id=packet.packet_id)
            self.writer.write(rec.encode())
            await self.writer.drain()
            if dup:
                return  # exactly-once: don't surface the duplicate
        await self.messages.put(msg)

    # ------------------------------------------------------------------

    def _alloc_id(self) -> int:
        self._next_id = (self._next_id % 65535) + 1
        return self._next_id

    def _await_ack(self, ptype: int, packet_id: int) -> asyncio.Future:
        fut = asyncio.get_running_loop().create_future()
        self._acks[(ptype, packet_id)] = fut
        return fut

    async def subscribe(self, *filters: str | tuple[str, int], qos: int = 0,
                        timeout: float = 5.0, **opts) -> list[int]:
        subs = []
        for f in filters:
            if isinstance(f, tuple):
                subs.append(Subscription(filter=f[0], qos=f[1], **opts))
            else:
                subs.append(Subscription(filter=f, qos=qos, **opts))
        pid = self._alloc_id()
        packet = Packet(fixed=FixedHeader(type=PT.SUBSCRIBE),
                        protocol_version=self.version, packet_id=pid,
                        filters=subs)
        fut = self._await_ack(PT.SUBACK, pid)
        self.writer.write(packet.encode())
        await self.writer.drain()
        ack = await asyncio.wait_for(fut, timeout)
        return ack.reason_codes

    async def unsubscribe(self, *filters: str, timeout: float = 5.0) -> list[int]:
        pid = self._alloc_id()
        packet = Packet(fixed=FixedHeader(type=PT.UNSUBSCRIBE),
                        protocol_version=self.version, packet_id=pid,
                        filters=[Subscription(filter=f) for f in filters])
        fut = self._await_ack(PT.UNSUBACK, pid)
        self.writer.write(packet.encode())
        await self.writer.drain()
        ack = await asyncio.wait_for(fut, timeout)
        return ack.reason_codes

    async def publish(self, topic: str, payload: bytes = b"", qos: int = 0,
                      retain: bool = False, timeout: float = 5.0,
                      properties: Properties | None = None) -> None:
        packet = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos,
                                          retain=retain),
                        protocol_version=self.version, topic=topic,
                        payload=payload)
        if properties is not None:
            packet.properties = properties
        if qos == 0:
            self.writer.write(packet.encode())
            await self.writer.drain()
            return
        pid = self._alloc_id()
        packet.packet_id = pid
        fut = self._await_ack(PT.PUBACK if qos == 1 else PT.PUBCOMP, pid)
        self.writer.write(packet.encode())
        await self.writer.drain()
        await asyncio.wait_for(fut, timeout)

    async def ping(self, timeout: float = 5.0) -> None:
        fut = self._await_ack(PT.PINGRESP, 0)
        self.writer.write(Packet(fixed=FixedHeader(type=PT.PINGREQ),
                                 protocol_version=self.version).encode())
        await self.writer.drain()
        await asyncio.wait_for(fut, timeout)

    async def next_message(self, timeout: float = 5.0) -> Message:
        return await asyncio.wait_for(self.messages.get(), timeout)

    async def disconnect(self, reason_code: int = 0) -> None:
        if self.writer is None:
            return
        try:
            self.writer.write(Packet(fixed=FixedHeader(type=PT.DISCONNECT),
                                     protocol_version=self.version,
                                     reason_code=reason_code).encode())
            await self.writer.drain()
        except (ConnectionError, OSError) as exc:
            # shutdown path: swallowed but recorded (write_error
            # pattern, ADR 012/013)
            self.transport_error = self.transport_error or repr(exc)
        await self.close()

    async def close(self) -> None:
        if self._read_task is not None:
            self._read_task.cancel()
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
        self._closed.set()

    async def wait_closed(self, timeout: float = 5.0) -> None:
        await asyncio.wait_for(self._closed.wait(), timeout)
