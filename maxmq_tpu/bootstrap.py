"""Process bootstrap: config → logger → broker + metrics server → run until
signalled.

Parity surface: internal/cli/start.go in the reference — ``runServer``
(start.go:111-181) loads config, builds the snowflake-ID logger, spawns the
metrics and MQTT servers concurrently, waits for SIGINT/SIGTERM
(start.go:69-77), and optionally writes CPU/heap profiles (128-137,165-180).
"""

from __future__ import annotations

import asyncio
import os
import signal

from .broker import Broker, BrokerOptions, Capabilities, TCPListener
from .broker.listeners import HTTPStatsListener, UnixListener, WSListener
from .hooks import AllowHook
from .hooks.logging import LoggingHook
from .hooks.storage import MemoryStore, SQLiteStore, StorageHook
from .metrics import MetricsServer, Registry, register_broker_metrics
from .utils.config import Config, config_as_dict
from .utils.logger import Logger
from .utils.snowflake import Snowflake

BANNER = r"""
  __  __            __  __  ___    _____ ___ _   _
 |  \/  | __ ___  _|  \/  |/ _ \  |_   _| _ \ | | |
 | |\/| |/ _` \ \/ / |\/| | (_) |   | | |  _/ |_| |
 |_|  |_|\__,_|_|\_\_|  |_|\__\_\   |_| |_|  \___/
        TPU-native MQTT broker
"""


def capabilities_from_config(conf: Config) -> Capabilities:
    """Map the flat config onto broker capabilities, the way the reference's
    facade maps its Config into mochi Capabilities (internal/mqtt/
    server.go:76-91)."""
    return Capabilities(
        maximum_session_expiry_interval=conf.mqtt_session_expiry_interval,
        maximum_message_expiry_interval=conf.mqtt_max_message_expiry_interval,
        receive_maximum=conf.mqtt_receive_maximum,
        maximum_qos=conf.mqtt_max_qos,
        retain_available=conf.mqtt_retain_available,
        maximum_packet_size=conf.mqtt_max_packet_size,
        topic_alias_maximum=conf.mqtt_max_topic_alias,
        wildcard_sub_available=conf.mqtt_wildcard_subscription_available,
        sub_id_available=conf.mqtt_subscription_id_available,
        shared_sub_available=conf.mqtt_shared_subscription_available,
        minimum_protocol_version=conf.mqtt_min_protocol_version,
        buffer_size=conf.mqtt_buffer_size,    # clamped in Capabilities
        shutdown_timeout=float(conf.mqtt_shutdown_timeout),
        maximum_keepalive=conf.mqtt_max_keep_alive,
        maximum_client_writes_pending=conf.mqtt_max_outbound_queue,
        maximum_inflight=conf.mqtt_max_inflight_messages,
        sys_topic_interval=float(conf.mqtt_sys_topic_interval),
        # overload-protection ladder (ADR 012)
        client_byte_budget=conf.broker_client_byte_budget,
        broker_byte_budget=conf.broker_byte_budget,
        connect_rate=float(conf.connect_rate),
        connect_burst=conf.connect_burst,
        connect_half_open_max=conf.connect_half_open_max,
        stall_deadline_ms=conf.stall_deadline_ms,
        overload_high_water=float(conf.broker_overload_high_water),
        overload_low_water=float(conf.broker_overload_low_water),
        # publish-path tracing (ADR 015)
        trace_sample_n=conf.trace_sample_n,
        trace_slow_ms=float(conf.trace_slow_ms),
        trace_ring=conf.trace_ring,
        # zero-copy fan-out (ADR 019)
        native_encode=conf.broker_native_encode,
        flush_coalesce=conf.broker_flush_coalesce,
        # MQTT+ content plane (ADR 023)
        content_filtering=conf.filter_enabled,
        filter_backend=conf.filter_backend,
        filter_max_subscriptions=conf.filter_max_subscriptions,
        filter_max_expr_len=conf.filter_max_expr_len,
        filter_max_fields=conf.filter_max_fields,
        filter_batch_max=conf.filter_batch_max,
        filter_window_min_s=float(conf.filter_window_min_s),
        filter_window_max_s=float(conf.filter_window_max_s),
    )


def install_event_loop(policy: str, logger: Logger | None = None) -> str:
    """Install the configured asyncio event-loop policy BEFORE
    asyncio.run (ADR 023 satellite). ``auto`` takes uvloop when the
    package is installed; ``uvloop`` warns and falls back cleanly when
    it is not — a config written for a uvloop box must still boot a
    bare one. Returns the name of what was installed."""
    policy = (policy or "auto").strip().lower()
    if policy not in ("auto", "asyncio", "uvloop"):
        raise ValueError(f"unknown broker_event_loop {policy!r} "
                         "(want auto|asyncio|uvloop)")
    if policy in ("auto", "uvloop"):
        try:
            import uvloop
        except ImportError:
            if policy == "uvloop" and logger is not None:
                logger.with_prefix("bootstrap").warn(
                    "uvloop requested but not installed; "
                    "falling back to asyncio")
        else:
            asyncio.set_event_loop_policy(uvloop.EventLoopPolicy())
            return "uvloop"
    asyncio.set_event_loop_policy(asyncio.DefaultEventLoopPolicy())
    return "asyncio"


def build_matcher(conf: Config, broker: Broker):
    """Attach the configured matcher engine to the broker.

    ``trie`` is the CPU reference path (broker default, no attach needed);
    ``nfa``/``dense`` are the device paths; a ``matcher_mesh`` like "2x4"
    shards the NFA over a device mesh (cluster mode); ``service``
    connects to an external chip-owning matcher service at
    ``matcher_socket`` (attached in run_server — it needs the loop)."""
    if conf.matcher in ("", "trie", "service"):
        return None
    if conf.matcher_mesh:
        from .parallel.sharded import (ShardedNFAEngine, ShardedSigEngine,
                                       make_mesh)
        rows, _, cols = conf.matcher_mesh.partition("x")
        mesh = make_mesh(shape=(int(rows), int(cols or 1)))
        if conf.matcher == "nfa":
            engine = ShardedNFAEngine(broker.topics, mesh=mesh,
                                      max_levels=conf.matcher_max_levels)
        else:
            # the sharded sig engine derives its depth window from the
            # corpus (DEPTH_CAP-bounded); matcher_max_levels is a
            # word-path/nfa/dense knob
            engine = ShardedSigEngine(broker.topics, mesh=mesh)
            engine.emit_intents = conf.matcher_intents   # ADR 007
    elif conf.matcher == "nfa":
        from .matching.engine import NFAEngine
        engine = NFAEngine(broker.topics,
                           max_levels=conf.matcher_max_levels)
    elif conf.matcher == "dense":
        from .matching.dense import DenseEngine
        engine = DenseEngine(broker.topics,
                             max_levels=conf.matcher_max_levels)
    elif conf.matcher == "sig":
        from .matching.sig import SigEngine
        engine = SigEngine(broker.topics,
                           max_levels=conf.matcher_max_levels)
        # fan-out-ready DeliveryIntents from the native decode (ADR 007)
        # — the broker handles both result shapes, so this is safe to
        # default on; matcher_intents = false restores merged sets
        engine.emit_intents = conf.matcher_intents
    else:
        raise ValueError(f"unknown matcher {conf.matcher!r}")
    from .matching.batcher import MicroBatcher
    batcher = MicroBatcher(engine,
                           window_us=conf.matcher_batch_window_us,
                           max_batch=conf.matcher_max_batch)
    # ADR 015: the batcher stamps dispatch/result marks on match
    # futures when the broker's tracer is sampling, so per-publish
    # traces split coalescing wait from device time
    batcher.tracer = broker.tracer
    attach = batcher
    if conf.matcher_supervised:
        # ADR 011: per-batch deadline + trie hedge + circuit breaker
        # around every device call — publishes complete (bit-equal to
        # the CPU trie) through device errors, hangs, failed recompiles
        from .matching.supervisor import SupervisedMatcher
        attach = SupervisedMatcher(batcher, index=broker.topics,
                                   logger=broker.log,
                                   **supervisor_kwargs(conf))
    broker.attach_matcher(attach)
    warm = getattr(engine, "warm_buckets", None)
    if warm is not None:
        warm(conf.matcher_max_batch)    # background bucket precompile
    prewarm = getattr(engine, "prewarm_decode_bases", None)
    if prewarm is not None:
        prewarm()    # chained-decode anchors at the boot quiescent point
    return attach


def supervisor_kwargs(conf: Config) -> dict:
    """The ADR-011 SupervisedMatcher knobs as a kwargs dict (shared by
    the in-process matcher build and the service attach)."""
    return dict(deadline_ms=conf.matcher_deadline_ms,
                breaker_threshold=conf.matcher_breaker_threshold,
                breaker_window_s=conf.matcher_breaker_window_s,
                backoff_initial_s=conf.matcher_breaker_backoff_s,
                backoff_max_s=conf.matcher_breaker_backoff_max_s)


def build_cluster(conf: Config, broker: Broker, logger: Logger | None = None):
    """Attach the federation manager (ADR 013) when ``cluster_node_id``
    is set: bridge links to every ``cluster_peers`` entry, the
    aggregated route table, and $cluster/* inbound handling. The links
    start with broker.serve()."""
    if not conf.cluster_node_id:
        return None
    from .cluster import ClusterManager
    from .cluster.membership import parse_peers
    manager = ClusterManager(
        broker, conf.cluster_node_id, parse_peers(conf.cluster_peers),
        link_qos=conf.cluster_link_qos,
        max_hops=conf.cluster_max_hops,
        link_byte_budget=conf.cluster_link_byte_budget,
        keepalive=float(conf.cluster_link_keepalive),
        session_replication=conf.cluster_session_replication,
        session_sync=conf.cluster_session_sync,
        session_sync_timeout_ms=conf.cluster_session_sync_timeout_ms,
        fwd_durability=conf.cluster_fwd_durability,
        replica_expiry_s=float(conf.cluster_replica_expiry_s),
        share_balance=conf.cluster_share_balance,
        session_takeover_timeout_ms=(
            conf.cluster_session_takeover_timeout_ms),
        trace_propagation=conf.cluster_trace_propagation,
        trace_return=conf.cluster_trace_return,
        telemetry_interval_s=float(conf.cluster_telemetry_interval_s),
        telemetry_full_every=conf.cluster_telemetry_full_every,
        rtt_deadline_k=float(conf.cluster_rtt_deadline_k),
        content_routes=conf.cluster_content_routes,
        logger=logger.with_prefix("cluster") if logger else None)
    broker.attach_cluster(manager)
    return manager


def build_storage(conf: Config) -> "StorageHook | None":
    """The ADR-014 persistence pipeline: backend store (SQLite opened
    with the ``storage_sync``-derived synchronous pragma) behind a
    write-behind journal, so hook writes never fsync on the event loop
    and QoS acks can ride the durability barrier under ``always``."""
    if not conf.storage_backend:
        return None
    from .hooks.faultstore import FaultInjectingStore
    from .hooks.journal import SQLITE_SYNC_BY_POLICY, WriteBehindStore
    policy = conf.storage_sync
    if policy not in SQLITE_SYNC_BY_POLICY:
        raise ValueError(f"unknown storage_sync {policy!r} "
                         f"(want always|batched|off)")
    if conf.storage_backend == "memory":
        inner = MemoryStore()
    else:
        inner = SQLiteStore(conf.storage_path,
                            synchronous=SQLITE_SYNC_BY_POLICY[policy])
    # the disk.* fault shim (ADR 024) wraps unconditionally: every site
    # is consulted off the event loop and the unarmed fast path is one
    # empty-dict membership test per commit
    inner = FaultInjectingStore(inner)
    store = WriteBehindStore(
        inner, policy=policy,
        batch_ms=conf.storage_batch_ms,
        batch_ops=conf.storage_batch_ops,
        queue_bytes=conf.storage_queue_bytes,
        breaker_threshold=conf.storage_breaker_threshold,
        backoff_s=float(conf.storage_breaker_backoff_s),
        backoff_max_s=float(conf.storage_breaker_backoff_max_s))
    return StorageHook(store)


def build_broker(conf: Config, logger: Logger) -> Broker:
    """Assemble a broker from config: capabilities, listeners, hooks,
    matcher. Mirrors internal/mqtt/server.go:38-118."""
    broker = Broker(BrokerOptions(capabilities=capabilities_from_config(conf),
                                  logger=logger.with_prefix("mqtt")))
    broker.add_hook(LoggingHook(logger.with_prefix("mqtt")))
    if conf.log_level == "trace":
        # per-packet tx logging lives in its own hook: its
        # on_packet_sent override disables zero-copy fan-out (ADR 019),
        # so it is only attached when TRACE would actually emit
        from .hooks.logging import PacketTxLogHook
        broker.add_hook(PacketTxLogHook(logger.with_prefix("mqtt")))
    if conf.auth_ledger:
        from .hooks.auth import Ledger, LedgerHook
        broker.add_hook(LedgerHook(Ledger.from_file(conf.auth_ledger)))
    else:
        broker.add_hook(AllowHook())
    storage = build_storage(conf)
    if storage is not None:
        broker.add_hook(storage)
    if conf.mqtt_tcp_address:
        broker.add_listener(TCPListener("tcp", conf.mqtt_tcp_address,
                                        reuse_port=conf.workers > 1))
    if conf.mqtt_ws_address:
        broker.add_listener(WSListener("ws", conf.mqtt_ws_address,
                                       reuse_port=conf.workers > 1))
    if conf.mqtt_unix_socket:
        broker.add_listener(UnixListener("unix", conf.mqtt_unix_socket))
    if conf.mqtt_sys_http_address:
        broker.add_listener(HTTPStatsListener(
            "sys-http", conf.mqtt_sys_http_address, lambda: broker.info))
    build_matcher(conf, broker)
    build_cluster(conf, broker, logger)
    return broker


def build_metrics(conf: Config, broker: Broker,
                  logger: Logger) -> MetricsServer | None:
    if not conf.metrics_enabled:
        return None
    registry = Registry()
    register_broker_metrics(registry, broker)
    # ADR 017: with a cluster attached, ANY node serves the federated
    # /cluster/metrics page from its telemetry plane
    telemetry = getattr(broker.cluster, "telemetry", None) \
        if broker.cluster is not None else None
    return MetricsServer(conf.metrics_address, registry,
                         path=conf.metrics_path,
                         profiling=conf.metrics_profiling,
                         logger=logger.with_prefix("metrics"),
                         tracer=broker.tracer,
                         cluster_metrics=(telemetry.cluster_exposition
                                          if telemetry is not None
                                          else None))


def new_logger_from_config(conf: Config) -> Logger:
    from .utils.logger import new_logger
    sf = Snowflake(machine_id=conf.machine_id)
    return new_logger(fmt=conf.log_format, level=conf.log_level,
                      log_id_gen=sf.next_id)


async def _maybe_attach_service(conf: Config, broker: Broker) -> None:
    """matcher = "service": connect to the external chip-owning matcher
    (``maxmq matcher-service``) at conf.matcher_socket."""
    if conf.matcher == "service":
        from .matching.service import attach_matcher_service
        await attach_matcher_service(
            broker, conf.matcher_socket,
            supervisor=(supervisor_kwargs(conf)
                        if conf.matcher_supervised else None))


def _signal_stop_event() -> asyncio.Event:
    """A stop event set by SIGINT/SIGTERM (start.go:71-77 analogue)."""
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:
            pass
    return stop


async def run_server(conf: Config, logger: Logger,
                     ready: asyncio.Event | None = None,
                     stop: asyncio.Event | None = None,
                     broker_out: list | None = None) -> None:
    """Run broker + metrics until ``stop`` is set or SIGINT/SIGTERM.

    ``ready``/``stop`` let tests drive the full bootstrap in-process the way
    the reference's start_test.go runs runServer with a cancellable context;
    ``broker_out`` (a list the built Broker is appended to) lets them
    assert on the wired components without reaching into module state.
    """
    boot = logger.with_prefix("bootstrap")
    boot.debug("effective configuration", **config_as_dict(conf))

    if await _maybe_run_pool(conf, logger, ready, stop):
        return

    profiler = _start_profiling(conf)

    broker = build_broker(conf, logger)
    if broker_out is not None:
        broker_out.append(broker)
    # service matcher must attach BEFORE the metrics registry is built,
    # or the matcher/pipeline metrics never register in service mode
    await _maybe_attach_service(conf, broker)
    metrics = build_metrics(conf, broker, logger)

    if stop is None:
        stop = _signal_stop_event()

    if metrics is not None:
        metrics.start()
    await broker.serve()
    boot.info("server started", tcp=conf.mqtt_tcp_address,
              matcher=conf.matcher or "trie")
    if ready is not None:
        ready.set()

    try:
        await stop.wait()
    finally:
        boot.info("shutting down")
        await broker.close()
        if metrics is not None:
            metrics.stop()
        matcher = broker.matcher
        if matcher is not None and hasattr(matcher, "close"):
            await matcher.close()
        if profiler is not None:
            _stop_profiling(profiler, conf, boot)
        boot.info("server stopped")


async def _maybe_run_pool(conf: Config, logger, ready, stop) -> bool:
    """Delivery-worker pool (ADR 005/021): the parent runs the shared
    matcher sidecar and spawns SO_REUSEPORT workers, which mesh as an
    in-box cluster over unix bridge links; a worker subprocess
    re-enters run_server with MAXMQ_WORKER_ID set and takes the worker
    branch."""
    worker_id = os.environ.get("MAXMQ_WORKER_ID")
    if worker_id is not None:
        from .broker.workers import POOL_DIR_ENV, run_worker
        pool_conf = os.environ.get("MAXMQ_POOL_CONF")
        if pool_conf:
            import json
            conf = Config(**json.loads(pool_conf))
        await run_worker(conf, logger, int(worker_id),
                         os.environ[POOL_DIR_ENV], ready=ready, stop=stop)
        return True
    if conf.workers > 1:
        from .broker.workers import run_pool
        await run_pool(conf, logger, ready=ready, stop=stop)
        return True
    return False


def _start_profiling(conf: Config):
    if not conf.profile:
        return None
    import cProfile
    import tracemalloc
    profiler = cProfile.Profile()
    profiler.enable()
    tracemalloc.start()
    return profiler


def _stop_profiling(profiler, conf: Config, boot) -> None:
    import tracemalloc
    profiler.disable()
    profiler.dump_stats(f"{conf.profile_path}/cpu.prof")
    snap = tracemalloc.take_snapshot()
    with open(f"{conf.profile_path}/heap.prof", "w") as f:
        for s in snap.statistics("lineno")[:256]:
            f.write(str(s) + "\n")
    tracemalloc.stop()
    boot.info("profiles written", path=conf.profile_path)
