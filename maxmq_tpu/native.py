"""ctypes bindings for the C++ native host runtime (native/maxmq_native.cpp).

Loads ``libmaxmq_native.so`` (building it with ``make -C native`` on first
use if a compiler is available), and exposes:

* ``NativeVocab`` / ``tokenize`` — the batch topic tokenizer feeding the TPU
  matchers; exact drop-in for matching/topics.py:tokenize_topics.
* ``scan_frames`` — the MQTT fixed-header frame scanner; slices a byte
  buffer of concatenated control packets into frames without per-byte
  Python work (same framing rules as protocol/codec.py).

Everything degrades gracefully: ``available()`` is False when the library
can't be built/loaded (or MAXMQ_NO_NATIVE is set) and callers fall back to
the pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.environ.get("MAXMQ_NATIVE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libmaxmq_native.so")

_lib = None
_load_lock = threading.Lock()
_load_attempted = False


def _try_load():
    global _lib, _load_attempted
    with _load_lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("MAXMQ_NO_NATIVE"):
            return None
        # on-demand build only where a Makefile exists — an override dir
        # (MAXMQ_NATIVE_DIR, e.g. native/asan) holds prebuilt .so only
        if (not os.path.exists(_SO_PATH)
                and os.path.exists(os.path.join(_NATIVE_DIR, "Makefile"))):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s"],
                               check=True, capture_output=True, timeout=120)
            except Exception:
                return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.mq_vocab_new.restype = ctypes.c_void_p
        lib.mq_vocab_free.argtypes = [ctypes.c_void_p]
        lib.mq_vocab_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_int32]
        lib.mq_vocab_size.argtypes = [ctypes.c_void_p]
        lib.mq_vocab_size.restype = ctypes.c_int64
        lib.mq_tokenize.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64), ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.uint8)]
        lib.mq_tokenize_joined.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.int32),
            np.ctypeslib.ndpointer(np.uint8)]
        lib.mq_scan_frames.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64),
            np.ctypeslib.ndpointer(np.int64),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int64)]
        lib.mq_scan_frames.restype = ctypes.c_int64
        lib.mq_tokenize_sig.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.uint32),
            np.ctypeslib.ndpointer(np.uint32),
            np.ctypeslib.ndpointer(np.uint8), ctypes.c_int64,
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.int8),
            np.ctypeslib.ndpointer(np.uint32)]
        lib.mq_probe_new.restype = ctypes.c_void_p
        lib.mq_probe_free.argtypes = [ctypes.c_void_p]
        lib.mq_probe_add_group.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint8,
            ctypes.c_uint32,
            np.ctypeslib.ndpointer(np.uint32),
            np.ctypeslib.ndpointer(np.uint32),
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int64]
        lib.mq_probe_run.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            np.ctypeslib.ndpointer(np.int8), ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64),
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int64,
            ctypes.c_int32]
        lib.mq_probe_run.restype = ctypes.c_int64
        lib.mq_probe_set_ge.argtypes = [ctypes.c_void_p]
        lib.mq_tokenize_probe.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.int8),
            np.ctypeslib.ndpointer(np.int64),
            np.ctypeslib.ndpointer(np.int32), ctypes.c_int64]
        lib.mq_tokenize_probe.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return _try_load() is not None


_decode_mod = None
_decode_attempted = False


def chain_params_in_effect(mod) -> tuple:
    """The decode extension's live (min_base, tail_num, tail_den) — the
    value A/B harnesses and test finally blocks must restore VERBATIM
    (restoring hardcoded defaults silently changes global decode
    behavior if the native defaults drift). Falls back to the
    historical defaults only when the loaded extension predates the
    ``_get_chain_params`` getter."""
    getter = getattr(mod, "_get_chain_params", None)
    return getter() if getter is not None else (64, 1, 1)


def decode_module(build: bool = True):
    """The maxmq_decode CPython extension (candidate verify + subscriber
    union in C; see native/maxmq_decode.cpp), or None. A separate .so
    from the ctypes runtime because its hot loop builds Python objects —
    that needs the C API, not a C ABI.

    ``build=False`` only loads an already-built .so (import-time callers
    must not block on a compile); the device match path passes the
    default and compiles on demand."""
    global _decode_mod, _decode_attempted
    with _load_lock:
        if _decode_attempted:
            return _decode_mod
        if os.environ.get("MAXMQ_NO_NATIVE"):
            _decode_attempted = True
            return None
        path = os.path.join(_NATIVE_DIR, "maxmq_decode.so")
        if not os.path.exists(path):
            if (not build or not os.path.exists(
                    os.path.join(_NATIVE_DIR, "Makefile"))):
                return None            # stay retriable for build=True
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-s",
                                "maxmq_decode.so"],
                               check=True, capture_output=True, timeout=120)
            except Exception:
                _decode_attempted = True
                return None
        _decode_attempted = True
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "maxmq_decode", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _decode_mod = mod
        except Exception:
            _decode_mod = None
        return _decode_mod


class NativeVocab:
    """C++ mirror of a matcher vocabulary dict (level string -> token id).
    Built once per table refresh; reads are lock-free in C++."""

    def __init__(self, vocab: dict[str, int]) -> None:
        lib = _try_load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.mq_vocab_new())
        for level, tok in vocab.items():
            raw = level.encode("utf-8")
            lib.mq_vocab_add(self._handle, raw, len(raw), tok)

    def __len__(self) -> int:
        return int(self._lib.mq_vocab_size(self._handle))

    def __del__(self):
        handle, self._handle = getattr(self, "_handle", None), None
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.mq_vocab_free(handle)

    def tokenize(self, topics: list[str], max_levels: int):
        """Same contract as matching/topics.py:tokenize_topics. Topics are
        shipped as ONE NUL-joined utf-8 buffer (U+0000 can't appear in an
        MQTT topic name [MQTT-1.5.4-2]) and split in C."""
        n = len(topics)
        buf = "\x00".join(topics).encode("utf-8")
        toks = np.empty((n, max_levels), dtype=np.int32)
        lengths = np.empty(n, dtype=np.int32)
        dollar = np.empty(n, dtype=np.uint8)
        self._lib.mq_tokenize_joined(self._handle, buf, len(buf), n,
                                     max_levels, toks, lengths, dollar)
        return toks, lengths, dollar.astype(bool)


class ExactSigTable:
    """Host-exact coefficient tables marshalled once per compiled-table
    snapshot for mq_tokenize_sig (depth -> per-position multipliers)."""

    def __init__(self, host_exact: dict) -> None:
        max_d = max(host_exact.keys(), default=0)
        self.max_d = max_d
        self.coef = np.zeros((max_d + 1, max(max_d, 1)), dtype=np.uint32)
        self.dc = np.zeros(max_d + 1, dtype=np.uint32)
        self.present = np.zeros(max_d + 1, dtype=np.uint8)
        for d, g in host_exact.items():
            spec = g.spec
            for c, pos in zip(spec.coef, spec.kept):
                self.coef[d, pos] = c
            self.dc[d] = spec.depth_coef
            self.present[d] = 1


def tokenize_sig(vocab: "NativeVocab", topics: list[str], window: int,
                 tok_dtype, exact: ExactSigTable):
    """One-pass compact tokenizer + host-exact signature (C++). Returns
    (toks [n, window] of tok_dtype, lens_enc int8[n], esig uint32[n]) per
    maxmq_tpu/matching/sig.py:tokenize_compact's encoding contract."""
    lib = vocab._lib
    n = len(topics)
    buf = "\x00".join(topics).encode("utf-8")
    toks = np.empty((n, window), dtype=tok_dtype)
    lens = np.empty(n, dtype=np.int8)
    esig = np.empty(n, dtype=np.uint32)
    mode = {np.uint8: 1, np.uint16: 2, np.int32: 4}[tok_dtype]
    lib.mq_tokenize_sig(vocab._handle, buf, len(buf), n, window, mode,
                        exact.coef, exact.dc, exact.present,
                        exact.coef.shape[1] if exact.max_d else 0,
                        toks.ctypes.data_as(ctypes.c_void_p), lens, esig)
    return toks, lens, esig


class NativeProbe:
    """C++ host probe over every exact-shape group (full-literal +
    '+'-shape): one hashed signature + binary search per (topic, group
    of the topic's depth), threaded over topic ranges. Built once per
    compiled-table snapshot from tables.host_exact / tables.host_plus."""

    def __init__(self, host_exact: dict, host_plus: dict,
                 ge_depth: bool = False) -> None:
        lib = _try_load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.mq_probe_new())
        for d, g in (host_exact or {}).items():
            coef = np.zeros(max(d, 1), dtype=np.uint32)
            for c, pos in zip(g.spec.coef, g.spec.kept):
                coef[pos] = c
            with np.errstate(over="ignore"):
                dc = int(np.uint32(g.spec.depth_coef) * np.uint32(d))
            lib.mq_probe_add_group(
                self._handle, d, 0, dc, coef,
                np.ascontiguousarray(g.sigs, dtype=np.uint32),
                np.ascontiguousarray(g.rows, dtype=np.int32), len(g.sigs))
        for d, p in (host_plus or {}).items():
            for k in range(len(p.sigs)):
                lib.mq_probe_add_group(
                    self._handle, d, int(bool(p.wildf[k])), int(p.dc[k]),
                    np.ascontiguousarray(p.coef[k], dtype=np.uint32),
                    np.ascontiguousarray(p.sigs[k], dtype=np.uint32),
                    np.ascontiguousarray(p.rows[k], dtype=np.int32),
                    len(p.sigs[k]))
        if ge_depth:
            # '#'-prefix semantics: groups apply to topics of depth >=
            # their prefix depth (pass tables.host_hash as host_plus —
            # same probe layout, dc=0). Must follow every add_group.
            lib.mq_probe_set_ge(self._handle)

    def __del__(self):
        handle, self._handle = getattr(self, "_handle", None), None
        if handle and getattr(self, "_lib", None) is not None:
            self._lib.mq_probe_free(handle)

    def run(self, toks: np.ndarray, lens_enc: np.ndarray
            ) -> tuple[np.ndarray, np.ndarray]:
        """(topic ids int64[M], row ids int32[M]) hit pairs, topic-sorted.
        ``toks`` is the narrow [n, window] token matrix of any of the
        compact dtypes."""
        n, window = toks.shape
        mode = {1: 1, 2: 2, 4: 4}[toks.dtype.itemsize]
        cap = max(4 * n, 1024)
        while True:
            ti = np.empty(cap, dtype=np.int64)
            rw = np.empty(cap, dtype=np.int32)
            total = self._lib.mq_probe_run(
                self._handle, toks.ctypes.data_as(ctypes.c_void_p), mode,
                lens_enc, n, window, ti, rw, cap, 0)
            if total <= cap:
                return ti[:total], rw[:total]
            cap = int(total)


def tokenize_probe(vocab: "NativeVocab", probe: "NativeProbe",
                   topics: list[str], window: int, tok_dtype):
    """Fused single-pass tokenize + host probe (C++): returns
    (toks [n, window] of tok_dtype, lens_enc int8[n], ti int64[M],
    rows int32[M]) — hit pairs topic-sorted. One pass over the topic
    bytes with the level tokens still in registers at probe time."""
    lib = vocab._lib
    n = len(topics)
    buf = "\x00".join(topics).encode("utf-8")
    toks = np.empty((n, window), dtype=tok_dtype)
    lens = np.empty(n, dtype=np.int8)
    mode = {np.uint8: 1, np.uint16: 2, np.int32: 4}[tok_dtype]
    cap = max(4 * n, 1024)
    while True:
        ti = np.empty(cap, dtype=np.int64)
        rw = np.empty(cap, dtype=np.int32)
        total = lib.mq_tokenize_probe(
            vocab._handle, probe._handle, buf, len(buf), n, window, mode,
            toks.ctypes.data_as(ctypes.c_void_p), lens, ti, rw, cap)
        if total <= cap:
            return toks, lens, ti[:total], rw[:total]
        cap = int(total)


class MalformedFrame(ValueError):
    """The buffer contains an invalid fixed header (reserved type 0 or a
    variable-byte integer longer than 4 bytes, MQTT-1.5.5)."""


def scan_frames(data: bytes, max_frames: int = 4096
                ) -> tuple[list[tuple[int, int]], int]:
    """Scan ``data`` for complete MQTT frames.

    Returns ``(frames, consumed)`` where frames is a list of (start, end)
    byte ranges and consumed is the offset scanning stopped at (start of the
    first incomplete frame — the caller keeps ``data[consumed:]`` for the
    next read). Raises MalformedFrame on an invalid header.
    """
    lib = _try_load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    starts = np.empty(max_frames, dtype=np.int64)
    totals = np.empty(max_frames, dtype=np.int64)
    consumed = ctypes.c_int64(0)
    n = lib.mq_scan_frames(data, len(data), starts, totals, max_frames,
                           ctypes.byref(consumed))
    if n < 0:
        raise MalformedFrame(f"invalid fixed header at offset {consumed.value}")
    return ([(int(starts[i]), int(starts[i] + totals[i])) for i in range(n)],
            int(consumed.value))


def scan_frames_py(data: bytes, max_frames: int = 4096
                   ) -> tuple[list[tuple[int, int]], int]:
    """Pure-Python reference for scan_frames (also the fallback)."""
    frames: list[tuple[int, int]] = []
    pos = 0
    while pos < len(data) and len(frames) < max_frames:
        if (data[pos] >> 4) == 0:
            raise MalformedFrame(f"invalid fixed header at offset {pos}")
        rem = 0
        shift = 0
        vpos = pos + 1
        complete = False
        while vpos < len(data):
            b = data[vpos]
            vpos += 1
            rem |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                complete = True
                break
            if shift > 21:
                raise MalformedFrame(
                    f"invalid fixed header at offset {pos}")
        if not complete:
            break
        total = (vpos - pos) + rem
        if pos + total > len(data):
            break
        frames.append((pos, pos + total))
        pos += total
    return frames, pos
