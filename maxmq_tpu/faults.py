"""Deterministic fault injection for the matcher degradation ladder.

The ladder (ADR 011) only earns trust if every rung can be exercised on
demand: a device call that raises, a kernel that hangs past the batch
deadline, a recompile that fails, a matcher-service socket that drops,
a pool worker that dies. This registry arms those faults at well-known
*sites* in the production code; the sites themselves cost one dict
lookup on an (almost always) empty dict when nothing is armed.

Arming is deterministic and counted: ``arm(site, mode, count)`` fires
the fault for exactly the next ``count`` hits of that site (``count=-1``
= until disarmed), then self-disarms, so a test (or a degraded-mode
bench run) can script "fail the next 3 device batches, then recover"
with no sleeps or races. ``fired`` records how many times each site
actually tripped.

Modes:

* ``raise`` — the site raises :class:`InjectedFault` (a
  :class:`DeviceMatchError`): the supervisor classifies it as
  reason="error" and answers from the CPU trie.
* ``hang``  — the site blocks for ``delay_s`` seconds (in whatever
  thread runs the device call), driving the supervisor's per-batch
  deadline instead of its exception path.
* anything else (``drop``, ``exit``, ...) — ``fire`` returns True and
  the SITE acts: the matcher service closes the client connection, a
  pool worker stops itself. This keeps process-structure faults out of
  the registry's hands — it only ever raises or sleeps.

Env arming (``MAXMQ_FAULTS``) lets ``bench.py`` and subprocess pool
workers arm faults they can't reach by reference::

    MAXMQ_FAULTS="device.match:raise:3,device.match:hang:1:0.5"

parses as ``site:mode[:count[:delay_s[:skip]]]``, comma-separated,
applied in order (later entries queue behind earlier ones for the same
site). ``skip`` lets an env-armed fault pass its first N hits before
firing — the crash-day harness (ADR 024) needs "SIGKILL at the 7th
group commit", and the first commits happen at boot (boot_epoch
flush), long before the traffic under test. Because each subprocess
re-parses the env at import, the pool parent delivers ``pool.worker``
entries to exactly ONE initial worker spawn and strips them everywhere
else (broker/workers.py) — a worker-kill drill means one death, not a
pool-wide crash loop.
"""

from __future__ import annotations

import os
import signal
import threading
import time
import zlib


class DeviceMatchError(RuntimeError):
    """The device matcher path failed (kernel launch, runtime error, or
    injected fault). The supervisor (matching/supervisor.py) catches any
    Exception, but sites that can classify their failures raise this so
    logs and post-mortems separate device faults from host bugs."""


class InjectedFault(DeviceMatchError):
    """Raised by an armed ``raise``-mode fault site."""


# canonical sites (the production code fires these; tests arm them)
DEVICE_MATCH = "device.match"          # engine device-batch entry points
DEVICE_RECOMPILE = "device.recompile"  # engine refresh()/table compile
SERVICE_SOCKET = "service.socket"      # matcher-service client connection
POOL_WORKER = "pool.worker"            # delivery-pool worker process
CLIENT_WRITE = "client.write"          # broker client writer loop (ADR 012)
LISTENER_ACCEPT = "listener.accept"    # broker connection accept (ADR 012)
CLUSTER_LINK = "cluster.link"          # bridge link connect/pump (ADR 013)
CLUSTER_PARTITION = "cluster.partition"  # directed inter-node network
                                       # partition (ADR 018; keyed per
                                       # link direction "src->dst")
CLUSTER_SHAPE = "cluster.shape"        # directed inter-node WAN link
                                       # shape (ADR 022; keyed per link
                                       # direction "src->dst": delay/
                                       # jitter/rate/loss, not binary)
CLUSTER_ROUTE_APPLY = "cluster.route_apply"  # route snapshot/delta apply
CLUSTER_SESSION_SYNC = "cluster.session_sync"  # session replication send/
                                       # apply (ADR 016; keyed per peer)
CLUSTER_TAKEOVER = "cluster.takeover"  # CONNECT takeover/state handoff
                                       # (ADR 016; keyed per prior owner)
STORAGE_PUT = "storage.put"            # journal enqueue boundary (ADR 014)
STORAGE_COMMIT = "storage.commit"      # journal writer-thread group commit
STORAGE_RESTORE = "storage.restore"    # per-record boot restore parse
NATIVE_ENCODE = "native.encode"        # C publish-frame head assembly
                                       # (ADR 019; trips fall back to the
                                       # pure-Python encoder)
FILTER_EVAL = "filter.eval"            # content-plane batch evaluation
                                       # (ADR 023; trips fail OPEN: the
                                       # flush delivers unfiltered)
FILTER_WINDOW = "filter.window"        # aggregate window emission (ADR
                                       # 023; trips shed that emission,
                                       # counted in agg_shed)
DISK_WRITE = "disk.write"              # backend write/commit path: an
                                       # armed trip surfaces as EIO from
                                       # the store (ADR 024)
DISK_ENOSPC = "disk.enospc"            # backend commit: disk full
                                       # (ENOSPC) from the store
DISK_FSYNC = "disk.fsync"              # backend commit: write landed,
                                       # fsync FAILED — dirty-page state
                                       # unknown (fsyncgate; the journal
                                       # must poison + reopen + replay)
DISK_LATENCY = "disk.latency"          # backend commit latency (hang
                                       # mode sleeps the WRITER thread)
CRASH_AT = "crash.at"                  # named kill points (ADR 024);
                                       # keyed per point: crash.at#<p>
                                       # mode "kill" SIGKILLs the
                                       # PROCESS — subprocess drills only

# The crash-point registry (ADR 024): every named point a subprocess
# broker can be told to SIGKILL itself at, placed at the exact commit-
# pipeline instants whose before/after durability semantics differ.
# Armed via MAXMQ_FAULTS, e.g. "crash.at#pre_fsync:kill:1:0:6" = die at
# the 7th commit attempt (skip 6).
CRASH_POINTS = (
    "pre_fsync",            # journal writer: batch taken, backend NOT
                            # yet committed (acked-under-`batched` data
                            # in this window is the documented loss)
    "post_fsync_pre_ack",   # journal writer: backend committed, ack
                            # barriers NOT yet released (`always` must
                            # redeliver, never lose)
    "mid_wal_write",        # SQLite apply_batch: half the batch's ops
                            # executed, transaction open (the WAL tears)
    "restore_parse",        # boot restore: mid-bucket parse (a crash
                            # DURING recovery must not corrupt anew)
    "replica_flush",        # cluster/sessions.py: replication drain
                            # scheduled but not yet on the wire
)


class _Spec:
    __slots__ = ("mode", "remaining", "delay_s", "skip")

    def __init__(self, mode: str, remaining: int, delay_s: float,
                 skip: int = 0) -> None:
        self.mode = mode
        self.remaining = remaining
        self.delay_s = delay_s
        self.skip = skip


class ShapeSpec:
    """One directed link's WAN shape (ADR 022): fixed one-way delay,
    uniform jitter, a token-bucket rate limit, and probabilistic loss.

    Everything here is pure integer-ns arithmetic over clocks the CALL
    SITE reads (through ``REGISTRY.clock_ns``), and the only randomness
    is a private xorshift64* stream seeded from the link key — so a
    scripted-clock test replays the exact same jitter/loss sequence
    every run. The spec never sleeps; :meth:`depart_ns` answers "when
    may this item hit the far end", and the bridge's deferral queue
    does the (non-blocking) waiting.

    Reorder preservation: a jitter draw that would land an item before
    its predecessor is clamped to the predecessor's departure — a
    shaped link is a slow FIFO pipe, never a packet shuffler (the blip
    audit's FIFO claim, ADR 020, must keep holding on shaped links).
    """

    __slots__ = ("delay_ns", "jitter_ns", "rate_bps", "loss",
                 "burst_bytes", "deferrals", "losses", "_rng",
                 "_last_depart_ns", "_tokens", "_tb_stamp_ns")

    def __init__(self, delay_ms: float = 0.0, jitter_ms: float = 0.0,
                 rate_bps: int = 0, loss: float = 0.0,
                 burst_bytes: int = 16384, seed: int = 0) -> None:
        if delay_ms < 0 or jitter_ms < 0 or rate_bps < 0 \
                or not 0.0 <= loss <= 1.0:
            raise ValueError("bad shape (want delay_ms/jitter_ms/"
                             "rate_bps >= 0, 0 <= loss <= 1)")
        self.delay_ns = int(delay_ms * 1e6)
        self.jitter_ns = int(jitter_ms * 1e6)
        self.rate_bps = int(rate_bps)
        self.loss = float(loss)
        self.burst_bytes = max(int(burst_bytes), 1)
        self.deferrals = 0          # items that actually waited
        self.losses = 0             # items the loss draw ate
        self._rng = (seed & 0xFFFFFFFFFFFFFFFF) or 0x9E3779B97F4A7C15
        self._last_depart_ns = 0    # FIFO fence (reorder preservation)
        self._tokens: float | None = None   # bucket starts full
        self._tb_stamp_ns = 0

    # -- deterministic randomness --------------------------------------

    def rand(self) -> float:
        """Next [0, 1) draw from the spec's private xorshift64* stream
        (no ``random`` module state: two shaped links never perturb
        each other's sequences, and a fixed seed replays exactly)."""
        x = self._rng
        x ^= (x << 13) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 7
        x ^= (x << 17) & 0xFFFFFFFFFFFFFFFF
        self._rng = x
        return ((x * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF) \
            / float(1 << 64)

    def lose(self) -> bool:
        """One loss draw; counted."""
        if self.loss <= 0.0:
            return False
        if self.rand() >= self.loss:
            return False
        self.losses += 1
        return True

    # -- timing math (all ns, caller supplies now) ---------------------

    def _rate_wait_ns(self, now_ns: int, nbytes: int) -> int:
        """Token bucket: ``burst_bytes`` of credit refilled at
        ``rate_bps``; a send overdraws the bucket and the debt converts
        to wait time — burst passes at line rate, sustained traffic
        paces to the configured bandwidth."""
        if not self.rate_bps:
            return 0
        per_ns = self.rate_bps / 8 / 1e9        # bytes per ns
        if self._tokens is None:
            self._tokens = float(self.burst_bytes)
        else:
            self._tokens = min(
                float(self.burst_bytes),
                self._tokens + (now_ns - self._tb_stamp_ns) * per_ns)
        self._tb_stamp_ns = now_ns
        self._tokens -= nbytes
        if self._tokens >= 0:
            return 0
        return int(-self._tokens / per_ns)

    def depart_ns(self, now_ns: int, nbytes: int) -> int:
        """The instant this item may be released to the wire: now +
        delay + jitter draw + token-bucket wait, clamped to never
        precede the previous item's departure (FIFO)."""
        t = now_ns + self.delay_ns
        if self.jitter_ns:
            t += int(self.rand() * self.jitter_ns)
        t += self._rate_wait_ns(now_ns, nbytes)
        if t < self._last_depart_ns:
            t = self._last_depart_ns
        self._last_depart_ns = t
        if t > now_ns:
            self.deferrals += 1
        return t

    @property
    def oneway_s(self) -> float:
        """Expected one-way propagation (delay + mean jitter), seconds
        — the liveness sites' sleep when emulating a ping round trip."""
        return (self.delay_ns + self.jitter_ns / 2) / 1e9


def _sigkill_self() -> None:
    os.kill(os.getpid(), signal.SIGKILL)


class FaultRegistry:
    """Thread-safe armed-fault table. One global instance (``REGISTRY``)
    serves the whole process; tests that want isolation construct their
    own and pass it to the code under test where supported."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # site -> FIFO of specs (so "raise twice then hang once" scripts)
        self._specs: dict[str, list[_Spec]] = {}
        # directed link key "src->dst" -> ShapeSpec (ADR 022); separate
        # from _specs because a shape is continuous state (bucket fill,
        # FIFO fence, PRNG stream), not a countdown of discrete trips
        self._shapes: dict[str, ShapeSpec] = {}
        self.fired: dict[str, int] = {}
        # swappable monotonic-ns clock (ADR 015): the pipeline tracer
        # reads every span timestamp through this indirection, so a
        # test can install a scripted clock and get deterministic
        # spans; restore with reset_clock()
        self.clock_ns = time.monotonic_ns
        # swappable kill action (ADR 024): crash_point() delivers the
        # SIGKILL through this indirection so an in-process test can
        # observe the trip without dying with the subprocess drills
        self.kill_fn = _sigkill_self

    def reset_clock(self) -> None:
        self.clock_ns = time.monotonic_ns

    # -- arming --------------------------------------------------------

    def arm(self, site: str, mode: str = "raise", count: int = 1,
            delay_s: float = 0.05, skip: int = 0) -> None:
        if count == 0:
            return
        with self._lock:
            self._specs.setdefault(site, []).append(
                _Spec(mode, count, delay_s, max(int(skip), 0)))

    def disarm(self, site: str) -> None:
        with self._lock:
            self._specs.pop(site, None)

    def clear(self) -> None:
        with self._lock:
            self._specs.clear()
            self._shapes.clear()
            self.fired.clear()

    def armed(self, site: str) -> bool:
        return site in self._specs

    # -- WAN link shapes (ADR 022) -------------------------------------

    def set_shape(self, key: str, spec: ShapeSpec) -> None:
        with self._lock:
            self._shapes[key] = spec

    def get_shape(self, key: str) -> ShapeSpec | None:
        """Racy-but-safe hot-path lookup (one dict get on an almost
        always empty dict), mirroring the ``fire`` fast path."""
        if not self._shapes:
            return None
        return self._shapes.get(key)

    def del_shape(self, key: str) -> None:
        with self._lock:
            self._shapes.pop(key, None)

    def any_shaped(self) -> bool:
        return bool(self._shapes)

    def count_fired(self, site_key: str) -> None:
        """Count one shape action under ``fired`` so harness phase
        records see shaping activity next to partition trips."""
        self.fired[site_key] = self.fired.get(site_key, 0) + 1

    def any_armed(self) -> bool:
        """True when ANY site is armed — the cheap hot-path guard loop
        code uses before paying a keyed fire_detail lookup (broker
        writer loop: one call per written packet when idle)."""
        return bool(self._specs)

    def arm_from_spec(self, spec: str) -> None:
        """Parse a ``MAXMQ_FAULTS``-style csv and arm each entry."""
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) < 2:
                raise ValueError(f"bad fault spec {entry!r} (want "
                                 "site:mode[:count[:delay_s[:skip]]])")
            site, mode = parts[0], parts[1]
            count = int(parts[2]) if len(parts) > 2 else 1
            delay = float(parts[3]) if len(parts) > 3 else 0.05
            skip = int(parts[4]) if len(parts) > 4 else 0
            self.arm(site, mode, count, delay, skip)

    # -- firing (the production-code side) -----------------------------

    def _take(self, site: str) -> _Spec | None:
        """Pop (and count) the next armed spec for ``site``, or None."""
        if site not in self._specs:       # racy-but-safe fast path
            return None
        with self._lock:
            queue = self._specs.get(site)
            if not queue:
                return None
            spec = queue[0]
            if spec.skip > 0:
                # a pass-through hit: the site proceeds untouched and
                # the spec moves one step closer to firing (uncounted —
                # `fired` records trips, not near-misses)
                spec.skip -= 1
                return None
            if spec.remaining > 0:
                spec.remaining -= 1
                if spec.remaining == 0:
                    queue.pop(0)
                    if not queue:
                        del self._specs[site]
            self.fired[site] = self.fired.get(site, 0) + 1
        return spec

    def fire(self, site: str) -> bool:
        """Trip ``site`` if armed. ``raise`` mode raises InjectedFault,
        ``hang`` sleeps ``delay_s`` then returns True; any other mode
        returns True and the call site acts. Returns False when the site
        is not armed (the hot-path common case: one dict membership test
        on an empty dict)."""
        spec = self._take(site)
        if spec is None:
            return False
        if spec.mode == "raise":
            raise InjectedFault(f"injected fault at {site}")
        if spec.mode == "hang":
            time.sleep(spec.delay_s)
        return True

    def fire_detail(self, site: str,
                    key: str | None = None) -> tuple[str, float] | None:
        """Keyed, async-friendly firing for loop-thread sites (ADR 012).

        Tries the instance-scoped arming ``site#key`` first (e.g.
        ``client.write#slow-sub`` stalls ONE client's writer), then the
        plain site. ``raise`` mode raises as :meth:`fire` does; every
        other mode returns ``(mode, delay_s)`` and the CALL SITE acts —
        an asyncio call site must ``await asyncio.sleep(delay_s)`` for
        ``hang`` rather than let the registry block the event loop."""
        spec = self._take(f"{site}#{key}") if key else None
        if spec is None:
            spec = self._take(site)
        if spec is None:
            return None
        if spec.mode == "raise":
            raise InjectedFault(f"injected fault at {site}")
        return spec.mode, spec.delay_s


REGISTRY = FaultRegistry()


# ----------------------------------------------------------------------
# Network partitions (ADR 018): the ``cluster.partition`` site family
# ----------------------------------------------------------------------
#
# The site is keyed per DIRECTED link: ``cluster.partition#A->B``
# affects traffic traveling from node A to node B only. The production
# code fires it at every place bytes cross a node boundary — bridge
# connect, bridge keepalive ping, the bridge writer loop (per wire
# item), and the receiving broker's ``$cluster/*`` inbound dispatch —
# so an armed direction behaves like a blackholed network path: sends
# vanish in flight, pings fail (the link is detected down and enters
# reconnect backoff), reconnects fail until healed. Modes:
#
# * ``drop`` — bytes in the armed direction silently vanish; QoS1
#   bridge traffic times out unacked and (ADR 018) parks for
#   retry-after-heal.
# * ``hang`` — bytes are delayed by ``delay_s`` (latency injection);
#   everything still arrives.
#
# ``partition(a, b)`` arms BOTH directions (a full split);
# ``partition(a, b, mode="asym")`` arms only a->b (asymmetric loss:
# a's traffic to b vanishes while b still reaches a). ``heal(a, b)``
# disarms both directions. Arms are count=-1 (until healed).


def partition_key(src: str, dst: str) -> str:
    return f"{src}->{dst}"


def partition(a: str, b: str, mode: str = "drop",
              delay_s: float = 0.05) -> None:
    """Arm a network partition between nodes ``a`` and ``b`` (ADR 018).

    ``mode="drop"``/``"hang"`` arm both directions; ``mode="asym"``
    arms a->b only (drop). Stays armed until :func:`heal`."""
    if mode == "asym":
        dirs, armed_mode = [(a, b)], "drop"
    elif mode in ("drop", "hang"):
        dirs, armed_mode = [(a, b), (b, a)], mode
    else:
        raise ValueError(f"unknown partition mode {mode!r} "
                         "(want drop/hang/asym)")
    for src, dst in dirs:
        REGISTRY.arm(f"{CLUSTER_PARTITION}#{partition_key(src, dst)}",
                     armed_mode, -1, delay_s)


def heal(a: str, b: str) -> None:
    """Disarm a partition between ``a`` and ``b`` (both directions)."""
    for src, dst in ((a, b), (b, a)):
        REGISTRY.disarm(f"{CLUSTER_PARTITION}#{partition_key(src, dst)}")


# ----------------------------------------------------------------------
# WAN link shaping (ADR 022): the ``cluster.shape`` site family
# ----------------------------------------------------------------------
#
# Like ``cluster.partition`` the site is keyed per DIRECTED link
# (``cluster.shape#A->B``), but a shape is continuous degradation, not
# a binary fault: one-way delay, jitter, a token-bucket rate limit,
# and probabilistic loss. The production code consults it at the same
# three boundaries the partition plumbing hooks, with the aspects
# split so the in-process harness (one registry serving both ends of
# every link) never double-applies a direction:
#
# * bridge connect / keepalive (liveness, sender side) — the emulated
#   ping round trip sleeps both directions' one-way delay and a loss
#   draw fails the probe, so liveness sees the WAN the data sees;
# * the bridge writer (data, sender side) — delay + jitter + rate,
#   via a non-blocking reorder-preserving deferral queue;
# * the receiving broker's ``$cluster`` inbound (data, receiver side)
#   — the loss draw: a dropped message is in-flight loss (no ack, no
#   apply), which is what arms the ADR-020 blip audit + parked-retry
#   machinery rather than a link flap.
#
# ``shape(a, b, ...)`` arms ONE direction (asymmetric bandwidth is the
# point of per-direction arming); ``unshape(a, b)`` clears both.


def shape(a: str, b: str, *, delay_ms: float = 0.0,
          jitter_ms: float = 0.0, rate_bps: int = 0, loss: float = 0.0,
          burst_bytes: int = 16384, seed: int | None = None) -> ShapeSpec:
    """Arm the directed WAN shape ``a -> b`` (ADR 022) and return its
    spec. The PRNG seed defaults to a CRC of the link key — stable
    across runs, distinct per direction."""
    key = partition_key(a, b)
    if seed is None:
        seed = zlib.crc32(key.encode())
    spec = ShapeSpec(delay_ms=delay_ms, jitter_ms=jitter_ms,
                     rate_bps=rate_bps, loss=loss,
                     burst_bytes=burst_bytes, seed=seed)
    REGISTRY.set_shape(key, spec)
    return spec


def unshape(a: str, b: str) -> None:
    """Disarm the WAN shape between ``a`` and ``b`` (both directions)."""
    for src, dst in ((a, b), (b, a)):
        REGISTRY.del_shape(partition_key(src, dst))


# ----------------------------------------------------------------------
# Crash points (ADR 024): the ``crash.at`` site family
# ----------------------------------------------------------------------
#
# A crash point is a named instant in the commit pipeline (CRASH_POINTS
# above) where a broker told to die, dies NOW — SIGKILL to self, no
# atexit, no flush, exactly what a power cut at that instant leaves
# behind. The production code calls ``crash_point("<name>")`` at each
# site; the cost when nothing is armed is the usual one-dict-membership
# fast path. Arming rides MAXMQ_FAULTS with the keyed-site convention
# (``crash.at#pre_fsync:kill:1:0:<skip>``) so the crash-day harness's
# subprocess brokers inherit their death sentence through env.
#
# Mode ``kill`` (or ``raise``/anything — a crash point only crashes)
# fires the registry's ``kill_fn``; tests that must observe the trip
# in-process swap ``REGISTRY.kill_fn`` first.


def crash_point(point: str) -> None:
    """Die here if this named crash point is armed (ADR 024)."""
    site = f"{CRASH_AT}#{point}"
    if site not in REGISTRY._specs:     # racy-but-safe fast path
        return
    spec = REGISTRY._take(site)
    if spec is not None:
        REGISTRY.kill_fn()


# module-level conveniences bound to the process registry
arm = REGISTRY.arm
disarm = REGISTRY.disarm
clear = REGISTRY.clear
armed = REGISTRY.armed
any_armed = REGISTRY.any_armed
fire = REGISTRY.fire
fire_detail = REGISTRY.fire_detail
arm_from_spec = REGISTRY.arm_from_spec
get_shape = REGISTRY.get_shape
any_shaped = REGISTRY.any_shaped
fired = REGISTRY.fired

# env arming: subprocess pool workers and bench's degraded-mode runs
# inherit MAXMQ_FAULTS through their environment
_env_spec = os.environ.get("MAXMQ_FAULTS", "")
if _env_spec:
    REGISTRY.arm_from_spec(_env_spec)
