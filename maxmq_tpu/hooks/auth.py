"""Authentication hooks: permit-all and a rule-ledger hook.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/hooks/auth/ in the
reference (AllowHook, Ledger with auth rules + ACL filters).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .base import Hook


class AllowHook(Hook):
    """Permit every connection and every ACL check."""

    id = "allow-all-auth"

    def on_connect_authenticate(self, client, packet) -> bool:
        return True

    def on_acl_check(self, client, topic: str, write: bool) -> bool:
        return True


def _match_rule_value(rule_value: str, actual: str) -> bool:
    """Ledger matching: empty matches anything; trailing '*' is a prefix
    wildcard; otherwise exact."""
    if rule_value == "":
        return True
    if rule_value.endswith("*"):
        return actual.startswith(rule_value[:-1])
    return rule_value == actual


@dataclass
class AuthRule:
    username: str = ""
    password: str = ""
    remote: str = ""
    client_id: str = ""
    allow: bool = True

    def matches(self, username: str, password: str, remote: str,
                client_id: str) -> bool:
        return (_match_rule_value(self.username, username)
                and _match_rule_value(self.remote, remote)
                and _match_rule_value(self.client_id, client_id)
                and (self.password == "" or self.password == password))


@dataclass
class ACLRule:
    username: str = ""
    remote: str = ""
    client_id: str = ""
    # filter -> access: "deny" | "read" | "write" | "readwrite"
    filters: dict[str, str] = field(default_factory=dict)

    def check(self, username: str, remote: str, client_id: str, topic: str,
              write: bool) -> bool | None:
        """None = rule does not apply; True/False = allow/deny."""
        if not (_match_rule_value(self.username, username)
                and _match_rule_value(self.remote, remote)
                and _match_rule_value(self.client_id, client_id)):
            return None
        for filt, access in self.filters.items():
            if _filter_covers(filt, topic):
                if access == "deny":
                    return False
                if access == "readwrite":
                    return True
                return access == ("write" if write else "read")
        return None


def _filter_covers(filter_: str, topic: str) -> bool:
    """Does an ACL filter (with MQTT wildcards) cover a concrete topic?"""
    flevels = filter_.split("/")
    tlevels = topic.split("/")
    for i, fl in enumerate(flevels):
        if fl == "#":
            return True
        if i >= len(tlevels):
            return False
        if fl != "+" and fl != tlevels[i]:
            return False
    return len(flevels) == len(tlevels)


@dataclass
class Ledger:
    auth: list[AuthRule] = field(default_factory=list)
    acl: list[ACLRule] = field(default_factory=list)

    @classmethod
    def from_dict(cls, data: dict) -> "Ledger":
        ledger = cls()
        for r in data.get("auth", []):
            ledger.auth.append(AuthRule(**r))
        for r in data.get("acl", []):
            ledger.acl.append(ACLRule(**r))
        return ledger

    @classmethod
    def from_json(cls, text: str) -> "Ledger":
        import json
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_yaml(cls, text: str) -> "Ledger":
        import yaml
        return cls.from_dict(yaml.safe_load(text) or {})

    @classmethod
    def from_file(cls, path: str) -> "Ledger":
        """Load rules from a .json or .yaml/.yml file (the reference's
        ledger is YAML/JSON loadable, hooks/auth/ledger.go)."""
        with open(path, encoding="utf-8") as f:
            text = f.read()
        if path.endswith((".yaml", ".yml")):
            return cls.from_yaml(text)
        return cls.from_json(text)


class LedgerHook(Hook):
    """Rule-based authentication + topic ACLs."""

    id = "ledger-auth"

    def __init__(self, ledger: Ledger) -> None:
        self.ledger = ledger

    def on_connect_authenticate(self, client, packet) -> bool:
        username = packet.username.decode("utf-8", "replace")
        password = packet.password.decode("utf-8", "replace")
        for rule in self.ledger.auth:
            if rule.matches(username, password, client.remote, client.id):
                return rule.allow
        return False

    def on_acl_check(self, client, topic: str, write: bool) -> bool:
        username = client.properties.username.decode("utf-8", "replace")
        for rule in self.ledger.acl:
            verdict = rule.check(username, client.remote, client.id, topic,
                                 write)
            if verdict is not None:
                return verdict
        return True  # no applicable rule -> allowed (reference behavior)
