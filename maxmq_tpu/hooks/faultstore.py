"""Disk-fault injection shim for the storage backend (ADR 024).

The ADR-014 fault sites (``storage.put``/``storage.commit``) raise
generic Python exceptions at the JOURNAL's boundaries — useful for
breaker drills, useless for answering "what does the pipeline do when
the DISK says EIO / ENOSPC / fsync-failed". :class:`FaultInjectingStore`
wraps the real backend (SQLite or memory) so the ``disk.*`` fault
family (faults.py) surfaces as the OS errors a dying disk produces,
from the exact layer that would produce them:

* ``disk.write``   — the write/commit raises ``OSError(EIO)``: a bad
  sector / failed block write. Retryable; the journal's breaker ladder
  handles it like any commit failure.
* ``disk.enospc``  — ``DiskFull`` (``OSError(ENOSPC)``): the volume is
  full. NOT retryable by waiting politely — the journal trips its
  breaker immediately and sheds QoS0-irrelevant rewrites (its own
  ladder rung, journal.py).
* ``disk.fsync``   — ``FsyncFailed`` raised AFTER the inner commit ran:
  the write(2)s landed but the flush failed, so dirty-page state is
  unknown (fsyncgate). The journal must treat the connection as
  POISONED — reopen the backend and replay the parked journal rather
  than assume anything survived. Replays are idempotent (same-key
  upserts), so a batch that DID reach the platter commits twice,
  harmlessly.
* ``disk.latency`` — arm with ``hang`` mode: the registry sleeps the
  writer thread for ``delay_s`` (commit latency, never loop latency).

All sites are consulted off the event loop (the journal's writer
thread, or boot-time restore); the unarmed fast path is one membership
test on an (almost always) empty dict, so the shim wraps the backend
unconditionally (bootstrap.build_storage).

:func:`torn_tail` is the power-loss half of the family: truncate the
last N bytes of the SQLite main/-wal file between a kill and a
restart, simulating a torn final write. It is a harness-side helper
(the victim process is already dead when it runs), kept here so the
disk-fault surface lives in one module.
"""

from __future__ import annotations

import errno
import os

from .. import faults
from .storage import Store


class FsyncFailed(OSError):
    """fsync(2) failed after the writes landed: dirty-page fate unknown
    (fsyncgate semantics). The journal poisons the backend connection
    on seeing this — reopen + replay, never retry on the old handle."""

    def __init__(self, msg: str = "injected fsync failure") -> None:
        super().__init__(errno.EIO, msg)


class DiskFull(OSError):
    """ENOSPC from the backend: the volume is full."""

    def __init__(self, msg: str = "injected ENOSPC") -> None:
        super().__init__(errno.ENOSPC, msg)


def _fire_disk_faults() -> None:
    """One write/commit attempt's worth of disk faults, in severity
    order. ``disk.latency`` is consulted first (a slow disk still
    fails afterward if told to); the error sites raise."""
    faults.fire(faults.DISK_LATENCY)        # hang mode sleeps delay_s
    if faults.fire(faults.DISK_WRITE):
        raise OSError(errno.EIO, "injected disk write error")
    if faults.fire(faults.DISK_ENOSPC):
        raise DiskFull()


class FaultInjectingStore(Store):
    """A :class:`Store` that passes everything through to ``inner``,
    consulting the ``disk.*`` sites around each write/commit."""

    def __init__(self, inner: Store) -> None:
        self.inner = inner

    # -- reads / lifecycle: pure delegation ----------------------------

    def get(self, bucket, key):
        return self.inner.get(bucket, key)

    def all(self, bucket):
        return self.inner.all(bucket)

    def close(self):
        self.inner.close()

    def reopen(self):
        """Poisoned-connection recovery (journal.py): delegate to the
        backend when it supports reopening, else no-op (MemoryStore
        has no connection to poison)."""
        reopen = getattr(self.inner, "reopen", None)
        if reopen is not None:
            reopen()

    def __getattr__(self, name):
        # counters/paths the metrics layer duck-types off the backend
        # (corruptions, aside_failures, path, ...) stay reachable
        return getattr(self.inner, name)

    # -- writes: the disk.* consultation points ------------------------

    def put(self, bucket, key, value):
        _fire_disk_faults()
        self.inner.put(bucket, key, value)
        if faults.fire(faults.DISK_FSYNC):
            raise FsyncFailed()

    def delete(self, bucket, key):
        _fire_disk_faults()
        self.inner.delete(bucket, key)
        if faults.fire(faults.DISK_FSYNC):
            raise FsyncFailed()

    def delete_prefix(self, bucket, prefix):
        _fire_disk_faults()
        self.inner.delete_prefix(bucket, prefix)
        if faults.fire(faults.DISK_FSYNC):
            raise FsyncFailed()

    def apply_batch(self, ops):
        """The group-commit path (one journal commit = one call here):
        EIO/ENOSPC fire BEFORE the inner transaction (the write never
        happened), fsync fires AFTER it (the write may or may not have
        reached the platter — exactly the ambiguity the journal's
        poison-reopen-replay discipline exists for)."""
        _fire_disk_faults()
        self.inner.apply_batch(ops)
        if faults.fire(faults.DISK_FSYNC):
            raise FsyncFailed()


def torn_tail(path: str, nbytes: int = 512, target: str = "wal") -> int:
    """Truncate the last ``nbytes`` off a store file — the torn final
    write a power cut leaves. ``target`` picks the victim: ``"wal"``
    (the usual tear: SQLite recovers by dropping the torn frames and
    everything committed before them survives) or ``"db"`` (main-file
    damage: the open-time quick_check catches it and the move-aside
    path runs). Returns the bytes actually removed (0 when the file is
    missing or already smaller)."""
    victim = path + "-wal" if target == "wal" else path
    try:
        size = os.path.getsize(victim)
    except OSError:
        return 0
    cut = min(int(nbytes), size)
    if cut <= 0:
        return 0
    with open(victim, "rb+") as f:
        f.truncate(size - cut)
    return cut
