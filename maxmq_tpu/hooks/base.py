"""Hook/plugin boundary: every observable broker event, with the same four
dispatch semantics the reference engine gives its hooks:

* notify: every hook is invoked, return values ignored
* modify-chain: each hook may return a replacement packet/subscription
  (``on_packet_read``, ``on_publish``, ``on_subscribe``, ``on_will``)
* any-allow: authentication/ACL pass if ANY hook allows
  (``on_connect_authenticate``, ``on_acl_check``)
* first-non-empty: persistence getters return the first hook's non-empty
  result (``stored_*``)

Parity surface: vendor/github.com/mochi-co/mqtt/v2/hooks.go in the reference
(35-event Hook interface + Hooks dispatcher). The TPU matcher plugs in at
``on_select_subscribers`` exactly like the reference's OnSelectSubscribers.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from ..matching.trie import SubscriberSet
    from ..protocol.packets import Packet, Subscription, Will


class Hook:
    """Base hook: override any subset of events. All defaults are no-ops that
    preserve the modify-chain value unchanged.

    Modify-chain events receive the VALUE FIRST (packet/will/subscriber set),
    then the client — the order the Hooks.modify dispatcher passes them in.
    """

    id = "hook"

    def init(self, config: Any) -> None:  # called at add time
        pass

    def stop(self) -> None:
        pass

    # -- lifecycle ----------------------------------------------------------
    def on_started(self) -> None: ...
    def on_stopped(self) -> None: ...
    def on_sys_info_tick(self, info) -> None: ...

    # -- connection ---------------------------------------------------------
    def on_connect(self, client, packet: "Packet") -> None:
        """May raise ProtocolError to reject the connection."""

    def on_connect_authenticate(self, client, packet: "Packet") -> bool:
        return False

    def on_acl_check(self, client, topic: str, write: bool) -> bool:
        return False

    def on_session_establish(self, client, packet: "Packet") -> None: ...
    def on_session_established(self, client, packet: "Packet") -> None: ...
    def on_disconnect(self, client, err, expire: bool) -> None: ...
    def on_auth_packet(self, packet: "Packet", client) -> "Packet":
        return packet

    # -- packet flow --------------------------------------------------------
    def on_packet_read(self, packet: "Packet", client) -> "Packet":
        return packet

    def on_packet_encode(self, packet: "Packet", client) -> "Packet":
        return packet

    def on_packet_sent(self, client, packet: "Packet", nbytes: int) -> None: ...
    def on_packet_processed(self, client, packet: "Packet", err) -> None: ...

    # -- subscribe / unsubscribe -------------------------------------------
    def on_subscribe(self, packet: "Packet", client) -> "Packet":
        return packet

    def on_subscribed(self, client, packet: "Packet",
                      reason_codes: list[int], counts: list[int]) -> None: ...

    def on_select_subscribers(self, subscribers: "SubscriberSet",
                              packet: "Packet") -> "SubscriberSet":
        """Intercept the matched subscriber set before shared-group
        selection (reference: hooks.go:334-345 OnSelectSubscribers).

        Contract: the set's OUTER dicts are the hook's to mutate
        (add/drop/replace entries), but the Subscription RECORDS are
        aliased from the matcher's caches and immutable — mutating one
        corrupts every concurrent delivery sharing it (ADR 009; the
        churn suite samples records for grafted state). A hook that
        needs to rewrite record fields must set the class attribute
        ``select_subscribers_mutates_records = True``; it then receives
        a deep copy and pays that cost per publish. Hooks that only
        filter $share groups can set
        ``select_subscribers_shared_only = True`` for the cheapest
        path."""
        return subscribers

    def on_unsubscribe(self, packet: "Packet", client) -> "Packet":
        return packet

    def on_unsubscribed(self, client, packet: "Packet") -> None: ...

    # -- publish ------------------------------------------------------------
    def on_publish(self, packet: "Packet", client) -> "Packet":
        """May raise RejectPacket to drop, or ProtocolError to disconnect."""
        return packet

    def on_published(self, client, packet: "Packet") -> None: ...
    def on_publish_dropped(self, client, packet: "Packet") -> None: ...

    # -- retained -----------------------------------------------------------
    def on_retain_message(self, client, packet: "Packet", stored: int) -> None: ...
    def on_retain_published(self, client, packet: "Packet") -> None: ...
    def on_retained_expired(self, filter_: str) -> None: ...

    # -- QoS ----------------------------------------------------------------
    def on_qos_publish(self, client, packet: "Packet", sent: float,
                       resends: int) -> None: ...
    def on_qos_complete(self, client, packet: "Packet") -> None: ...
    def on_qos_dropped(self, client, packet: "Packet") -> None: ...
    def on_packet_id_exhausted(self, client, packet: "Packet") -> None: ...

    # -- wills / expiry -----------------------------------------------------
    def on_will(self, will: "Will", client) -> "Will":
        return will

    def on_will_sent(self, client, packet: "Packet") -> None: ...
    def on_client_expired(self, client) -> None: ...

    # -- persistence (first-non-empty getters + write-through events) ------
    def stored_clients(self) -> list:
        return []

    def stored_subscriptions(self) -> list:
        return []

    def stored_inflight_messages(self) -> list:
        return []

    def stored_retained_messages(self) -> list:
        return []

    def stored_sys_info(self):
        return None


class RejectPacket(Exception):
    """Raised by on_publish to silently drop a packet (ack but don't route)."""

    def __init__(self, ack_success: bool = True):
        super().__init__("packet rejected by hook")
        self.ack_success = ack_success


_MODIFY = {"on_packet_read", "on_packet_encode", "on_subscribe", "on_will",
           "on_publish", "on_unsubscribe", "on_auth_packet",
           "on_select_subscribers"}
_ANY_ALLOW = {"on_connect_authenticate", "on_acl_check"}
_FIRST_NON_EMPTY = {"stored_clients", "stored_subscriptions",
                    "stored_inflight_messages", "stored_retained_messages",
                    "stored_sys_info"}


class Hooks:
    """Ordered hook dispatcher."""

    def __init__(self) -> None:
        self._hooks: list[Hook] = []
        # event -> hooks overriding it; computed once per hook-set change
        # (dispatch runs several times per packet on the fan-out path)
        self._override_cache: dict[str, list[Hook]] = {}

    def add(self, hook: Hook, config: Any = None) -> Hook:
        hook.init(config)
        self._hooks.append(hook)
        self._override_cache.clear()
        return hook

    def stop_all(self) -> None:
        for h in self._hooks:
            try:
                h.stop()
            except Exception:
                pass

    def __iter__(self):
        return iter(self._hooks)

    def __len__(self) -> int:
        return len(self._hooks)

    def _overriders(self, event: str) -> list[Hook]:
        lst = self._override_cache.get(event)
        if lst is None:
            base = getattr(Hook, event)
            lst = [h for h in self._hooks
                   if getattr(type(h), event, base) is not base]
            self._override_cache[event] = lst
        return lst

    def overrides(self, event: str) -> bool:
        """True when any hook implements ``event`` (fast-path gates)."""
        return bool(self._overriders(event))

    def notify(self, event: str, *args) -> None:
        for h in self._overriders(event):
            getattr(h, event)(*args)

    def modify(self, event: str, value, *args):
        """Chain ``value`` through every hook implementing ``event``. The
        extra ``args`` are passed after the value."""
        assert event in _MODIFY, event
        for h in self._overriders(event):
            out = getattr(h, event)(value, *args)
            if out is not None:
                value = out
        return value

    def any_allow(self, event: str, *args) -> bool:
        assert event in _ANY_ALLOW, event
        for h in self._overriders(event):
            if getattr(h, event)(*args):
                return True
        # With no auth hooks installed the broker refuses everything, same as
        # the reference (an explicit allow-all hook must be added).
        return False

    def provides(self, event: str) -> bool:
        return any(True for _ in self._overriders(event))

    def first_non_empty(self, event: str):
        assert event in _FIRST_NON_EMPTY, event
        for h in self._overriders(event):
            out = getattr(h, event)()
            if out:
                return out
        return None if event == "stored_sys_info" else []
