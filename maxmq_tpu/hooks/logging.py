"""Structured-logging hook: one leveled log event per observable broker event.

Parity surface: internal/mqtt/logging.go in the reference — a hook
implementing 20 of the 35 events (logging.go:43-66), emitting structured
leveled logs for packet rx/tx (TRACE), connect/disconnect, subscribe/
unsubscribe, publish, QoS flow, retained messages, wills and expiry
(logging.go:69-422).
"""

from __future__ import annotations

from ..protocol.codec import PacketType
from ..utils.logger import Logger
from .base import Hook

_TYPE_NAMES = {v: k for k, v in vars(PacketType).items()
               if isinstance(v, int) and not k.startswith("_")}


def _ptype(t: int) -> str:
    return _TYPE_NAMES.get(t, str(t))


def _cid(client) -> str:
    return getattr(client, "id", "") or "?"


def _trace_fields(packet) -> dict:
    """Correlation fields for publish-path events (ADR 015/017): when
    this publish rode the sampled pipeline tracer, every log line about
    it carries the same ``trace`` id the flight recorder / Chrome
    export uses. On the RECEIVING node of a cross-node forward the
    trace is an adopted one and logs as ``<origin>:<id>`` — one grep
    correlates the publish across every node of a cluster run."""
    tr = getattr(packet, "_trace", None)
    if tr is not None:
        return {"trace": f"{tr.origin}:{tr.id}" if tr.origin else tr.id}
    ref = getattr(packet, "_trace_ref", None)
    if ref is not None:
        return {"trace": f"{ref[0]}:{ref[1]}"}
    return {}


class LoggingHook(Hook):
    """Logs every broker event at the same levels the reference uses:
    packet-level rx/tx at TRACE, protocol milestones at DEBUG/INFO,
    losses at WARN."""

    id = "logging"

    def __init__(self, logger: Logger) -> None:
        self.log = logger

    # -- lifecycle ----------------------------------------------------------
    def on_started(self) -> None:
        self.log.info("broker started")

    def on_stopped(self) -> None:
        self.log.info("broker stopped")

    # -- connection ---------------------------------------------------------
    def on_connect(self, client, packet) -> None:
        self.log.debug("received CONNECT packet", client=_cid(client),
                       listener=client.listener, version=packet.protocol_version,
                       clean=packet.clean_start)

    def on_session_established(self, client, packet) -> None:
        self.log.info("client connected", client=_cid(client),
                      remote=client.remote, listener=client.listener,
                      keepalive=client.keepalive,
                      inflight=len(client.inflight))

    def on_disconnect(self, client, err, expire: bool) -> None:
        # a reason-code-0 "error" is a clean client DISCONNECT, not a failure
        if err is not None and getattr(getattr(err, "code", None),
                                       "value", 1) != 0:
            self.log.warn("client disconnected with error",
                          client=_cid(client), error=str(err), expire=expire)
        else:
            self.log.info("client disconnected", client=_cid(client),
                          expire=expire)

    def on_client_expired(self, client) -> None:
        self.log.debug("session expired", client=_cid(client))

    # -- packet flow (TRACE) ------------------------------------------------
    def on_packet_read(self, packet, client):
        self.log.trace("received packet", client=_cid(client),
                       type=_ptype(packet.fixed.type), id=packet.packet_id,
                       bytes=packet.fixed.remaining)
        return packet

    def on_packet_id_exhausted(self, client, packet) -> None:
        self.log.warn("packet ids exhausted", client=_cid(client))

    # -- subscribe / unsubscribe -------------------------------------------
    def on_subscribed(self, client, packet, reason_codes, counts) -> None:
        self.log.info("client subscribed", client=_cid(client),
                      filters=[s.filter for s in packet.filters],
                      reason_codes=reason_codes)

    def on_unsubscribed(self, client, packet) -> None:
        self.log.info("client unsubscribed", client=_cid(client),
                      filters=[s.filter for s in packet.filters])

    # -- publish ------------------------------------------------------------
    def on_publish(self, packet, client):
        self.log.debug("received PUBLISH", client=_cid(client),
                       topic=packet.topic, qos=packet.fixed.qos,
                       retain=packet.fixed.retain,
                       bytes=len(packet.payload or b""),
                       **_trace_fields(packet))
        return packet

    def on_published(self, client, packet) -> None:
        self.log.debug("message published", client=_cid(client),
                       topic=packet.topic, **_trace_fields(packet))

    def on_publish_dropped(self, client, packet) -> None:
        self.log.warn("publish dropped (slow consumer)",
                      client=_cid(client), topic=packet.topic,
                      **_trace_fields(packet))

    # -- retained -----------------------------------------------------------
    def on_retain_message(self, client, packet, stored: int) -> None:
        self.log.debug("retained message changed", client=_cid(client),
                       topic=packet.topic, stored=stored)

    def on_retained_expired(self, filter_: str) -> None:
        self.log.debug("retained message expired", topic=filter_)

    # -- QoS ----------------------------------------------------------------
    def on_qos_publish(self, client, packet, sent: float, resends: int) -> None:
        self.log.trace("inflight message queued", client=_cid(client),
                       id=packet.packet_id, resends=resends)

    def on_qos_complete(self, client, packet) -> None:
        self.log.trace("qos flow complete", client=_cid(client),
                       id=packet.packet_id)

    def on_qos_dropped(self, client, packet) -> None:
        self.log.warn("inflight message dropped", client=_cid(client),
                      id=packet.packet_id)

    # -- wills --------------------------------------------------------------
    def on_will_sent(self, client, packet) -> None:
        self.log.debug("will message sent", client=_cid(client),
                       topic=packet.topic)


class PacketTxLogHook(Hook):
    """TRACE-level per-packet tx logging, as its own hook because an
    ``on_packet_sent`` override anywhere forces every fan-out delivery
    onto the per-client encode path (the hook must observe a real
    Packet, ADR 019) — attached by bootstrap only when the configured
    level actually emits TRACE, so the default deployment keeps
    zero-copy fan-out."""

    id = "logging-tx"

    def __init__(self, logger: Logger) -> None:
        self.log = logger

    def on_packet_sent(self, client, packet, nbytes: int) -> None:
        self.log.trace("sent packet", client=_cid(client),
                       type=_ptype(packet.fixed.type), id=packet.packet_id,
                       bytes=nbytes)
