"""Persistence: serializable session/message records plus two store-backed
hooks (in-memory and SQLite). The broker restores from ``stored_*`` getters at
serve time and writes through on every relevant event.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/hooks/storage/storage.go
(record types) and the Stored* hook plumbing in hooks.go:511-606. The
reference vendors no backend; here SQLite (stdlib) is a first-class one.
"""

from __future__ import annotations

import json
import sqlite3
import threading
from dataclasses import asdict, dataclass

from ..protocol.codec import FixedHeader, PacketType as PT
from ..protocol.packets import Packet
from ..protocol.properties import Properties
from .base import Hook


@dataclass
class ClientRecord:
    client_id: str
    listener: str = ""
    username: bytes = b""
    clean: bool = False
    protocol_version: int = 4
    session_expiry: int = 0
    session_expiry_set: bool = False
    disconnected_at: float = 0.0

    def to_json(self) -> str:
        d = asdict(self)
        d["username"] = self.username.decode("utf-8", "replace")
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "ClientRecord":
        d = json.loads(s)
        d["username"] = d.get("username", "").encode()
        return cls(**d)


@dataclass
class SubscriptionRecord:
    client_id: str
    filter: str
    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0
    identifier: int = 0

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "SubscriptionRecord":
        return cls(**json.loads(s))


@dataclass
class MessageRecord:
    """A retained or inflight message, wire-reconstructable."""

    client_id: str = ""       # inflight owner; '' for retained
    origin: str = ""
    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    packet_id: int = 0
    packet_type: int = PT.PUBLISH
    created: float = 0.0
    expiry: int | None = None
    properties_json: str = "{}"

    @classmethod
    def from_packet(cls, packet: Packet, client_id: str = "") -> "MessageRecord":
        props = {}
        pr = packet.properties
        for k in ("payload_format", "message_expiry", "content_type",
                  "response_topic", "user_properties", "subscription_ids"):
            v = getattr(pr, k)
            if v:
                props[k] = v if not isinstance(v, bytes) else v.hex()
        if pr.correlation_data:
            props["correlation_data"] = pr.correlation_data.hex()
        return cls(client_id=client_id, origin=packet.origin,
                   topic=packet.topic, payload=packet.payload,
                   qos=packet.fixed.qos, retain=packet.fixed.retain,
                   packet_id=packet.packet_id, packet_type=packet.fixed.type,
                   created=packet.created,
                   properties_json=json.dumps(props))

    def to_packet(self) -> Packet:
        props = Properties()
        for k, v in json.loads(self.properties_json).items():
            if k == "correlation_data":
                props.correlation_data = bytes.fromhex(v)
            elif k == "user_properties":
                props.user_properties = [tuple(p) for p in v]
            else:
                setattr(props, k, v)
        return Packet(
            fixed=FixedHeader(type=self.packet_type, qos=self.qos,
                              retain=self.retain),
            topic=self.topic, payload=self.payload, packet_id=self.packet_id,
            origin=self.origin, created=self.created, properties=props)

    def to_json(self) -> str:
        d = asdict(self)
        d["payload"] = self.payload.hex()
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "MessageRecord":
        d = json.loads(s)
        d["payload"] = bytes.fromhex(d.get("payload", ""))
        return cls(**d)


class StorageHook(Hook):
    """Write-through persistence against an abstract key/value store with
    namespaced buckets: clients, subscriptions, retained, inflight, sysinfo."""

    id = "storage"

    def __init__(self, store: "Store") -> None:
        self.store = store

    def stop(self) -> None:
        self.store.close()

    # -- restore getters ----------------------------------------------------

    def stored_clients(self) -> list:
        return [ClientRecord.from_json(v)
                for v in self.store.all("clients").values()]

    def stored_subscriptions(self) -> list:
        return [SubscriptionRecord.from_json(v)
                for v in self.store.all("subscriptions").values()]

    def stored_retained_messages(self) -> list:
        return [MessageRecord.from_json(v)
                for v in self.store.all("retained").values()]

    def stored_inflight_messages(self) -> list:
        return [MessageRecord.from_json(v)
                for v in self.store.all("inflight").values()]

    def stored_sys_info(self):
        from ..broker.sys_info import SysInfo
        raw = self.store.get("sysinfo", "sysinfo")
        if not raw:
            return None
        data = json.loads(raw)
        data.pop("extra", None)
        known = {f for f in SysInfo.__dataclass_fields__ if f != "extra"}
        return SysInfo(**{k: v for k, v in data.items() if k in known})

    # -- write-through events -----------------------------------------------

    def _save_client(self, client) -> None:
        rec = ClientRecord(
            client_id=client.id, listener=client.listener,
            username=client.properties.username,
            clean=client.properties.clean_start,
            protocol_version=client.properties.protocol_version,
            session_expiry=client.properties.session_expiry,
            session_expiry_set=client.properties.session_expiry_set,
            disconnected_at=client.disconnected_at)
        self.store.put("clients", client.id, rec.to_json())

    def on_session_established(self, client, packet) -> None:
        self._save_client(client)

    def on_disconnect(self, client, err, expire: bool) -> None:
        if expire:
            self.store.delete("clients", client.id)
            self.store.delete_prefix("subscriptions", client.id + "|")
            self.store.delete_prefix("inflight", client.id + "|")
        else:
            self._save_client(client)

    def on_client_expired(self, client) -> None:
        self.store.delete("clients", client.id)
        self.store.delete_prefix("subscriptions", client.id + "|")
        self.store.delete_prefix("inflight", client.id + "|")

    def on_subscribed(self, client, packet, reason_codes, counts) -> None:
        for sub, code in zip(packet.filters, reason_codes):
            if code >= 0x80:
                continue
            rec = SubscriptionRecord(
                client_id=client.id, filter=sub.filter, qos=sub.qos,
                no_local=sub.no_local,
                retain_as_published=sub.retain_as_published,
                retain_handling=sub.retain_handling, identifier=sub.identifier)
            self.store.put("subscriptions", f"{client.id}|{sub.filter}",
                           rec.to_json())

    def on_unsubscribed(self, client, packet) -> None:
        for sub in packet.filters:
            self.store.delete("subscriptions", f"{client.id}|{sub.filter}")

    def on_retain_message(self, client, packet, stored: int) -> None:
        if stored == -1 or not packet.payload:
            self.store.delete("retained", packet.topic)
        else:
            self.store.put("retained", packet.topic,
                           MessageRecord.from_packet(packet).to_json())

    def on_retained_expired(self, topic: str) -> None:
        self.store.delete("retained", topic)

    def on_qos_publish(self, client, packet, sent: float, resends: int) -> None:
        self.store.put("inflight", f"{client.id}|{packet.packet_id}",
                       MessageRecord.from_packet(packet, client.id).to_json())

    def on_qos_complete(self, client, packet) -> None:
        self.store.delete("inflight", f"{client.id}|{packet.packet_id}")

    def on_qos_dropped(self, client, packet) -> None:
        self.store.delete("inflight", f"{client.id}|{packet.packet_id}")

    def on_sys_info_tick(self, info) -> None:
        self.store.put("sysinfo", "sysinfo", json.dumps(
            {k: v for k, v in asdict(info).items() if k != "extra"}))


class Store:
    """Abstract bucketed KV store."""

    def put(self, bucket: str, key: str, value: str) -> None:
        raise NotImplementedError

    def get(self, bucket: str, key: str) -> str | None:
        raise NotImplementedError

    def delete(self, bucket: str, key: str) -> None:
        raise NotImplementedError

    def delete_prefix(self, bucket: str, prefix: str) -> None:
        raise NotImplementedError

    def all(self, bucket: str) -> dict[str, str]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryStore(Store):
    def __init__(self) -> None:
        self._data: dict[str, dict[str, str]] = {}

    def put(self, bucket, key, value):
        self._data.setdefault(bucket, {})[key] = value

    def get(self, bucket, key):
        return self._data.get(bucket, {}).get(key)

    def delete(self, bucket, key):
        self._data.get(bucket, {}).pop(key, None)

    def delete_prefix(self, bucket, prefix):
        b = self._data.get(bucket, {})
        for k in [k for k in b if k.startswith(prefix)]:
            del b[k]

    def all(self, bucket):
        return dict(self._data.get(bucket, {}))


class SQLiteStore(Store):
    """Durable store on stdlib sqlite3 (WAL mode)."""

    def __init__(self, path: str) -> None:
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "bucket TEXT NOT NULL, key TEXT NOT NULL, value TEXT NOT NULL,"
                "PRIMARY KEY (bucket, key))")
            self._conn.commit()

    def put(self, bucket, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (bucket, key, value) VALUES (?, ?, ?) "
                "ON CONFLICT(bucket, key) DO UPDATE SET value=excluded.value",
                (bucket, key, value))
            self._conn.commit()

    def get(self, bucket, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE bucket=? AND key=?",
                (bucket, key)).fetchone()
        return row[0] if row else None

    def delete(self, bucket, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE bucket=? AND key=?",
                               (bucket, key))
            self._conn.commit()

    def delete_prefix(self, bucket, prefix):
        with self._lock:
            self._conn.execute(
                "DELETE FROM kv WHERE bucket=? AND key GLOB ?",
                (bucket, prefix.replace("[", "[[]") + "*"))
            self._conn.commit()

    def all(self, bucket):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE bucket=?", (bucket,)).fetchall()
        return dict(rows)

    def close(self):
        with self._lock:
            self._conn.commit()
            self._conn.close()
