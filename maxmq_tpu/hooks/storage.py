"""Persistence: serializable session/message records plus two store-backed
hooks (in-memory and SQLite). The broker restores from ``stored_*`` getters at
serve time and writes through on every relevant event.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/hooks/storage/storage.go
(record types) and the Stored* hook plumbing in hooks.go:511-606. The
reference vendors no backend; here SQLite (stdlib) is a first-class one.

Crash consistency (ADR 014): record ``from_json`` is forward-compatible
(unknown keys from a newer schema are dropped, not a TypeError), restore
is per-record tolerant (a torn/undecodable record is QUARANTINED to a
side bucket and counted, never fatal to boot), SQLite verifies itself
with ``quick_check`` at open (a corrupt file is moved aside and
recreated instead of crashing serve()), and every boot persists a
monotonic ``boot_epoch`` the cluster layer uses instead of wall-clock
epochs. Writes normally ride the write-behind journal
(hooks/journal.py), which this hook sheds QoS0-irrelevant rewrites
into when the broker is load-shedding past the journal watermark.
"""

from __future__ import annotations

import json
import logging
import os
import sqlite3
import threading
import time
from dataclasses import asdict, dataclass, fields

from .. import faults
from ..protocol.codec import FixedHeader, PacketType as PT
from ..protocol.packets import Packet
from ..protocol.properties import Properties
from .base import Hook

_log = logging.getLogger("maxmq.storage")


def _known_fields(cls, d: dict) -> dict:
    """Forward-compat record decode: a record written by a NEWER build
    may carry keys this build doesn't know; restoring after a downgrade
    must drop them instead of dying in ``cls(**d)`` (ADR 014)."""
    known = {f.name for f in fields(cls)}
    return {k: v for k, v in d.items() if k in known}


@dataclass
class ClientRecord:
    client_id: str
    listener: str = ""
    username: bytes = b""
    clean: bool = False
    protocol_version: int = 4
    session_expiry: int = 0
    session_expiry_set: bool = False
    disconnected_at: float = 0.0

    def to_json(self) -> str:
        d = asdict(self)
        d["username"] = self.username.decode("utf-8", "replace")
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "ClientRecord":
        d = _known_fields(cls, json.loads(s))
        d["username"] = d.get("username", "").encode()
        return cls(**d)


@dataclass
class SubscriptionRecord:
    client_id: str
    filter: str
    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0
    identifier: int = 0
    # ADR 023/024: the raw content-filter option string ("$expr=...&
    # $agg=..."), empty for plain subscriptions — persisted so restore
    # can re-register the spec with the content plane instead of
    # silently downgrading a survivor to an unfiltered subscription
    options: str = ""

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "SubscriptionRecord":
        return cls(**_known_fields(cls, json.loads(s)))


@dataclass
class MessageRecord:
    """A retained or inflight message, wire-reconstructable."""

    client_id: str = ""       # inflight owner; '' for retained
    origin: str = ""
    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    packet_id: int = 0
    packet_type: int = PT.PUBLISH
    created: float = 0.0
    expiry: int | None = None
    properties_json: str = "{}"
    # ADR 018: inflight record parked in held_pids (allocated into the
    # window but never sent — send quota was exhausted); restore/
    # takeover re-parks it instead of resending past receive maximum
    held: bool = False

    @classmethod
    def from_packet(cls, packet: Packet, client_id: str = "") -> "MessageRecord":
        props = {}
        pr = packet.properties
        for k in ("payload_format", "message_expiry", "content_type",
                  "response_topic", "user_properties", "subscription_ids"):
            v = getattr(pr, k)
            if v:
                props[k] = v if not isinstance(v, bytes) else v.hex()
        if pr.correlation_data:
            props["correlation_data"] = pr.correlation_data.hex()
        return cls(client_id=client_id, origin=packet.origin,
                   topic=packet.topic, payload=packet.payload,
                   qos=packet.fixed.qos, retain=packet.fixed.retain,
                   packet_id=packet.packet_id, packet_type=packet.fixed.type,
                   created=packet.created,
                   properties_json=json.dumps(props))

    def to_packet(self) -> Packet:
        props = Properties()
        for k, v in json.loads(self.properties_json).items():
            if k == "correlation_data":
                props.correlation_data = bytes.fromhex(v)
            elif k == "user_properties":
                props.user_properties = [tuple(p) for p in v]
            else:
                setattr(props, k, v)
        return Packet(
            fixed=FixedHeader(type=self.packet_type, qos=self.qos,
                              retain=self.retain),
            topic=self.topic, payload=self.payload, packet_id=self.packet_id,
            origin=self.origin, created=self.created, properties=props)

    def to_json(self) -> str:
        d = asdict(self)
        d["payload"] = self.payload.hex()
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "MessageRecord":
        d = _known_fields(cls, json.loads(s))
        d["payload"] = bytes.fromhex(d.get("payload", ""))
        return cls(**d)


QUARANTINE_BUCKET = "quarantine"


class StorageHook(Hook):
    """Write-through persistence against an abstract key/value store with
    namespaced buckets: clients, subscriptions, retained, inflight,
    sysinfo, meta (boot_epoch), quarantine (torn records, ADR 014).

    When ``store`` is a write-behind journal (hooks/journal.py) the
    hook's writes never touch the backend on the event loop; ``journal``
    then exposes it to the broker for durability barriers, $SYS, and
    /metrics."""

    id = "storage"

    def __init__(self, store: "Store") -> None:
        self.store = store
        # duck-typed: anything with a durability barrier is "a journal"
        self.journal = store if hasattr(store, "barrier") else None
        self.boot_epoch = 0         # set by bump_boot_epoch at restore
        self.quarantined = 0        # torn/unknown records set aside
        self.journal_sheds = 0      # QoS0-irrelevant rewrites shed
        self.rewrites_skipped = 0   # redundant inflight resend rewrites

    def stop(self) -> None:
        self.store.close()

    # -- restore getters (per-record tolerant, ADR 014) ---------------------

    def _quarantine(self, bucket: str, key: str, raw: str, exc) -> None:
        """A record that won't parse is moved to the side bucket and
        counted — a torn write or a newer-schema leftover must cost ONE
        record, never the boot."""
        self.quarantined += 1
        try:
            self.store.put(QUARANTINE_BUCKET, f"{bucket}|{key}", raw)
            self.store.delete(bucket, key)
        except Exception:
            pass    # quarantining is best-effort; the count still tells
        _log.error("storage restore: quarantined %s/%s: %r",
                   bucket, key, exc)

    def _restore_bucket(self, bucket: str, parse) -> list:
        out = []
        for key, raw in self.store.all(bucket).items():
            # ADR 024: a crash DURING recovery must leave a store the
            # NEXT boot restores from — the kill-point drill dies here
            # mid-bucket and reboots onto the same file
            faults.crash_point("restore_parse")
            try:
                faults.fire(faults.STORAGE_RESTORE)
                out.append(parse(raw))
            except Exception as exc:
                self._quarantine(bucket, key, raw, exc)
        return out

    def stored_clients(self) -> list:
        return self._restore_bucket("clients", ClientRecord.from_json)

    def stored_subscriptions(self) -> list:
        return self._restore_bucket("subscriptions",
                                    SubscriptionRecord.from_json)

    def stored_retained_messages(self) -> list:
        return self._restore_bucket("retained", MessageRecord.from_json)

    def stored_inflight_messages(self) -> list:
        return self._restore_bucket("inflight", MessageRecord.from_json)

    def stored_sys_info(self):
        from ..broker.sys_info import SysInfo
        raw = self.store.get("sysinfo", "sysinfo")
        if not raw:
            return None
        try:
            faults.fire(faults.STORAGE_RESTORE)
            data = json.loads(raw)
            data.pop("extra", None)
            known = {f for f in SysInfo.__dataclass_fields__ if f != "extra"}
            return SysInfo(**{k: v for k, v in data.items() if k in known})
        except Exception as exc:
            self._quarantine("sysinfo", "sysinfo", raw, exc)
            return None

    # -- boot epoch (ADR 014; closes the ADR-013 wall-clock limitation) -----

    def bump_boot_epoch(self) -> int:
        """Read-increment-persist the monotonic boot counter. A fresh
        store seeds from wall-clock ms so nodes upgrading from ADR-013
        wall-clock epochs stay monotonic for their peers; every boot
        after that is +1 regardless of clock behavior. Flushed through
        the journal synchronously — boot runs before any traffic, and a
        boot epoch that could be lost would be no epoch at all."""
        prev = 0
        try:
            raw = self.store.get("meta", "boot_epoch")
            prev = int(raw) if raw else 0
        except Exception:
            prev = 0
        self.boot_epoch = prev + 1 if prev > 0 else int(time.time() * 1000)
        self.store.put("meta", "boot_epoch", str(self.boot_epoch))
        flush = getattr(self.store, "flush", None)
        if flush is not None:
            flush(timeout=5.0)
        return self.boot_epoch

    # -- shed policy (ADR 014, rides the ADR-012 watermark) -----------------

    def _shed_rewrite(self, client) -> bool:
        """True when a QoS0-irrelevant rewrite should be dropped: the
        broker is load-shedding (ADR 012) AND the journal sits past its
        byte watermark — storms must not grow the journal unbounded.
        A full disk (ADR 024 ENOSPC rung) sheds unconditionally: every
        parked byte already has nowhere to go, so QoS0-irrelevant
        rewrites are the first thing off the ladder."""
        j = self.journal
        if j is None:
            return False
        if getattr(j, "disk_full", False):
            over = getattr(getattr(client, "server", None),
                           "overload", None)
            if over is not None:
                over.disk_full_sheds += 1
            return True
        if not j.over_watermark:
            return False
        over = getattr(getattr(client, "server", None), "overload", None)
        return bool(over is not None and over.shedding)

    # -- write-through events -----------------------------------------------

    def _save_client(self, client) -> None:
        rec = ClientRecord(
            client_id=client.id, listener=client.listener,
            username=client.properties.username,
            clean=client.properties.clean_start,
            protocol_version=client.properties.protocol_version,
            session_expiry=client.properties.session_expiry,
            session_expiry_set=client.properties.session_expiry_set,
            disconnected_at=client.disconnected_at)
        self.store.put("clients", client.id, rec.to_json())

    def on_session_established(self, client, packet) -> None:
        self._save_client(client)

    def on_disconnect(self, client, err, expire: bool) -> None:
        if expire:
            self.store.delete("clients", client.id)
            self.store.delete_prefix("subscriptions", client.id + "|")
            self.store.delete_prefix("inflight", client.id + "|")
        else:
            self._save_client(client)

    def on_client_expired(self, client) -> None:
        self.store.delete("clients", client.id)
        self.store.delete_prefix("subscriptions", client.id + "|")
        self.store.delete_prefix("inflight", client.id + "|")

    def on_subscribed(self, client, packet, reason_codes, counts) -> None:
        for sub, code in zip(packet.filters, reason_codes):
            if code >= 0x80:
                continue
            rec = SubscriptionRecord(
                client_id=client.id, filter=sub.filter, qos=sub.qos,
                no_local=sub.no_local,
                retain_as_published=sub.retain_as_published,
                retain_handling=sub.retain_handling, identifier=sub.identifier,
                # ADR 023/024: the subscribe path stashes the parsed-OK
                # content options on the Subscription; a plain
                # (re-)subscribe stores "" and so clears any earlier
                # persisted spec (resubscribe-replaces semantics)
                options=getattr(sub, "content_options", "") or "")
            self.store.put("subscriptions", f"{client.id}|{sub.filter}",
                           rec.to_json())

    def on_unsubscribed(self, client, packet) -> None:
        for sub in packet.filters:
            self.store.delete("subscriptions", f"{client.id}|{sub.filter}")

    def on_retain_message(self, client, packet, stored: int) -> None:
        if stored == -1 or not packet.payload:
            self.store.delete("retained", packet.topic)
            return
        if packet.fixed.qos == 0 and self._shed_rewrite(client):
            # a QoS0 retained storm while shedding: losing the latest
            # rewrite leaves the prior retained value — QoS0 delivery
            # is already being shed above it (ADR 012), so the journal
            # doesn't owe the storm durability either
            self.journal_sheds += 1
            return
        self.store.put("retained", packet.topic,
                       MessageRecord.from_packet(packet).to_json())

    def on_retained_expired(self, topic: str) -> None:
        self.store.delete("retained", topic)

    def on_qos_publish(self, client, packet, sent: float, resends: int) -> None:
        inflight = getattr(client, "inflight", None)
        if resends and inflight is not None \
                and inflight.stored(packet.packet_id):
            # resend of a record already in the pipeline/store: the
            # serialized form is identical (dup/sent aren't persisted),
            # so the rewrite buys nothing — skip it (ADR 014)
            self.rewrites_skipped += 1
            return
        rec = MessageRecord.from_packet(packet, client.id)
        if packet.packet_id in getattr(client, "held_pids", ()):
            # ADR 018: quota-parked — persist the held-ness so restore
            # re-parks instead of resending past receive maximum (the
            # release rewrites the record with held cleared)
            rec.held = True
        self.store.put("inflight", f"{client.id}|{packet.packet_id}",
                       rec.to_json())
        if inflight is not None:
            inflight.note_stored(packet.packet_id)

    def on_qos_complete(self, client, packet) -> None:
        self.store.delete("inflight", f"{client.id}|{packet.packet_id}")

    def on_qos_dropped(self, client, packet) -> None:
        self.store.delete("inflight", f"{client.id}|{packet.packet_id}")

    def on_sys_info_tick(self, info) -> None:
        self.store.put("sysinfo", "sysinfo", json.dumps(
            {k: v for k, v in asdict(info).items() if k != "extra"}))


class Store:
    """Abstract bucketed KV store."""

    def put(self, bucket: str, key: str, value: str) -> None:
        raise NotImplementedError

    def get(self, bucket: str, key: str) -> str | None:
        raise NotImplementedError

    def delete(self, bucket: str, key: str) -> None:
        raise NotImplementedError

    def delete_prefix(self, bucket: str, prefix: str) -> None:
        raise NotImplementedError

    def all(self, bucket: str) -> dict[str, str]:
        raise NotImplementedError

    def apply_batch(self, ops) -> None:
        """Apply ``(kind, bucket, key, value)`` ops — kind one of
        ``put``/``delete``/``delete_prefix`` — as one transaction where
        the backend supports it (the journal's group commit, ADR 014).
        The default replays them individually."""
        for kind, bucket, key, value in ops:
            if kind == "put":
                self.put(bucket, key, value)
            elif kind == "delete":
                self.delete(bucket, key)
            else:
                self.delete_prefix(bucket, key)

    def close(self) -> None:
        pass


class MemoryStore(Store):
    def __init__(self) -> None:
        self._data: dict[str, dict[str, str]] = {}

    def put(self, bucket, key, value):
        self._data.setdefault(bucket, {})[key] = value

    def get(self, bucket, key):
        return self._data.get(bucket, {}).get(key)

    def delete(self, bucket, key):
        self._data.get(bucket, {}).pop(key, None)

    def delete_prefix(self, bucket, prefix):
        b = self._data.get(bucket, {})
        for k in [k for k in b if k.startswith(prefix)]:
            del b[k]

    def all(self, bucket):
        return dict(self._data.get(bucket, {}))


class CorruptStoreError(Exception):
    """The storage file failed its integrity check (ADR 014): the
    open path moves it aside and recreates. Distinct from transient
    sqlite3.OperationalError (locks, permissions), which must NOT
    trigger the move-aside."""


class SQLiteStore(Store):
    """Durable store on stdlib sqlite3 (WAL mode).

    ADR 014 hardening: ``synchronous`` follows the ``storage_sync``
    policy (journal.SQLITE_SYNC_BY_POLICY), ``busy_timeout`` bounds
    lock waits, and ``PRAGMA quick_check`` runs at open — a corrupt
    file is moved aside to ``<path>.corrupt-<n>`` and recreated
    (counted in ``corruptions``) instead of refusing to boot."""

    def __init__(self, path: str, synchronous: str = "FULL",
                 busy_timeout_ms: int = 5000, logger=None) -> None:
        self.path = path
        self.corruptions = 0
        self.aside_failures = 0         # forensic move-asides that failed
        self._synchronous = synchronous
        self._busy_timeout_ms = busy_timeout_ms
        self.log = logger or _log
        self._lock = threading.Lock()
        try:
            self._conn = self._open_verified(path)
        except CorruptStoreError as exc:
            self._conn = self._recreate_aside(path, exc)

    def _open_verified(self, path: str):
        """Open + integrity-check. Only CORRUPTION becomes
        :class:`CorruptStoreError` (→ move-aside); transient
        OperationalErrors — locked by another process, permissions,
        I/O — propagate as the real errors they are: moving a healthy
        database aside over a lock would BE the data loss."""
        conn = sqlite3.connect(path, check_same_thread=False)
        try:
            # busy_timeout FIRST: a concurrent WAL checkpoint must make
            # quick_check wait, not fail
            conn.execute(f"PRAGMA busy_timeout={int(self._busy_timeout_ms)}")
            try:
                row = conn.execute("PRAGMA quick_check").fetchone()
            except sqlite3.OperationalError:
                raise                   # locked/permission/io: NOT corruption
            except sqlite3.DatabaseError as exc:
                raise CorruptStoreError(str(exc)) from exc
            if not row or row[0] != "ok":
                raise CorruptStoreError(
                    f"quick_check: {row[0] if row else 'no result'}")
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute(f"PRAGMA synchronous={self._synchronous}")
            conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "bucket TEXT NOT NULL, key TEXT NOT NULL, value TEXT NOT NULL,"
                "PRIMARY KEY (bucket, key))")
            conn.commit()
        except BaseException:
            conn.close()
            raise
        return conn

    def _recreate_aside(self, path: str, exc: Exception):
        """Corruption policy: the broker must boot. Move the damaged
        file (and WAL/SHM siblings) aside for forensics, recreate
        fresh, count + log LOUDLY — state is lost, service is not."""
        self.corruptions += 1
        n = 1
        while os.path.exists(f"{path}.corrupt-{n}"):
            n += 1
        aside = f"{path}.corrupt-{n}"
        for suffix in ("", "-wal", "-shm"):
            src = path + suffix
            try:
                if os.path.exists(src):
                    os.replace(src, aside + suffix)
            except OSError as move_exc:
                # a failed move-aside loses the forensic copy, never
                # the boot: count + log it, then REMOVE the damaged
                # file in place so the recreate below starts fresh
                # instead of re-opening the same corruption
                self.aside_failures += 1
                self.log.error(
                    "storage move-aside of %s to %s failed (%r); "
                    "removing the damaged file in place — forensic "
                    "copy lost", src, aside + suffix, move_exc)
                try:
                    os.remove(src)
                except OSError as rm_exc:
                    self.log.error(
                        "storage could not remove damaged file %s "
                        "either: %r", src, rm_exc)
        self.log.error(
            "storage file %s failed integrity check (%r); moved aside "
            "to %s and recreated EMPTY — persisted sessions/retained/"
            "inflight from it are gone", path, exc, aside)
        return self._open_verified(path)

    def reopen(self) -> None:
        """Drop the current connection and open a verified fresh one
        (ADR 024): the journal calls this when a failed fsync poisoned
        the handle — dirty-page state is unknown, so the only honest
        move is a new connection plus a full replay of the parked
        journal. A file the reopen finds corrupt takes the move-aside
        path like any boot would."""
        with self._lock:
            try:
                self._conn.close()
            except sqlite3.Error:
                pass            # a poisoned handle may refuse to close
            try:
                self._conn = self._open_verified(self.path)
            except CorruptStoreError as exc:
                self._conn = self._recreate_aside(self.path, exc)

    def put(self, bucket, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (bucket, key, value) VALUES (?, ?, ?) "
                "ON CONFLICT(bucket, key) DO UPDATE SET value=excluded.value",
                (bucket, key, value))
            self._conn.commit()

    def get(self, bucket, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE bucket=? AND key=?",
                (bucket, key)).fetchone()
        return row[0] if row else None

    def delete(self, bucket, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE bucket=? AND key=?",
                               (bucket, key))
            self._conn.commit()

    def delete_prefix(self, bucket, prefix):
        with self._lock:
            self._conn.execute(
                "DELETE FROM kv WHERE bucket=? AND key GLOB ?",
                (bucket, prefix.replace("[", "[[]") + "*"))
            self._conn.commit()

    def all(self, bucket):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE bucket=?", (bucket,)).fetchall()
        return dict(rows)

    def apply_batch(self, ops):
        """Group commit (ADR 014): the whole batch is ONE transaction —
        one fsync per batch under synchronous=FULL, and a crash leaves
        either all of it or none of it."""
        mid = len(ops) // 2
        with self._lock:
            try:
                for i, (kind, bucket, key, value) in enumerate(ops):
                    if i == mid:
                        # ADR 024: die INSIDE the open transaction —
                        # statements executed, nothing committed; the
                        # restart must see all-or-nothing
                        faults.crash_point("mid_wal_write")
                    if kind == "put":
                        self._conn.execute(
                            "INSERT INTO kv (bucket, key, value) "
                            "VALUES (?, ?, ?) ON CONFLICT(bucket, key) "
                            "DO UPDATE SET value=excluded.value",
                            (bucket, key, value))
                    elif kind == "delete":
                        self._conn.execute(
                            "DELETE FROM kv WHERE bucket=? AND key=?",
                            (bucket, key))
                    else:
                        self._conn.execute(
                            "DELETE FROM kv WHERE bucket=? AND key GLOB ?",
                            (bucket, key.replace("[", "[[]") + "*"))
                self._conn.commit()
            except BaseException:
                self._conn.rollback()
                raise

    def close(self):
        with self._lock:
            self._conn.commit()
            self._conn.close()
