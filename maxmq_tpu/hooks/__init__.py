"""Hook/plugin layer: event boundary, auth hooks, persistence."""

from .auth import ACLRule, AllowHook, AuthRule, Ledger, LedgerHook
from .base import Hook, Hooks, RejectPacket
from .journal import WriteBehindStore
from .storage import (ClientRecord, MemoryStore, MessageRecord, SQLiteStore,
                      StorageHook, Store, SubscriptionRecord)

__all__ = [
    "ACLRule", "AllowHook", "AuthRule", "Ledger", "LedgerHook",
    "Hook", "Hooks", "RejectPacket",
    "ClientRecord", "MemoryStore", "MessageRecord", "SQLiteStore",
    "StorageHook", "Store", "SubscriptionRecord", "WriteBehindStore",
]
