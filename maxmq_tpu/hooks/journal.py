"""Write-behind journal for the persistence pipeline (ADR 014).

The seed storage hook fsynced SQLite on the broker's asyncio loop for
every QoS1 publish/ack/retain event — durability policy was "pay a
disk flush per message, on the event loop". :class:`WriteBehindStore`
puts a bounded, byte-accounted journal between the hook's writes and
the real store: the event loop only appends to an in-memory op queue
(O(dict insert)), and a dedicated writer thread drains it in *group
commits* — one backend transaction per batch of ops, one fsync per
transaction. Durability is a policy, not an accident:

* ``always``  — QoS acks are released through a *durability barrier*:
  the broker asks for a barrier future after a publish's writes are
  enqueued, and the ack goes out only once the writer thread has
  committed past them. Group commit still applies (everything that
  accumulated during the previous fsync rides the next one), so
  throughput scales with concurrent publishers instead of being
  serialized at one fsync per message.
* ``batched`` — writes commit every ``batch_ms``/``batch_ops``; acks
  release immediately. A crash can lose up to the configured window
  of ACKED traffic (documented in docs/adr/014).
* ``off``     — same write path, but the backend is opened without
  synchronous flushing (SQLite ``synchronous=OFF``); survives process
  crashes, not power loss.

Storage degradation ladder (the ADR 011/012 discipline for disks):
consecutive *commit* failures trip a circuit breaker — the journal
stops burning the writer thread on a dead backend and keeps accepting
writes in memory (the parked journal) with ``dirty`` set; after a
capped-exponential backoff a half-open reprobe commits one small
batch, and on success the parked journal replays in order. Barriers
never wedge the broker: opening the breaker releases every pending
barrier (availability over durability, loudly counted), and new
barriers while degraded resolve immediately.

Same-key writes *coalesce in place* (a retained topic republished at
1Hz costs one queued op, not one per publish), so the queue grows with
distinct keys touched since the last commit, not with write rate. The
byte budget (``queue_bytes``) is a watermark, not a hard drop line:
QoS1-relevant ops are never discarded here — above the watermark the
StorageHook sheds QoS0-irrelevant rewrites (hooks/storage.py) and
``overflows`` counts what still lands past it.

Disk-failure classes (ADR 024) get their own ladder rungs on top of
the generic breaker:

* **fsync failure poisons the connection** (fsyncgate): after a failed
  flush the backend's dirty-page state is unknown — retrying the
  commit on the same handle could "succeed" against pages the kernel
  already dropped. The journal marks the backend poisoned, trips the
  breaker immediately, and the half-open reprobe REOPENS the backend
  before replaying the parked journal (replay is idempotent same-key
  upserts, so anything that did reach the platter commits again,
  harmlessly).
* **ENOSPC is not transient**: a full volume won't heal by politely
  retrying the same batch, so the breaker trips on the FIRST ENOSPC
  (no threshold wait), ``disk_full`` raises the QoS0-irrelevant
  rewrite shed rung in hooks/storage.py regardless of broker load,
  and barriers release degraded (ADR-011 availability over
  durability) until a commit succeeds again.

Fault sites (faults.py): ``storage.put`` at the enqueue boundary,
``storage.commit`` in the writer thread (hang mode sleeps the WRITER,
never the loop — which is the point), ``storage.restore`` in the
hook's per-record restore parse, plus the backend-level ``disk.*``
family via hooks/faultstore.py. Crash points (ADR 024):
``crash.at#pre_fsync`` / ``crash.at#post_fsync_pre_ack`` bracket the
group commit — the two instants whose durability semantics differ.
"""

from __future__ import annotations

import errno
import heapq
import itertools
import logging
import threading
import time
from collections import deque

from .. import faults
from .faultstore import FsyncFailed
from .storage import Store

_OP_PUT = "put"
_OP_DELETE = "delete"
_OP_DELETE_PREFIX = "delete_prefix"

# breaker states (numeric for the gauge, mirroring the ADR-011 matcher
# breaker's exposition: 0 closed, 1 open, 2 half-open)
BREAKER_CLOSED = 0
BREAKER_OPEN = 1
BREAKER_HALF_OPEN = 2

# map the storage_sync policy onto SQLite's synchronous pragma: the
# group commit supplies the batching; the pragma decides whether each
# commit reaches the platter before the transaction returns
SQLITE_SYNC_BY_POLICY = {"always": "FULL", "batched": "FULL", "off": "OFF"}

POLICIES = ("always", "batched", "off")


def classify_commit_failure(exc: Exception) -> str:
    """Sort a commit failure into its ladder rung (ADR 024):
    ``"fsync"`` (poison + reopen), ``"enospc"`` (immediate breaker +
    disk-full shed), or ``"other"`` (the generic consecutive-failure
    breaker). Recognizes both the injected ``disk.*`` shapes and what
    the real backends raise — sqlite3 reports a full volume as
    OperationalError("database or disk is full")."""
    if isinstance(exc, FsyncFailed):
        return "fsync"
    if isinstance(exc, OSError) and exc.errno == errno.ENOSPC:
        return "enospc"
    msg = str(exc).lower()
    if "disk is full" in msg or "no space left" in msg:
        return "enospc"
    if "fsync" in msg:
        return "fsync"
    return "other"


class _Op:
    __slots__ = ("seq", "kind", "bucket", "key", "value", "size")

    def __init__(self, seq: int, kind: str, bucket: str, key: str,
                 value: str | None, size: int) -> None:
        self.seq = seq
        self.kind = kind
        self.bucket = bucket
        self.key = key
        self.value = value
        self.size = size


def _op_size(bucket: str, key: str, value: str | None) -> int:
    # 64 covers the _Op object + dict/deque slots; precision doesn't
    # matter, monotonicity with payload size does
    return len(bucket) + len(key) + (len(value) if value else 0) + 64


class WriteBehindStore(Store):
    """A :class:`Store` that journals writes in memory and drains them
    to ``inner`` from a dedicated writer thread with group commit.

    Reads (``get``/``all``) overlay the pending journal on the inner
    store, so a restore that races an unflushed shutdown still sees
    every write. All counters are plain ints read tear-free by the
    metrics scrape thread (the SysInfo contract)."""

    def __init__(self, inner: Store, *, policy: str = "batched",
                 batch_ms: int = 20, batch_ops: int = 512,
                 queue_bytes: int = 4 << 20,
                 breaker_threshold: int = 5,
                 backoff_s: float = 1.0, backoff_max_s: float = 30.0,
                 logger=None) -> None:
        if policy not in POLICIES:
            raise ValueError(f"unknown storage_sync policy {policy!r} "
                             f"(want one of {POLICIES})")
        self.inner = inner
        self.policy = policy
        self.batch_ms = max(int(batch_ms), 0)
        self.batch_ops = max(int(batch_ops), 1)
        self.queue_bytes = max(int(queue_bytes), 0)
        self.breaker_threshold = max(int(breaker_threshold), 1)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.log = logger or logging.getLogger("maxmq.storage")

        self._lock = threading.Lock()
        self._work = threading.Event()
        self._order: deque[_Op] = deque()
        self._pending: dict[tuple[str, str], _Op] = {}
        # last seq at which each bucket saw a delete_prefix: a same-key
        # put AFTER a pending prefix delete must not coalesce into an
        # op that would apply BEFORE it
        self._prefix_seq: dict[str, int] = {}
        self._seq = 0
        self.committed_seq = 0
        self._barriers: list[tuple[int, int, object, object]] = []
        self._bar_count = itertools.count()

        # -- observability (maxmq_storage_* + $SYS/broker/storage/*) --
        self.queued_bytes_now = 0
        self.commits = 0
        self.commit_failures = 0
        self.put_failures = 0
        self.ops_written = 0
        self.coalesced = 0
        self.overflows = 0
        self.barrier_waits = 0
        self.barriers_released_degraded = 0
        self.last_batch_ops = 0
        self.largest_batch_ops = 0
        self.last_commit_s = 0.0
        self.commit_seconds_total = 0.0
        self.dirty = False              # a write was lost or parked past
                                        # its durability promise

        # -- disk-failure ladder rungs (ADR 024) -----------------------
        self.fsync_failures = 0         # commits whose flush failed
        self.enospc_failures = 0        # commits refused by a full disk
        self.backend_reopens = 0        # poisoned connections reopened
        self.disk_full = False          # last failure was ENOSPC and no
                                        # commit has succeeded since —
                                        # raises the storage hook's
                                        # rewrite-shed rung unconditionally
        self._poisoned = False          # fsync failed: the backend must
                                        # be reopened before any retry

        # -- breaker ---------------------------------------------------
        self.breaker_state = BREAKER_CLOSED
        self.breaker_trips = 0
        self.breaker_recoveries = 0
        self._consecutive_failures = 0
        self._cur_backoff = self.backoff_s
        self._reprobe_at = 0.0
        self._degraded_since = 0.0
        self._degraded_seconds = 0.0

        # ADR 015: broker.serve() attaches its PipelineTracer here so
        # the WRITER THREAD can feed the journal_commit stage histogram
        # and attribute commit/put failures to the journal stage
        self.tracer = None

        self._stopped = False
        self._final_probe_done = False
        self._thread = threading.Thread(
            target=self._writer_loop, name="storage-journal", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # Store interface (event-loop side: never blocks on the backend)
    # ------------------------------------------------------------------

    def put(self, bucket: str, key: str, value: str) -> None:
        try:
            faults.fire(faults.STORAGE_PUT)
        except faults.InjectedFault:
            self.put_failures += 1
            self.dirty = True
            if self.tracer is not None:
                self.tracer.note_error("journal_commit", "put_failed")
            return
        self._enqueue(_OP_PUT, bucket, key, value)

    def delete(self, bucket: str, key: str) -> None:
        self._enqueue(_OP_DELETE, bucket, key, None)

    def delete_prefix(self, bucket: str, prefix: str) -> None:
        self._enqueue(_OP_DELETE_PREFIX, bucket, prefix, None)

    def get(self, bucket: str, key: str) -> str | None:
        with self._lock:
            ops = [op for op in self._order if op.bucket == bucket]
        value = self.inner.get(bucket, key)
        for op in ops:
            if op.kind == _OP_DELETE_PREFIX:
                if key.startswith(op.key):
                    value = None
            elif op.key == key:
                value = op.value if op.kind == _OP_PUT else None
        return value

    def all(self, bucket: str) -> dict[str, str]:
        # snapshot the overlay FIRST: an op the writer commits between
        # the two reads is then applied twice, which is idempotent —
        # the reverse order would lose it entirely
        with self._lock:
            ops = [op for op in self._order if op.bucket == bucket]
        data = self.inner.all(bucket)
        for op in ops:
            if op.kind == _OP_PUT:
                data[op.key] = op.value
            elif op.kind == _OP_DELETE:
                data.pop(op.key, None)
            else:
                for k in [k for k in data if k.startswith(op.key)]:
                    del data[k]
        return data

    def close(self) -> None:
        """Flush what the backend will take, stop the writer, close the
        backend. A breaker stuck open gets one forced final attempt; a
        still-dead backend loses the parked journal LOUDLY."""
        self._stopped = True
        self._work.set()
        self._thread.join(timeout=10.0)
        with self._lock:
            lost = len(self._order)
        if lost:
            self.dirty = True
            self.log.error(
                "storage journal closed with %d uncommitted ops "
                "(backend unavailable); parked writes lost", lost)
        self.inner.close()

    # ------------------------------------------------------------------
    # Journal plumbing
    # ------------------------------------------------------------------

    def _enqueue(self, kind: str, bucket: str, key: str,
                 value: str | None) -> None:
        size = _op_size(bucket, key, value)
        wake = False
        with self._lock:
            if kind == _OP_DELETE_PREFIX:
                self._seq += 1
                self._prefix_seq[bucket] = self._seq
                op = _Op(self._seq, kind, bucket, key, None, size)
                self._order.append(op)
                self.queued_bytes_now += size
            else:
                prev = self._pending.get((bucket, key))
                if (prev is not None
                        and prev.seq > self._prefix_seq.get(bucket, 0)):
                    # coalesce in place: the queued op keeps its seq
                    # (so barriers taken before this write still cover
                    # it — the newer value commits at the OLD position)
                    self.queued_bytes_now += size - prev.size
                    prev.kind, prev.value, prev.size = kind, value, size
                    self.coalesced += 1
                else:
                    self._seq += 1
                    op = _Op(self._seq, kind, bucket, key, value, size)
                    self._order.append(op)
                    self._pending[(bucket, key)] = op
                    self.queued_bytes_now += size
            if self.queue_bytes and self.queued_bytes_now > self.queue_bytes:
                self.overflows += 1
            wake = True
        if wake:
            self._work.set()

    @property
    def over_watermark(self) -> bool:
        """True when the journal sits past its byte budget — the signal
        hooks/storage.py uses to shed QoS0-irrelevant rewrites."""
        return bool(self.queue_bytes
                    and self.queued_bytes_now > self.queue_bytes)

    @property
    def queue_depth(self) -> int:
        return len(self._order)

    @property
    def degraded_seconds(self) -> float:
        extra = (time.monotonic() - self._degraded_since
                 if self.breaker_state != BREAKER_CLOSED else 0.0)
        return self._degraded_seconds + extra

    # -- durability barrier --------------------------------------------

    @property
    def barrier_needed(self) -> bool:
        """True when QoS acks must wait on a durability barrier
        (``storage_sync=always``). ``batched``/``off`` release acks
        immediately; what that can lose is ADR-014 documented."""
        return self.policy == "always"

    def barrier(self, loop):
        """An asyncio future resolved once everything enqueued so far is
        durable, or ``None`` when no wait is required (non-``always``
        policy, an idle journal, or a degraded breaker — a dead disk
        must not become a dead broker)."""
        if self.policy != "always":
            return None
        with self._lock:
            if self.breaker_state != BREAKER_CLOSED:
                self.dirty = True
                return None
            if not self._order and self.committed_seq >= self._seq:
                return None
            fut = loop.create_future()
            heapq.heappush(self._barriers,
                           (self._seq, next(self._bar_count), fut, loop))
            self.barrier_waits += 1
        self._work.set()
        return fut

    def flush(self, timeout: float = 5.0) -> bool:
        """Block (caller's thread) until the journal is fully committed;
        boot-time only (boot_epoch durability) — never on the loop while
        serving. False on timeout or a degraded backend."""
        self._work.set()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._order and self.committed_seq >= self._seq:
                    return True
                if self.breaker_state == BREAKER_OPEN:
                    return False
            time.sleep(0.002)
        return False

    def _resolve_barriers_locked(self, up_to_seq: int | None,
                                 degraded: bool = False) -> None:
        """Release barriers ≤ ``up_to_seq`` (None = all). Runs under
        the lock; resolution hops to each barrier's loop thread."""
        while self._barriers and (up_to_seq is None
                                  or self._barriers[0][0] <= up_to_seq):
            _seq, _n, fut, loop = heapq.heappop(self._barriers)
            if degraded:
                self.barriers_released_degraded += 1

            def _set(f=fut):
                if not f.done():
                    f.set_result(None)
            try:
                loop.call_soon_threadsafe(_set)
            except RuntimeError:
                pass    # loop already closed; nothing waits anymore

    # ------------------------------------------------------------------
    # Writer thread: group commit + breaker
    # ------------------------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            try:
                if not self._writer_turn():
                    return
            except Exception:       # the journal must outlive surprises
                self.log.exception("storage journal writer turn failed")
                time.sleep(0.05)

    def _writer_turn(self) -> bool:
        """One scheduling turn: wait for work, honor the breaker's
        backoff, drain one group commit. False = thread exits."""
        with self._lock:
            empty = not self._order
        if empty:
            if self._stopped:
                return False
            self._work.wait(timeout=0.2)
            self._work.clear()
            return True
        now = time.monotonic()
        if self.breaker_state == BREAKER_OPEN:
            if self._stopped:
                # close() grants ONE final reprobe; a still-dead
                # backend must not spin this thread forever
                if self._final_probe_done:
                    return False
                self._final_probe_done = True
            elif now < self._reprobe_at:
                self._work.wait(timeout=min(0.05, self._reprobe_at - now))
                self._work.clear()
                return True
            self.breaker_state = BREAKER_HALF_OPEN  # reprobe window
        elif (self.policy != "always" and self.batch_ms > 0
                and not self._stopped):
            # accumulate a batch window; `always` drains eagerly (group
            # commit forms naturally from whatever arrived mid-fsync)
            time.sleep(self.batch_ms / 1000.0)
        self._commit_batch()
        return True

    def _take_batch_locked(self, n: int) -> list[_Op]:
        batch: list[_Op] = []
        while self._order and len(batch) < n:
            op = self._order.popleft()
            batch.append(op)
            if (op.kind != _OP_DELETE_PREFIX
                    and self._pending.get((op.bucket, op.key)) is op):
                del self._pending[(op.bucket, op.key)]
        return batch

    def _commit_batch(self) -> None:
        # half-open probes with ONE op: a reprobe against a dead backend
        # should cost one failure, not re-fail the whole parked journal
        n = 1 if self.breaker_state == BREAKER_HALF_OPEN else self.batch_ops
        with self._lock:
            batch = self._take_batch_locked(n)
        if not batch:
            return
        t0 = time.perf_counter()
        try:
            faults.fire(faults.STORAGE_COMMIT)
            if self._poisoned:
                # fsyncgate discipline (ADR 024): never retry on the
                # handle whose flush failed — reopen first, then the
                # parked journal replays through the fresh connection
                self._reopen_poisoned()
            faults.crash_point("pre_fsync")
            self.inner.apply_batch(
                [(op.kind, op.bucket, op.key, op.value) for op in batch])
            faults.crash_point("post_fsync_pre_ack")
        except Exception as exc:
            self._commit_failed(batch, exc)
            return
        dt = time.perf_counter() - t0
        if self.tracer is not None:
            # ADR 015: group-commit duration, observed from the writer
            # thread (histogram-only: a commit covers many publishes)
            self.tracer.observe("journal_commit", dt)
            # ADR 017 (closing ADR-015's per-op attribution item): the
            # same commit attributed to each storage bucket it touched,
            # so "which writes own the fsync time" is answerable
            for bucket in {op.bucket for op in batch}:
                self.tracer.observe_journal(bucket, dt)
        with self._lock:
            self.committed_seq = max(self.committed_seq, batch[-1].seq)
            self.queued_bytes_now -= sum(op.size for op in batch)
            self._resolve_barriers_locked(self.committed_seq)
            self.commits += 1
            self.ops_written += len(batch)
            self.last_batch_ops = len(batch)
            self.largest_batch_ops = max(self.largest_batch_ops, len(batch))
            self.last_commit_s = dt
            self.commit_seconds_total += dt
            if self.breaker_state != BREAKER_CLOSED:
                # half-open reprobe succeeded: close, and the normal
                # drain (next turns) replays the parked journal in order
                self.breaker_state = BREAKER_CLOSED
                self.breaker_recoveries += 1
                self._degraded_seconds += time.monotonic() - self._degraded_since
                self._cur_backoff = self.backoff_s
            self._consecutive_failures = 0
            if self.disk_full:
                self.disk_full = False      # space came back; rung down
                self.log.warning("storage disk-full condition cleared "
                                 "(commit succeeded)")

    def _reopen_poisoned(self) -> None:
        """Swap the poisoned backend connection for a fresh one (ADR
        024). Raises on failure — the caller's commit then fails and
        the breaker/backoff machinery owns the retry cadence. A backend
        without ``reopen`` (bare MemoryStore in tests) just clears the
        poison: it has no kernel page cache to distrust."""
        reopen = getattr(self.inner, "reopen", None)
        if reopen is not None:
            reopen()
            self.backend_reopens += 1
        self._poisoned = False
        self.log.warning("storage backend reopened after fsync failure; "
                         "replaying %d parked ops", self.queue_depth)

    def _commit_failed(self, batch: list[_Op], exc: Exception) -> None:
        if self.tracer is not None:
            self.tracer.note_error("journal_commit", "commit_failed")
        failure_class = classify_commit_failure(exc)
        with self._lock:
            # park the batch back at the FRONT, preserving op order; a
            # same-key write enqueued while the commit ran owns
            # _pending already and must keep it (it is newer)
            self._order.extendleft(reversed(batch))
            for op in batch:
                key = (op.bucket, op.key)
                if op.kind != _OP_DELETE_PREFIX and key not in self._pending:
                    self._pending[key] = op
            self.commit_failures += 1
            self._consecutive_failures += 1
            self.dirty = True
            if failure_class == "fsync":
                # fsyncgate: the handle is now untrustworthy — poison
                # it and trip immediately; the reprobe reopens first
                self.fsync_failures += 1
                self._poisoned = True
            elif failure_class == "enospc":
                # a full disk is a state, not a blip: no point burning
                # threshold-many retries against it
                self.enospc_failures += 1
                self.disk_full = True
            tripped = (self.breaker_state == BREAKER_HALF_OPEN
                       or failure_class in ("fsync", "enospc")
                       or self._consecutive_failures >= self.breaker_threshold)
            if tripped:
                if self.breaker_state == BREAKER_CLOSED:
                    self._degraded_since = time.monotonic()
                self.breaker_state = BREAKER_OPEN
                self.breaker_trips += 1
                self._reprobe_at = time.monotonic() + self._cur_backoff
                self._cur_backoff = min(self._cur_backoff * 2,
                                        self.backoff_max_s)
                # a barrier must never outlive the durability it was
                # promised: release them all, loudly, and stay dirty
                self._resolve_barriers_locked(None, degraded=True)
        self.log.error("storage commit failed (%s, %d consecutive): %r",
                       failure_class, self._consecutive_failures, exc)
