"""MQTT reason codes (v5) and their v3.1.1 CONNACK mappings.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/packets/codes.go in the
reference (reason-code table and v5->v3 CONNACK downgrade). Re-derived from the
MQTT 3.1.1 / 5.0 specifications, not translated.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Code:
    """A reason code carried in acks/disconnects; failure when >= 0x80."""

    value: int
    reason: str = ""

    @property
    def is_error(self) -> bool:
        return self.value >= 0x80

    def __int__(self) -> int:  # convenience for encoders
        return self.value


# -- success codes -----------------------------------------------------------
Success = Code(0x00, "success")
GrantedQos0 = Code(0x00, "granted qos 0")
GrantedQos1 = Code(0x01, "granted qos 1")
GrantedQos2 = Code(0x02, "granted qos 2")
DisconnectWithWill = Code(0x04, "disconnect with will message")
NoMatchingSubscribers = Code(0x10, "no matching subscribers")
NoSubscriptionExisted = Code(0x11, "no subscription existed")
ContinueAuthentication = Code(0x18, "continue authentication")
ReAuthenticate = Code(0x19, "re-authenticate")

# -- error codes -------------------------------------------------------------
ErrUnspecifiedError = Code(0x80, "unspecified error")
ErrMalformedPacket = Code(0x81, "malformed packet")
ErrProtocolViolation = Code(0x82, "protocol error")
ErrImplementationSpecificError = Code(0x83, "implementation specific error")
ErrUnsupportedProtocolVersion = Code(0x84, "unsupported protocol version")
ErrClientIdentifierNotValid = Code(0x85, "client identifier not valid")
ErrBadUsernameOrPassword = Code(0x86, "bad username or password")
ErrNotAuthorized = Code(0x87, "not authorized")
ErrServerUnavailable = Code(0x88, "server unavailable")
ErrServerBusy = Code(0x89, "server busy")
ErrBanned = Code(0x8A, "banned")
ErrServerShuttingDown = Code(0x8B, "server shutting down")
ErrBadAuthenticationMethod = Code(0x8C, "bad authentication method")
ErrKeepAliveTimeout = Code(0x8D, "keep alive timeout")
ErrSessionTakenOver = Code(0x8E, "session taken over")
ErrTopicFilterInvalid = Code(0x8F, "topic filter invalid")
ErrTopicNameInvalid = Code(0x90, "topic name invalid")
ErrPacketIdentifierInUse = Code(0x91, "packet identifier in use")
ErrPacketIdentifierNotFound = Code(0x92, "packet identifier not found")
ErrReceiveMaximumExceeded = Code(0x93, "receive maximum exceeded")
ErrTopicAliasInvalid = Code(0x94, "topic alias invalid")
ErrPacketTooLarge = Code(0x95, "packet too large")
ErrMessageRateTooHigh = Code(0x96, "message rate too high")
ErrQuotaExceeded = Code(0x97, "quota exceeded")
ErrAdministrativeAction = Code(0x98, "administrative action")
ErrPayloadFormatInvalid = Code(0x99, "payload format invalid")
ErrRetainNotSupported = Code(0x9A, "retain not supported")
ErrQosNotSupported = Code(0x9B, "qos not supported")
ErrUseAnotherServer = Code(0x9C, "use another server")
ErrServerMoved = Code(0x9D, "server moved")
ErrSharedSubscriptionsNotSupported = Code(0x9E, "shared subscriptions not supported")
ErrConnectionRateExceeded = Code(0x9F, "connection rate exceeded")
ErrMaximumConnectTime = Code(0xA0, "maximum connect time")
ErrSubscriptionIdentifiersNotSupported = Code(0xA1, "subscription identifiers not supported")
ErrWildcardSubscriptionsNotSupported = Code(0xA2, "wildcard subscriptions not supported")

# Internal pseudo-codes (never sent on the wire) used by the broker runtime.
ErrPacketEmpty = Code(0xFE, "packet empty")
ErrInvalidPacketType = Code(0xFD, "invalid packet type")

# v5 reason code -> MQTT 3.1.1 CONNACK return code (spec table 3.1).
_V3_CONNACK = {
    ErrUnsupportedProtocolVersion.value: 0x01,
    ErrClientIdentifierNotValid.value: 0x02,
    ErrServerUnavailable.value: 0x03,
    ErrServerBusy.value: 0x03,
    ErrBadUsernameOrPassword.value: 0x04,
    ErrBadAuthenticationMethod.value: 0x04,
    ErrNotAuthorized.value: 0x05,
    ErrBanned.value: 0x05,
}


def connack_for_version(code: Code, protocol_version: int) -> int:
    """Downgrade a v5 CONNACK reason code for v3.x clients."""
    if protocol_version >= 5 or not code.is_error:
        return code.value
    return _V3_CONNACK.get(code.value, 0x03)
