"""MQTT wire protocol: codec primitives, properties, reason codes, packets."""

from . import codes
from .codec import FixedHeader, MalformedPacketError, PacketType
from .packets import Packet, ProtocolError, Subscription, Will, parse_stream
from .properties import Properties

__all__ = [
    "codes", "FixedHeader", "MalformedPacketError", "PacketType",
    "Packet", "ProtocolError", "Subscription", "Will", "parse_stream",
    "Properties",
]
