"""Wire-level primitives for the MQTT codec.

Big-endian integers, length-prefixed UTF-8 strings / binary blobs, and the
variable-byte integer used by the fixed header and v5 properties.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/packets/codec.go and
fixedheader.go in the reference. Re-implemented from the MQTT spec.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "MalformedPacketError",
    "read_uint16",
    "read_uint32",
    "read_string",
    "read_binary",
    "read_varint",
    "write_uint16",
    "write_uint32",
    "write_string",
    "write_binary",
    "write_varint",
    "varint_len",
    "valid_utf8_string",
    "FixedHeader",
    "PacketType",
]


class MalformedPacketError(ValueError):
    """Raised when wire bytes violate the MQTT encoding rules."""


class PacketType:
    RESERVED = 0
    CONNECT = 1
    CONNACK = 2
    PUBLISH = 3
    PUBACK = 4
    PUBREC = 5
    PUBREL = 6
    PUBCOMP = 7
    SUBSCRIBE = 8
    SUBACK = 9
    UNSUBSCRIBE = 10
    UNSUBACK = 11
    PINGREQ = 12
    PINGRESP = 13
    DISCONNECT = 14
    AUTH = 15

    NAMES = {
        1: "CONNECT", 2: "CONNACK", 3: "PUBLISH", 4: "PUBACK", 5: "PUBREC",
        6: "PUBREL", 7: "PUBCOMP", 8: "SUBSCRIBE", 9: "SUBACK",
        10: "UNSUBSCRIBE", 11: "UNSUBACK", 12: "PINGREQ", 13: "PINGRESP",
        14: "DISCONNECT", 15: "AUTH",
    }


# ---------------------------------------------------------------------------
# Readers: each takes (buf, offset) and returns (value, new_offset).
# ---------------------------------------------------------------------------

def read_uint16(buf: bytes, off: int) -> tuple[int, int]:
    if off + 2 > len(buf):
        raise MalformedPacketError("truncated uint16")
    return (buf[off] << 8) | buf[off + 1], off + 2


def read_uint32(buf: bytes, off: int) -> tuple[int, int]:
    if off + 4 > len(buf):
        raise MalformedPacketError("truncated uint32")
    return int.from_bytes(buf[off:off + 4], "big"), off + 4


def read_binary(buf: bytes, off: int) -> tuple[bytes, int]:
    n, off = read_uint16(buf, off)
    if off + n > len(buf):
        raise MalformedPacketError("truncated binary data")
    return bytes(buf[off:off + n]), off + n


def valid_utf8_string(data: bytes) -> bool:
    """MQTT-1.5.3: well-formed UTF-8 with no U+0000 and no UTF-16 surrogates."""
    try:
        s = data.decode("utf-8", errors="strict")
    except UnicodeDecodeError:
        return False
    return "\x00" not in s


def read_string(buf: bytes, off: int) -> tuple[str, int]:
    data, off = read_binary(buf, off)
    if not valid_utf8_string(data):
        raise MalformedPacketError("invalid utf-8 string")
    return data.decode("utf-8"), off


def read_varint(buf: bytes, off: int) -> tuple[int, int]:
    """Variable byte integer, at most 4 bytes (max 268,435,455)."""
    value = 0
    shift = 0
    for i in range(4):
        if off + i >= len(buf):
            raise MalformedPacketError("truncated variable byte integer")
        b = buf[off + i]
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, off + i + 1
        shift += 7
    raise MalformedPacketError("variable byte integer too long")


# ---------------------------------------------------------------------------
# Writers: append to a bytearray.
# ---------------------------------------------------------------------------

def write_uint16(out: bytearray, value: int) -> None:
    out.append((value >> 8) & 0xFF)
    out.append(value & 0xFF)


def write_uint32(out: bytearray, value: int) -> None:
    out.extend(value.to_bytes(4, "big"))


def write_binary(out: bytearray, data: bytes) -> None:
    if len(data) > 0xFFFF:
        raise MalformedPacketError("binary data exceeds 65535 bytes")
    write_uint16(out, len(data))
    out.extend(data)


def write_string(out: bytearray, s: str) -> None:
    write_binary(out, s.encode("utf-8"))


def write_varint(out: bytearray, value: int) -> None:
    if value < 0 or value > 268_435_455:
        raise MalformedPacketError("variable byte integer out of range")
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def varint_len(value: int) -> int:
    if value < 128:
        return 1
    if value < 16_384:
        return 2
    if value < 2_097_152:
        return 3
    return 4


# ---------------------------------------------------------------------------
# Fixed header
# ---------------------------------------------------------------------------

_FLAGS_REQUIRED = {  # packet type -> required flag nibble (None = variable)
    PacketType.CONNECT: 0, PacketType.CONNACK: 0, PacketType.PUBACK: 0,
    PacketType.PUBREC: 0, PacketType.PUBREL: 2, PacketType.PUBCOMP: 0,
    PacketType.SUBSCRIBE: 2, PacketType.SUBACK: 0, PacketType.UNSUBSCRIBE: 2,
    PacketType.UNSUBACK: 0, PacketType.PINGREQ: 0, PacketType.PINGRESP: 0,
    PacketType.DISCONNECT: 0, PacketType.AUTH: 0,
}


@dataclass
class FixedHeader:
    """First byte (type + flags) and remaining length of every MQTT packet."""

    type: int = 0
    dup: bool = False
    qos: int = 0
    retain: bool = False
    remaining: int = 0

    def encode(self, out: bytearray) -> None:
        b = (self.type << 4)
        if self.type == PacketType.PUBLISH:
            b |= (0x8 if self.dup else 0) | ((self.qos & 0x3) << 1) | (1 if self.retain else 0)
        else:
            b |= _FLAGS_REQUIRED.get(self.type, 0)
        out.append(b)
        write_varint(out, self.remaining)

    @classmethod
    def decode(cls, first_byte: int, remaining: int) -> "FixedHeader":
        ptype = (first_byte >> 4) & 0xF
        flags = first_byte & 0xF
        fh = cls(type=ptype, remaining=remaining)
        if ptype == PacketType.PUBLISH:
            fh.dup = bool(flags & 0x8)
            fh.qos = (flags >> 1) & 0x3
            fh.retain = bool(flags & 0x1)
            if fh.qos == 3:
                raise MalformedPacketError("publish qos 3 is malformed")
            # dup with qos 0 violates the SENDER requirement [MQTT-3.3.1-2]
            # but the receive side tolerates it, as the reference does
            # (tpackets.go TPublishDup is a pass case); the broker clears
            # dup on forward regardless
        else:
            required = _FLAGS_REQUIRED.get(ptype)
            if required is None:
                raise MalformedPacketError(f"reserved packet type {ptype}")
            if flags != required:
                raise MalformedPacketError(
                    f"bad fixed-header flags {flags:#x} for type {ptype}")
        return fh
