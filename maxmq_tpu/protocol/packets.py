"""MQTT control packets: one concrete ``Packet`` model + per-type codecs.

All 15 packet types for protocol versions 3 (MQTT 3.1), 4 (MQTT 3.1.1) and
5 (MQTT 5.0). Properties blocks are encoded/decoded only for v5.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/packets/packets.go in the
reference (single Packet struct, per-type Encode/Decode/Validate). Re-derived
from the OASIS MQTT specifications.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from . import codes
from .codec import (
    FixedHeader,
    MalformedPacketError,
    PacketType as PT,
    read_binary,
    read_string,
    read_uint16,
    valid_utf8_string,
    write_binary,
    write_string,
    write_uint16,
)
from .properties import Properties, blank_properties

PROTOCOL_NAMES = {3: "MQIsdp", 4: "MQTT", 5: "MQTT"}


class ProtocolError(ValueError):
    """A spec violation that must terminate the network connection."""

    def __init__(self, code: codes.Code, detail: str = ""):
        super().__init__(detail or code.reason)
        self.code = code


@dataclass
class Subscription:
    """One topic filter within SUBSCRIBE, plus v5 subscription options."""

    filter: str
    qos: int = 0
    no_local: bool = False
    retain_as_published: bool = False
    retain_handling: int = 0
    identifier: int = 0  # v5 subscription identifier attached at subscribe time
    # Merged view when one client holds several overlapping matching filters.
    identifiers: dict[str, int] = field(default_factory=dict)

    def options_byte(self) -> int:
        return ((self.qos & 0x3)
                | (0x04 if self.no_local else 0)
                | (0x08 if self.retain_as_published else 0)
                | ((self.retain_handling & 0x3) << 4))

    @classmethod
    def from_options_byte(cls, filter_: str, b: int, v5: bool) -> "Subscription":
        if (b & 0x3) == 3:
            raise MalformedPacketError("subscription qos 3 is malformed")  # [MQTT-3.8.3-4]
        if v5:
            if b & 0xC0:
                raise MalformedPacketError("subscription options reserved bits set")
            rh = (b >> 4) & 0x3
            if rh == 3:
                raise MalformedPacketError("retain handling 3 is malformed")
            return cls(filter=filter_, qos=b & 0x3, no_local=bool(b & 0x04),
                       retain_as_published=bool(b & 0x08), retain_handling=rh)
        if b & 0xFC:
            raise MalformedPacketError("subscription options reserved bits set")
        return cls(filter=filter_, qos=b & 0x3)


@dataclass
class Will:
    """Last Will & Testament captured from CONNECT."""

    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    properties: Properties = field(default_factory=Properties)

    @property
    def flag(self) -> bool:
        return bool(self.topic)


@dataclass
class Packet:
    """A decoded (or to-be-encoded) MQTT control packet of any type."""

    fixed: FixedHeader = field(default_factory=FixedHeader)
    protocol_version: int = 4

    # CONNECT
    protocol_name: str = ""
    clean_start: bool = False
    keepalive: int = 0
    client_id: str = ""
    username: bytes = b""
    password: bytes = b""
    username_flag: bool = False
    password_flag: bool = False
    will: Will | None = None

    # CONNACK
    session_present: bool = False

    # PUBLISH / acks / subscribe
    topic: str = ""
    payload: bytes = b""
    packet_id: int = 0
    reason_code: int = 0
    reason_codes: list[int] = field(default_factory=list)  # SUBACK/UNSUBACK
    filters: list[Subscription] = field(default_factory=list)

    properties: Properties = field(default_factory=Properties)

    # Runtime bookkeeping (not wire data).
    created: float = 0.0  # unix seconds; used for inflight/retained expiry
    origin: str = ""      # client id that produced the packet

    @property
    def type(self) -> int:
        return self.fixed.type

    def copy(self) -> "Packet":
        p = Packet(
            fixed=FixedHeader(**self.fixed.__dict__),
            protocol_version=self.protocol_version,
            protocol_name=self.protocol_name,
            clean_start=self.clean_start,
            keepalive=self.keepalive,
            client_id=self.client_id,
            username=self.username,
            password=self.password,
            username_flag=self.username_flag,
            password_flag=self.password_flag,
            session_present=self.session_present,
            topic=self.topic,
            payload=self.payload,
            packet_id=self.packet_id,
            reason_code=self.reason_code,
            reason_codes=list(self.reason_codes),
            properties=self.properties.copy(),
            created=self.created,
            origin=self.origin,
        )
        if self.will is not None:
            p.will = Will(topic=self.will.topic, payload=self.will.payload,
                          qos=self.will.qos, retain=self.will.retain,
                          properties=self.will.properties.copy())
        p.filters = [Subscription(filter=s.filter, qos=s.qos, no_local=s.no_local,
                                  retain_as_published=s.retain_as_published,
                                  retain_handling=s.retain_handling,
                                  identifier=s.identifier,
                                  identifiers=dict(s.identifiers))
                     for s in self.filters]
        return p

    @property
    def v5(self) -> bool:
        return self.protocol_version >= 5

    # ------------------------------------------------------------------
    # Encoding
    # ------------------------------------------------------------------

    def encode(self) -> bytes:  # qa: complex
        body = bytearray()
        t = self.fixed.type
        if t == PT.CONNECT:
            self._enc_connect(body)
        elif t == PT.CONNACK:
            body.append(1 if self.session_present else 0)
            body.append(self.reason_code & 0xFF)
            if self.v5:
                self.properties.encode(body, PT.CONNACK)
        elif t == PT.PUBLISH:
            write_string(body, self.topic)
            if self.fixed.qos > 0:
                write_uint16(body, self.packet_id)
            if self.v5:
                self.properties.encode(body, PT.PUBLISH)
            body.extend(self.payload)
        elif t in (PT.PUBACK, PT.PUBREC, PT.PUBREL, PT.PUBCOMP):
            write_uint16(body, self.packet_id)
            if self.v5:
                if self.reason_code != 0 or not self.properties.is_empty():
                    body.append(self.reason_code & 0xFF)
                    self.properties.encode(body, t)
        elif t == PT.SUBSCRIBE:
            write_uint16(body, self.packet_id)
            if self.v5:
                self.properties.encode(body, PT.SUBSCRIBE)
            for sub in self.filters:
                write_string(body, sub.filter)
                body.append(sub.options_byte() if self.v5 else sub.qos & 0x3)
        elif t == PT.SUBACK:
            write_uint16(body, self.packet_id)
            if self.v5:
                self.properties.encode(body, PT.SUBACK)
            body.extend(c & 0xFF for c in self.reason_codes)
        elif t == PT.UNSUBSCRIBE:
            write_uint16(body, self.packet_id)
            if self.v5:
                self.properties.encode(body, PT.UNSUBSCRIBE)
            for sub in self.filters:
                write_string(body, sub.filter)
        elif t == PT.UNSUBACK:
            write_uint16(body, self.packet_id)
            if self.v5:
                self.properties.encode(body, PT.UNSUBACK)
                body.extend(c & 0xFF for c in self.reason_codes)
        elif t in (PT.PINGREQ, PT.PINGRESP):
            pass
        elif t == PT.DISCONNECT:
            if self.v5 and (self.reason_code != 0 or not self.properties.is_empty()):
                body.append(self.reason_code & 0xFF)
                self.properties.encode(body, PT.DISCONNECT)
        elif t == PT.AUTH:
            if self.reason_code != 0 or not self.properties.is_empty():
                body.append(self.reason_code & 0xFF)
                self.properties.encode(body, PT.AUTH)
        else:
            raise ProtocolError(codes.ErrInvalidPacketType)

        self.fixed.remaining = len(body)
        out = bytearray()
        self.fixed.encode(out)
        out.extend(body)
        return bytes(out)

    def _connect_flags(self) -> int:
        flags = 0
        if self.clean_start:
            flags |= 0x02
        if self.will is not None and self.will.flag:
            flags |= 0x04 | ((self.will.qos & 0x3) << 3)
            if self.will.retain:
                flags |= 0x20
        if self.password_flag:
            flags |= 0x40
        if self.username_flag:
            flags |= 0x80
        return flags

    def _enc_connect(self, body: bytearray) -> None:
        write_string(body, PROTOCOL_NAMES.get(self.protocol_version, "MQTT"))
        body.append(self.protocol_version)
        body.append(self._connect_flags())
        write_uint16(body, self.keepalive)
        if self.v5:
            self.properties.encode(body, PT.CONNECT)
        write_string(body, self.client_id)
        if self.will is not None and self.will.flag:
            if self.v5:
                self.will.properties.encode(body, -1)
            write_string(body, self.will.topic)
            write_binary(body, self.will.payload)
        if self.username_flag:
            write_binary(body, self.username)
        if self.password_flag:
            write_binary(body, self.password)

    # ------------------------------------------------------------------
    # Decoding (body only; fixed header is parsed by the transport)
    # ------------------------------------------------------------------

    @classmethod
    def decode(cls, fixed: FixedHeader, body: bytes,  # qa: complex
               protocol_version: int = 4) -> "Packet":
        if fixed.remaining > len(body):
            # parse_stream always hands a complete body; a shorter one
            # means a truncated buffer was fed directly (the conformance
            # corpus's Mal* fixtures do exactly this)
            raise MalformedPacketError("body shorter than remaining length")
        p = _blank_packet(fixed, protocol_version)
        t = fixed.type
        try:
            if t == PT.CONNECT:
                p._dec_connect(body)
            elif t == PT.CONNACK:
                off = 0
                p.session_present = bool(body[off] & 0x1); off += 1
                p.reason_code = body[off]; off += 1
                if p.v5:
                    p.properties, off = Properties.decode(body, off, PT.CONNACK)
            elif t == PT.PUBLISH:
                p._dec_publish(body)
            elif t in (PT.PUBACK, PT.PUBREC, PT.PUBREL, PT.PUBCOMP):
                p.packet_id, off = read_uint16(body, 0)
                if p.v5 and len(body) > off:
                    p.reason_code = body[off]; off += 1
                    if len(body) > off:
                        p.properties, off = Properties.decode(body, off, t)
            elif t == PT.SUBSCRIBE:
                p._dec_subscribe(body)
            elif t == PT.SUBACK:
                p.packet_id, off = read_uint16(body, 0)
                if p.v5:
                    p.properties, off = Properties.decode(body, off, PT.SUBACK)
                p.reason_codes = list(body[off:])
            elif t == PT.UNSUBSCRIBE:
                p._dec_unsubscribe(body)
            elif t == PT.UNSUBACK:
                p.packet_id, off = read_uint16(body, 0)
                if p.v5:
                    p.properties, off = Properties.decode(body, off, PT.UNSUBACK)
                    p.reason_codes = list(body[off:])
            elif t in (PT.PINGREQ, PT.PINGRESP):
                pass
            elif t == PT.DISCONNECT:
                if p.v5 and body:
                    p.reason_code = body[0]
                    if len(body) > 1:
                        p.properties, _ = Properties.decode(body, 1, PT.DISCONNECT)
            elif t == PT.AUTH:
                if not p.v5:
                    # type 15 is reserved before MQTT 5 [MQTT-2.2.1]
                    raise ProtocolError(codes.ErrProtocolViolation,
                                        "AUTH packet on pre-v5 connection")
                if body:
                    p.reason_code = body[0]
                    if len(body) > 1:
                        p.properties, _ = Properties.decode(body, 1, PT.AUTH)
            else:
                raise ProtocolError(codes.ErrInvalidPacketType)
        except IndexError as e:
            raise MalformedPacketError(f"truncated {PT.NAMES.get(t, t)} body") from e
        return p

    def _dec_connect(self, body: bytes) -> None:
        off = 0
        self.protocol_name, off = read_string(body, off)
        self.protocol_version = body[off]; off += 1
        expected = PROTOCOL_NAMES.get(self.protocol_version)
        if expected is None or self.protocol_name != expected:
            raise ProtocolError(codes.ErrUnsupportedProtocolVersion,
                                f"unknown protocol {self.protocol_name!r} "
                                f"v{self.protocol_version}")
        flags = body[off]; off += 1
        will_flag = self._check_connect_flags(flags)
        self.keepalive, off = read_uint16(body, off)
        if self.v5:
            self.properties, off = Properties.decode(body, off, PT.CONNECT)
        self.client_id, off = read_string(body, off)
        if will_flag:
            off = self._dec_will(body, off, flags)
        if self.username_flag:
            self.username, off = read_binary(body, off)
        if self.password_flag:
            self.password, off = read_binary(body, off)
        if off != len(body):
            raise MalformedPacketError("trailing bytes after CONNECT payload")

    def _check_connect_flags(self, flags: int) -> bool:
        """Validate the CONNECT flags byte; returns the will flag."""
        if flags & 0x01:
            raise ProtocolError(codes.ErrProtocolViolation,
                                "connect reserved flag set")  # [MQTT-3.1.2-3]
        self.clean_start = bool(flags & 0x02)
        will_flag = bool(flags & 0x04)
        will_qos = (flags >> 3) & 0x3
        will_retain = bool(flags & 0x20)
        self.password_flag = bool(flags & 0x40)
        self.username_flag = bool(flags & 0x80)
        if not will_flag and (will_qos or will_retain):
            raise ProtocolError(codes.ErrProtocolViolation,
                                "will qos/retain without will flag")
        if will_qos > 2:
            raise ProtocolError(codes.ErrProtocolViolation, "will qos 3")
        if self.password_flag and not self.username_flag and not self.v5:
            # [MQTT-3.1.2-22]; v5 lifts this restriction.
            raise ProtocolError(codes.ErrProtocolViolation,
                                "password flag without username flag")
        return will_flag

    def _dec_will(self, body: bytes, off: int, flags: int) -> int:
        self.will = Will(qos=(flags >> 3) & 0x3,
                         retain=bool(flags & 0x20))
        if self.v5:
            self.will.properties, off = Properties.decode(body, off, -1)
        self.will.topic, off = read_string(body, off)
        self.will.payload, off = read_binary(body, off)
        if not self.will.topic:
            raise ProtocolError(codes.ErrProtocolViolation, "empty will topic")
        return off

    def _dec_publish(self, body: bytes) -> None:
        off = 0
        self.topic, off = read_string(body, off)
        if self.fixed.qos > 0:
            self.packet_id, off = read_uint16(body, off)
            if self.packet_id == 0:
                raise ProtocolError(codes.ErrProtocolViolation,
                                    "publish qos>0 with packet id 0")
        if self.v5:
            self.properties, off = Properties.decode(body, off, PT.PUBLISH)
        self.payload = bytes(body[off:])

    def _dec_subscribe(self, body: bytes) -> None:
        self.packet_id, off = read_uint16(body, 0)
        if self.packet_id == 0:
            raise ProtocolError(codes.ErrProtocolViolation, "subscribe packet id 0")
        if self.v5:
            self.properties, off = Properties.decode(body, off, PT.SUBSCRIBE)
            if len(self.properties.subscription_ids) > 1:
                raise ProtocolError(codes.ErrProtocolViolation,
                                    "multiple subscription ids")
        while off < len(body):
            filt, off = read_string(body, off)
            if off >= len(body):
                raise MalformedPacketError("subscribe filter missing options byte")
            sub = Subscription.from_options_byte(filt, body[off], self.v5)
            off += 1
            if self.properties.subscription_ids:
                sub.identifier = self.properties.subscription_ids[0]
            self.filters.append(sub)
        if not self.filters:
            raise ProtocolError(codes.ErrProtocolViolation,
                                "subscribe with no filters")  # [MQTT-3.8.3-3]

    def _dec_unsubscribe(self, body: bytes) -> None:
        self.packet_id, off = read_uint16(body, 0)
        if self.packet_id == 0:
            raise ProtocolError(codes.ErrProtocolViolation, "unsubscribe packet id 0")
        if self.v5:
            self.properties, off = Properties.decode(body, off, PT.UNSUBSCRIBE)
        while off < len(body):
            filt, off = read_string(body, off)
            self.filters.append(Subscription(filter=filt))
        if not self.filters:
            raise ProtocolError(codes.ErrProtocolViolation,
                                "unsubscribe with no filters")

    # ------------------------------------------------------------------
    # Validation beyond decode-time checks
    # ------------------------------------------------------------------

    def validate_publish(self) -> None:
        if self.fixed.qos > 0 and not self.packet_id:
            raise ProtocolError(codes.ErrProtocolViolation,
                                "qos > 0 publish without packet id"
                                )  # [MQTT-2.2.1-3]
        if self.fixed.qos == 0 and self.packet_id:
            raise ProtocolError(codes.ErrProtocolViolation,
                                "qos 0 publish with packet id"
                                )  # [MQTT-2.2.1-2]
        if self.properties.subscription_ids:
            # only the server sends subscription identifiers
            raise ProtocolError(codes.ErrProtocolViolation,
                                "subscription identifier from client"
                                )  # [MQTT-3.3.4-6]
        if not self.topic:
            # a v5 publish may carry only a topic alias [MQTT-3.3.2-6]
            if self.v5 and self.properties.topic_alias:
                return
            raise ProtocolError(codes.ErrTopicNameInvalid, "empty topic")
        if "+" in self.topic or "#" in self.topic:
            raise ProtocolError(codes.ErrTopicNameInvalid,
                                "wildcards in publish topic")  # [MQTT-3.3.2-2]
        if not valid_utf8_string(self.topic.encode("utf-8")):
            raise ProtocolError(codes.ErrTopicNameInvalid)

    def encode_under(self, max_size: int) -> bytes | None:
        """Encode within ``max_size`` bytes, discarding the optional
        problem-info properties (reason string, then user properties)
        when they don't fit — [MQTT-3.2.2-19/20] and siblings; the
        reference includes each iff the packet stays under the cap
        (properties.go:290-296, 323-334). None = still oversize after
        dropping everything droppable (the caller drops the packet,
        [MQTT-3.1.2-25])."""
        wire = self.encode()
        if not max_size or len(wire) <= max_size:
            return wire
        if not self.v5:
            return None
        p = self.copy()
        rs = p.properties.reason_string
        up = p.properties.user_properties
        p.properties.reason_string = ""
        p.properties.user_properties = []
        wire = p.encode()
        if len(wire) > max_size:
            return None
        if rs:                       # re-admit what still fits, in the
            p.properties.reason_string = rs      # reference's order
            trial = p.encode()
            if len(trial) <= max_size:
                wire = trial
            else:
                p.properties.reason_string = ""
        if up:
            p.properties.user_properties = up
            trial = p.encode()
            if len(trial) <= max_size:
                wire = trial
        return wire

    def reason_code_valid(self) -> bool:
        """Whether the reason code is one the spec allows for this packet
        type (reference parity surface: ReasonCodeValid,
        vendor/.../v2/packets/packets.go:779-829; AUTH per AuthValidate,
        packets.go:1133-1141 [MQTT-3.15.2-1])."""
        t = self.fixed.type
        allowed = _VALID_REASONS.get(t)
        return allowed is None or self.reason_code in allowed


# Spec-allowed reason codes per packet type. Types absent here are
# unconstrained (PUBACK mirrors the reference, whose switch has no case
# for it — packets.go:779-829).
_VALID_REASONS = {
    PT.PUBREC: frozenset({
        codes.Success.value, codes.NoMatchingSubscribers.value,
        codes.ErrUnspecifiedError.value,
        codes.ErrImplementationSpecificError.value,
        codes.ErrNotAuthorized.value, codes.ErrTopicNameInvalid.value,
        codes.ErrPacketIdentifierInUse.value,
        codes.ErrQuotaExceeded.value,
        codes.ErrPayloadFormatInvalid.value}),
    PT.PUBREL: frozenset({
        codes.Success.value, codes.ErrPacketIdentifierNotFound.value}),
    PT.PUBCOMP: frozenset({
        codes.Success.value, codes.ErrPacketIdentifierNotFound.value}),
    PT.SUBACK: frozenset({
        codes.GrantedQos0.value, codes.GrantedQos1.value,
        codes.GrantedQos2.value, codes.ErrUnspecifiedError.value,
        codes.ErrImplementationSpecificError.value,
        codes.ErrNotAuthorized.value, codes.ErrTopicFilterInvalid.value,
        codes.ErrPacketIdentifierInUse.value,
        codes.ErrQuotaExceeded.value,
        codes.ErrSharedSubscriptionsNotSupported.value,
        codes.ErrSubscriptionIdentifiersNotSupported.value,
        codes.ErrWildcardSubscriptionsNotSupported.value}),
    PT.UNSUBACK: frozenset({
        codes.Success.value, codes.NoSubscriptionExisted.value,
        codes.ErrUnspecifiedError.value,
        codes.ErrImplementationSpecificError.value,
        codes.ErrNotAuthorized.value, codes.ErrTopicFilterInvalid.value,
        codes.ErrPacketIdentifierInUse.value}),
    PT.AUTH: frozenset({
        codes.Success.value, codes.ContinueAuthentication.value,
        codes.ReAuthenticate.value}),
}


# Dataclass construction runs on the per-packet hot path; building from
# prebuilt default templates (immutable values shared, the three mutable
# fields set fresh) costs ~1/3 of the generated __init__. Parity is
# pinned by the conformance corpus (tests/test_tpackets.py) and
# test_packets.py — every decoded packet goes through this.
_PACKET_TEMPLATE: dict | None = None


def _blank_packet(fixed: FixedHeader, protocol_version: int) -> "Packet":
    global _PACKET_TEMPLATE
    if _PACKET_TEMPLATE is None:
        import dataclasses

        tmpl = {k: v for k, v in Packet().__dict__.items()
                if not isinstance(v, (list, dict, Properties, FixedHeader))}
        # a future mutable field must be added to the resets below, not
        # silently shared or dropped
        assert set(tmpl) | {"fixed", "protocol_version", "reason_codes",
                            "filters", "properties"} == \
            {f.name for f in dataclasses.fields(Packet)}
        _PACKET_TEMPLATE = tmpl
    q = object.__new__(Packet)
    q.__dict__.update(_PACKET_TEMPLATE)
    q.fixed = fixed
    q.protocol_version = protocol_version
    q.reason_codes = []
    q.filters = []
    q.properties = blank_properties()
    return q


def parse_stream(buf: bytearray, max_packet_size: int = 0):
    """Incremental framing: yield (FixedHeader, body) pairs consumed from buf.

    Leaves any trailing partial packet in ``buf``. Raises MalformedPacketError
    on an unparseable fixed header, ProtocolError(ErrPacketTooLarge) when a
    frame exceeds max_packet_size (0 = unlimited).
    """
    while True:
        if len(buf) < 2:
            return
        first = buf[0]
        # variable byte integer for remaining length
        remaining = 0
        shift = 0
        i = 1
        while True:
            if i >= len(buf):
                return  # need more bytes
            b = buf[i]
            remaining |= (b & 0x7F) << shift
            i += 1
            if not b & 0x80:
                break
            shift += 7
            if shift > 21:
                raise MalformedPacketError("remaining length varint too long")
        total = i + remaining
        if max_packet_size and total > max_packet_size:
            raise ProtocolError(codes.ErrPacketTooLarge)
        if len(buf) < total:
            return
        fh = FixedHeader.decode(first, remaining)
        body = bytes(buf[i:total])
        del buf[:total]
        yield fh, body
