"""MQTT v5 properties: identifiers, per-packet validity matrix, encode/decode.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/packets/properties.go in the
reference (27 properties + validity matrix). Re-derived from the MQTT 5.0 spec
section 2.2.2.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .codec import (
    MalformedPacketError,
    PacketType as PT,
    read_binary,
    read_string,
    read_uint16,
    read_uint32,
    read_varint,
    write_binary,
    write_string,
    write_uint16,
    write_uint32,
    write_varint,
)

# Property identifiers (MQTT 5.0 table 2-4).
PAYLOAD_FORMAT = 0x01
MESSAGE_EXPIRY = 0x02
CONTENT_TYPE = 0x03
RESPONSE_TOPIC = 0x08
CORRELATION_DATA = 0x09
SUBSCRIPTION_ID = 0x0B
SESSION_EXPIRY = 0x11
ASSIGNED_CLIENT_ID = 0x12
SERVER_KEEP_ALIVE = 0x13
AUTH_METHOD = 0x15
AUTH_DATA = 0x16
REQUEST_PROBLEM_INFO = 0x17
WILL_DELAY = 0x18
REQUEST_RESPONSE_INFO = 0x19
RESPONSE_INFO = 0x1A
SERVER_REFERENCE = 0x1C
REASON_STRING = 0x1F
RECEIVE_MAXIMUM = 0x21
TOPIC_ALIAS_MAX = 0x22
TOPIC_ALIAS = 0x23
MAXIMUM_QOS = 0x24
RETAIN_AVAILABLE = 0x25
USER_PROPERTY = 0x26
MAXIMUM_PACKET_SIZE = 0x27
WILDCARD_SUB_AVAILABLE = 0x28
SUB_ID_AVAILABLE = 0x29
SHARED_SUB_AVAILABLE = 0x2A

# Validity matrix: property id -> set of packet types it may appear in.
# "will" marks properties valid in the CONNECT will-properties block.
WILL = -1
_VALID: dict[int, frozenset[int]] = {
    PAYLOAD_FORMAT: frozenset({PT.PUBLISH, WILL}),
    MESSAGE_EXPIRY: frozenset({PT.PUBLISH, WILL}),
    CONTENT_TYPE: frozenset({PT.PUBLISH, WILL}),
    RESPONSE_TOPIC: frozenset({PT.PUBLISH, WILL}),
    CORRELATION_DATA: frozenset({PT.PUBLISH, WILL}),
    SUBSCRIPTION_ID: frozenset({PT.PUBLISH, PT.SUBSCRIBE}),
    SESSION_EXPIRY: frozenset({PT.CONNECT, PT.CONNACK, PT.DISCONNECT}),
    ASSIGNED_CLIENT_ID: frozenset({PT.CONNACK}),
    SERVER_KEEP_ALIVE: frozenset({PT.CONNACK}),
    AUTH_METHOD: frozenset({PT.CONNECT, PT.CONNACK, PT.AUTH}),
    AUTH_DATA: frozenset({PT.CONNECT, PT.CONNACK, PT.AUTH}),
    REQUEST_PROBLEM_INFO: frozenset({PT.CONNECT}),
    WILL_DELAY: frozenset({WILL}),
    REQUEST_RESPONSE_INFO: frozenset({PT.CONNECT}),
    RESPONSE_INFO: frozenset({PT.CONNACK}),
    SERVER_REFERENCE: frozenset({PT.CONNACK, PT.DISCONNECT}),
    REASON_STRING: frozenset({
        PT.CONNACK, PT.PUBACK, PT.PUBREC, PT.PUBREL, PT.PUBCOMP, PT.SUBACK,
        PT.UNSUBACK, PT.DISCONNECT, PT.AUTH}),
    RECEIVE_MAXIMUM: frozenset({PT.CONNECT, PT.CONNACK}),
    TOPIC_ALIAS_MAX: frozenset({PT.CONNECT, PT.CONNACK}),
    TOPIC_ALIAS: frozenset({PT.PUBLISH}),
    MAXIMUM_QOS: frozenset({PT.CONNACK}),
    RETAIN_AVAILABLE: frozenset({PT.CONNACK}),
    USER_PROPERTY: frozenset({
        PT.CONNECT, PT.CONNACK, PT.PUBLISH, PT.PUBACK, PT.PUBREC, PT.PUBREL,
        PT.PUBCOMP, PT.SUBSCRIBE, PT.SUBACK, PT.UNSUBSCRIBE, PT.UNSUBACK,
        PT.DISCONNECT, PT.AUTH, WILL}),
    MAXIMUM_PACKET_SIZE: frozenset({PT.CONNECT, PT.CONNACK}),
    WILDCARD_SUB_AVAILABLE: frozenset({PT.CONNACK}),
    SUB_ID_AVAILABLE: frozenset({PT.CONNACK}),
    SHARED_SUB_AVAILABLE: frozenset({PT.CONNACK}),
}


@dataclass
class Properties:
    """Decoded v5 property block. ``None`` / empty means "absent"."""

    payload_format: int | None = None
    message_expiry: int | None = None
    content_type: str = ""
    response_topic: str = ""
    correlation_data: bytes = b""
    subscription_ids: list[int] = field(default_factory=list)
    session_expiry: int | None = None
    assigned_client_id: str = ""
    server_keep_alive: int | None = None
    auth_method: str = ""
    auth_data: bytes = b""
    request_problem_info: int | None = None
    will_delay: int | None = None
    request_response_info: int | None = None
    response_info: str = ""
    server_reference: str = ""
    reason_string: str = ""
    receive_maximum: int | None = None
    topic_alias_max: int | None = None
    topic_alias: int | None = None
    maximum_qos: int | None = None
    retain_available: int | None = None
    user_properties: list[tuple[str, str]] = field(default_factory=list)
    maximum_packet_size: int | None = None
    wildcard_sub_available: int | None = None
    sub_id_available: int | None = None
    shared_sub_available: int | None = None

    def is_empty(self) -> bool:
        return self == Properties()

    def copy(self) -> "Properties":
        p = Properties(**{k: v for k, v in self.__dict__.items()
                          if k not in ("subscription_ids", "user_properties")})
        p.subscription_ids = list(self.subscription_ids)
        p.user_properties = list(self.user_properties)
        return p

    # -- encoding -----------------------------------------------------------

    def encode(self, out: bytearray, packet_type: int) -> None:  # qa: complex
        """Append the property-length varint + property block for packet_type."""
        body = bytearray()
        ctx = packet_type

        def ok(pid: int) -> bool:
            return ctx in _VALID[pid]

        if self.payload_format is not None and ok(PAYLOAD_FORMAT):
            body.append(PAYLOAD_FORMAT)
            body.append(self.payload_format & 0xFF)
        if self.message_expiry is not None and ok(MESSAGE_EXPIRY):
            body.append(MESSAGE_EXPIRY)
            write_uint32(body, self.message_expiry)
        if self.content_type and ok(CONTENT_TYPE):
            body.append(CONTENT_TYPE)
            write_string(body, self.content_type)
        if self.response_topic and ok(RESPONSE_TOPIC):
            body.append(RESPONSE_TOPIC)
            write_string(body, self.response_topic)
        if self.correlation_data and ok(CORRELATION_DATA):
            body.append(CORRELATION_DATA)
            write_binary(body, self.correlation_data)
        if ok(SUBSCRIPTION_ID):
            for sid in self.subscription_ids:
                body.append(SUBSCRIPTION_ID)
                write_varint(body, sid)
        if self.session_expiry is not None and ok(SESSION_EXPIRY):
            body.append(SESSION_EXPIRY)
            write_uint32(body, self.session_expiry)
        if self.assigned_client_id and ok(ASSIGNED_CLIENT_ID):
            body.append(ASSIGNED_CLIENT_ID)
            write_string(body, self.assigned_client_id)
        if self.server_keep_alive is not None and ok(SERVER_KEEP_ALIVE):
            body.append(SERVER_KEEP_ALIVE)
            write_uint16(body, self.server_keep_alive)
        if self.auth_method and ok(AUTH_METHOD):
            body.append(AUTH_METHOD)
            write_string(body, self.auth_method)
        if self.auth_data and ok(AUTH_DATA):
            body.append(AUTH_DATA)
            write_binary(body, self.auth_data)
        if self.request_problem_info is not None and ok(REQUEST_PROBLEM_INFO):
            body.append(REQUEST_PROBLEM_INFO)
            body.append(self.request_problem_info & 0xFF)
        if self.will_delay is not None and ok(WILL_DELAY):
            body.append(WILL_DELAY)
            write_uint32(body, self.will_delay)
        if self.request_response_info is not None and ok(REQUEST_RESPONSE_INFO):
            body.append(REQUEST_RESPONSE_INFO)
            body.append(self.request_response_info & 0xFF)
        if self.response_info and ok(RESPONSE_INFO):
            body.append(RESPONSE_INFO)
            write_string(body, self.response_info)
        if self.server_reference and ok(SERVER_REFERENCE):
            body.append(SERVER_REFERENCE)
            write_string(body, self.server_reference)
        if self.reason_string and ok(REASON_STRING):
            body.append(REASON_STRING)
            write_string(body, self.reason_string)
        if self.receive_maximum is not None and ok(RECEIVE_MAXIMUM):
            body.append(RECEIVE_MAXIMUM)
            write_uint16(body, self.receive_maximum)
        if self.topic_alias_max is not None and ok(TOPIC_ALIAS_MAX):
            body.append(TOPIC_ALIAS_MAX)
            write_uint16(body, self.topic_alias_max)
        if self.topic_alias is not None and ok(TOPIC_ALIAS):
            body.append(TOPIC_ALIAS)
            write_uint16(body, self.topic_alias)
        if self.maximum_qos is not None and ok(MAXIMUM_QOS):
            body.append(MAXIMUM_QOS)
            body.append(self.maximum_qos & 0xFF)
        if self.retain_available is not None and ok(RETAIN_AVAILABLE):
            body.append(RETAIN_AVAILABLE)
            body.append(self.retain_available & 0xFF)
        if ok(USER_PROPERTY):
            for k, v in self.user_properties:
                body.append(USER_PROPERTY)
                write_string(body, k)
                write_string(body, v)
        if self.maximum_packet_size is not None and ok(MAXIMUM_PACKET_SIZE):
            body.append(MAXIMUM_PACKET_SIZE)
            write_uint32(body, self.maximum_packet_size)
        if self.wildcard_sub_available is not None and ok(WILDCARD_SUB_AVAILABLE):
            body.append(WILDCARD_SUB_AVAILABLE)
            body.append(self.wildcard_sub_available & 0xFF)
        if self.sub_id_available is not None and ok(SUB_ID_AVAILABLE):
            body.append(SUB_ID_AVAILABLE)
            body.append(self.sub_id_available & 0xFF)
        if self.shared_sub_available is not None and ok(SHARED_SUB_AVAILABLE):
            body.append(SHARED_SUB_AVAILABLE)
            body.append(self.shared_sub_available & 0xFF)

        write_varint(out, len(body))
        out.extend(body)

    # -- decoding -----------------------------------------------------------

    @classmethod
    def decode(cls, buf: bytes, off: int, packet_type: int) -> tuple["Properties", int]:  # qa: complex
        """Read the property-length varint + block; validate per packet type."""
        length, off = read_varint(buf, off)
        end = off + length
        if end > len(buf):
            raise MalformedPacketError("truncated properties block")
        p = blank_properties()
        seen: set[int] = set()
        while off < end:
            pid, off = read_varint(buf, off)
            valid_in = _VALID.get(pid)
            if valid_in is None or packet_type not in valid_in:
                raise MalformedPacketError(
                    f"property {pid:#x} invalid for packet type {packet_type}")
            if pid in seen and pid not in (USER_PROPERTY, SUBSCRIPTION_ID):
                raise MalformedPacketError(f"duplicate property {pid:#x}")
            seen.add(pid)
            if pid == PAYLOAD_FORMAT:
                p.payload_format = buf[off]; off += 1
            elif pid == MESSAGE_EXPIRY:
                p.message_expiry, off = read_uint32(buf, off)
            elif pid == CONTENT_TYPE:
                p.content_type, off = read_string(buf, off)
            elif pid == RESPONSE_TOPIC:
                p.response_topic, off = read_string(buf, off)
            elif pid == CORRELATION_DATA:
                p.correlation_data, off = read_binary(buf, off)
            elif pid == SUBSCRIPTION_ID:
                sid, off = read_varint(buf, off)
                if sid == 0:
                    raise MalformedPacketError("subscription id 0 is malformed")
                p.subscription_ids.append(sid)
            elif pid == SESSION_EXPIRY:
                p.session_expiry, off = read_uint32(buf, off)
            elif pid == ASSIGNED_CLIENT_ID:
                p.assigned_client_id, off = read_string(buf, off)
            elif pid == SERVER_KEEP_ALIVE:
                p.server_keep_alive, off = read_uint16(buf, off)
            elif pid == AUTH_METHOD:
                p.auth_method, off = read_string(buf, off)
            elif pid == AUTH_DATA:
                p.auth_data, off = read_binary(buf, off)
            elif pid == REQUEST_PROBLEM_INFO:
                p.request_problem_info = buf[off]; off += 1
            elif pid == WILL_DELAY:
                p.will_delay, off = read_uint32(buf, off)
            elif pid == REQUEST_RESPONSE_INFO:
                p.request_response_info = buf[off]; off += 1
            elif pid == RESPONSE_INFO:
                p.response_info, off = read_string(buf, off)
            elif pid == SERVER_REFERENCE:
                p.server_reference, off = read_string(buf, off)
            elif pid == REASON_STRING:
                p.reason_string, off = read_string(buf, off)
            elif pid == RECEIVE_MAXIMUM:
                p.receive_maximum, off = read_uint16(buf, off)
                if p.receive_maximum == 0:
                    raise MalformedPacketError("receive maximum 0 is malformed")
            elif pid == TOPIC_ALIAS_MAX:
                p.topic_alias_max, off = read_uint16(buf, off)
            elif pid == TOPIC_ALIAS:
                p.topic_alias, off = read_uint16(buf, off)
                if p.topic_alias == 0:
                    raise MalformedPacketError("topic alias 0 is malformed")
            elif pid == MAXIMUM_QOS:
                p.maximum_qos = buf[off]; off += 1
                if p.maximum_qos > 1:
                    raise MalformedPacketError("maximum qos must be 0 or 1")
            elif pid == RETAIN_AVAILABLE:
                p.retain_available = buf[off]; off += 1
            elif pid == USER_PROPERTY:
                k, off = read_string(buf, off)
                v, off = read_string(buf, off)
                p.user_properties.append((k, v))
            elif pid == MAXIMUM_PACKET_SIZE:
                p.maximum_packet_size, off = read_uint32(buf, off)
                if p.maximum_packet_size == 0:
                    raise MalformedPacketError("maximum packet size 0 is malformed")
            elif pid == WILDCARD_SUB_AVAILABLE:
                p.wildcard_sub_available = buf[off]; off += 1
            elif pid == SUB_ID_AVAILABLE:
                p.sub_id_available = buf[off]; off += 1
            elif pid == SHARED_SUB_AVAILABLE:
                p.shared_sub_available = buf[off]; off += 1
            if off > end:
                raise MalformedPacketError("property ran past block end")
        return p, off


_PROPS_TEMPLATE: dict | None = None


def blank_properties() -> "Properties":
    """Template-built Properties: immutable defaults shared, the two
    list fields fresh — ~1/3 the cost of the generated __init__ on the
    per-packet decode path."""
    global _PROPS_TEMPLATE
    if _PROPS_TEMPLATE is None:
        import dataclasses

        tmpl = {k: v for k, v in Properties().__dict__.items()
                if not isinstance(v, (list, dict))}
        # a future mutable field must be added to the resets below, not
        # silently shared or dropped
        assert set(tmpl) | {"subscription_ids", "user_properties"} ==             {f.name for f in dataclasses.fields(Properties)}
        _PROPS_TEMPLATE = tmpl
    q = object.__new__(Properties)
    q.__dict__.update(_PROPS_TEMPLATE)
    q.subscription_ids = []
    q.user_properties = []
    return q
