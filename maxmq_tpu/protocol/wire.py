"""Shared PUBLISH wire templates for zero-copy fan-out (ADR 019).

A publish delivered to N subscribers used to cost N ``Packet.copy()`` +
N full encodes. The wire differences between those N frames are tiny
and structural: the fixed-header flags byte (QoS / retain-as-published),
the 2-byte packet id, and — v5 only — a spliced subscription-id /
topic-alias property segment. Everything else (topic, the shared
property prefix/suffix, the payload) is byte-identical.

This module splits the frame accordingly:

* :func:`publish_template` builds ONE immutable :class:`PublishTemplate`
  per (packet, protocol major version) — cached on the packet like the
  QoS0 ``_wire0`` cache — holding the shared segments.
* :meth:`PublishTemplate.patch` assembles one subscriber's frame as a
  buffer sequence ``(head, [props_a], [mid], [props_u], payload)``:
  only the small head (fixed header + remaining-length varint + topic +
  packet id + property-length varint) and the per-subscriber property
  segment are fresh bytes; the property prefix/suffix and the payload
  are the template's shared objects, never copied per subscriber.

Byte-identity with the slow path (``Packet.encode``) is structural, not
coincidental: the shared property prefix/suffix are produced by
``Properties.encode`` itself (with the per-subscriber properties
cleared), and the spliced segment sits exactly where that encoder puts
subscription ids and the topic alias — contiguously, between the
correlation-data prefix and the user-property suffix. The differential
test matrix in tests/test_wire_templates.py holds this invariant.

The head assembly has a native sibling (``encode_publish_template`` in
native/maxmq_decode.cpp); like the decode fast path it is optional,
fault-site wrapped (``faults.NATIVE_ENCODE``), and falls back to the
pure-Python builder on any error.
"""

from __future__ import annotations

from .. import faults
from .codec import varint_len, write_uint16, write_varint
from .packets import Packet
from .properties import SUBSCRIPTION_ID, TOPIC_ALIAS

__all__ = ["PublishTemplate", "publish_template", "sid_alias_seg",
           "encode_head", "native_head_encoder"]

_EMPTY_TOPIC = b"\x00\x00"


# ----------------------------------------------------------------------
# Per-subscriber head assembly: native entry point + Python fallback
# ----------------------------------------------------------------------

_native_head = False        # False = unresolved, None = unavailable


def native_head_encoder(build: bool = False):
    """The C ``encode_publish_template`` entry point, resolved once
    from the maxmq_decode extension — or None. Resolution failures are
    permanent for the process (same policy as the decode fast path)."""
    global _native_head
    if _native_head is False:
        _native_head = None
        try:
            from .. import native as _native
            mod = _native.decode_module(build=build)
            if mod is not None:
                _native_head = getattr(mod, "encode_publish_template",
                                       None)
        except Exception:
            _native_head = None
    return _native_head


def _encode_head_py(flags: int, topic_seg: bytes, packet_id: int,
                    props_len: int, tail_len: int) -> bytes:
    """Pure-Python head builder: fixed-header byte, remaining-length
    varint, topic segment, optional packet id, optional property-length
    varint. ``props_len < 0`` means a v3 frame (no properties block);
    ``tail_len`` is the byte count that FOLLOWS the head on the wire
    beyond the properties (i.e. the payload)."""
    pid_len = 2 if packet_id else 0
    remaining = len(topic_seg) + pid_len + tail_len
    if props_len >= 0:
        remaining += varint_len(props_len) + props_len
    head = bytearray([flags])
    write_varint(head, remaining)
    head += topic_seg
    if packet_id:
        write_uint16(head, packet_id)
    if props_len >= 0:
        write_varint(head, props_len)
    return bytes(head)


def encode_head(flags: int, topic_seg: bytes, packet_id: int,
                props_len: int, tail_len: int,
                native: bool = True) -> bytes:
    """Frame-head assembly, via the C builder when available + enabled.
    Any native error — including an armed ``faults.NATIVE_ENCODE``
    site — degrades to the Python builder for THIS call; the outputs
    are byte-identical by the differential tests."""
    if native:
        enc = _native_head if _native_head is not False \
            else native_head_encoder()
        if enc is not None:
            try:
                if faults.REGISTRY.any_armed():
                    faults.fire(faults.NATIVE_ENCODE)
                return enc(flags, topic_seg, packet_id, props_len,
                           tail_len)
            except Exception:
                pass
    return _encode_head_py(flags, topic_seg, packet_id, props_len,
                           tail_len)


def sid_alias_seg(subscription_ids, topic_alias) -> bytes:
    """The per-subscriber v5 property segment: one 0x0B+varint per
    subscription id, then 0x23+uint16 for an assigned outbound topic
    alias. Spliced between the template's shared property prefix and
    suffix — exactly where ``Properties.encode`` emits them."""
    if not subscription_ids and topic_alias is None:
        return b""
    seg = bytearray()
    for sid in subscription_ids:
        seg.append(SUBSCRIPTION_ID)
        write_varint(seg, sid)
    if topic_alias is not None:
        seg.append(TOPIC_ALIAS)
        write_uint16(seg, topic_alias)
    return bytes(seg)


# ----------------------------------------------------------------------
# The shared template
# ----------------------------------------------------------------------


class PublishTemplate:
    """Immutable shared segments of one publish's outbound frames for
    one protocol major version. ``shared_len`` is the byte count a
    patched delivery reuses without copying (property prefix/suffix +
    payload) — the fan-out ledger's "bytes not copied" term."""

    __slots__ = ("v5", "topic_seg", "props_a", "props_u", "payload",
                 "shared_len")

    def __init__(self, v5: bool, topic_seg: bytes, props_a: bytes,
                 props_u: bytes, payload: bytes) -> None:
        self.v5 = v5
        self.topic_seg = topic_seg
        self.props_a = props_a
        self.props_u = props_u
        self.payload = payload
        self.shared_len = len(props_a) + len(props_u) + len(payload)

    def frame_size(self, mid_len: int, pid: bool,
                   alias_topic: bool = False) -> int:
        """Exact frame size for a delivery with a ``mid_len``-byte
        spliced segment — cheap enough to run per subscriber for the
        maximum-packet-size admission check before any bytes move."""
        topic_len = 2 if alias_topic else len(self.topic_seg)
        body = topic_len + (2 if pid else 0) + len(self.payload)
        if self.v5:
            props_len = len(self.props_a) + mid_len + len(self.props_u)
            body += varint_len(props_len) + props_len
        return 1 + varint_len(body) + body

    def patch(self, qos: int, retain: bool, packet_id: int,
              mid: bytes = b"", alias_topic: bool = False,
              native: bool = True) -> tuple[tuple, int]:
        """One subscriber's frame as ``(buffers, exact_size)``. Only
        the head and ``mid`` are fresh allocations; every other buffer
        is a shared template segment. ``alias_topic`` sends the empty
        topic of an established v5 outbound alias."""
        topic_seg = _EMPTY_TOPIC if alias_topic else self.topic_seg
        flags = 0x30 | ((qos & 0x3) << 1) | (1 if retain else 0)
        payload = self.payload
        if not self.v5:
            head = encode_head(flags, topic_seg, packet_id, -1,
                               len(payload), native)
            if payload:
                return (head, payload), len(head) + len(payload)
            return (head,), len(head)
        props_len = len(self.props_a) + len(mid) + len(self.props_u)
        head = encode_head(flags, topic_seg, packet_id, props_len,
                           len(payload), native)
        bufs = [head]
        if self.props_a:
            bufs.append(self.props_a)
        if mid:
            bufs.append(mid)
        if self.props_u:
            bufs.append(self.props_u)
        if payload:
            bufs.append(payload)
        return tuple(bufs), len(head) + props_len + len(payload)


def _strip_props_varint(buf: bytearray) -> bytes:
    """Drop the leading property-length varint ``Properties.encode``
    writes; the template re-derives it per subscriber."""
    i = 1
    while buf[i - 1] & 0x80:
        i += 1
    return bytes(buf[i:])


def _build_template(packet: Packet, version: int) -> PublishTemplate:
    from .codec import PacketType as PT
    topic = packet.topic.encode("utf-8")
    topic_seg = len(topic).to_bytes(2, "big") + topic
    payload = bytes(packet.payload or b"")
    if version < 5:
        return PublishTemplate(False, topic_seg, b"", b"", payload)
    # Split the shared v5 property bytes around the per-subscriber
    # splice point by running the REAL property encoder twice: once
    # without the suffix (user properties) for the prefix length, once
    # with it for prefix+suffix. The per-subscriber properties
    # (subscription ids, topic alias) are cleared for both passes —
    # inbound alias ids must not leak into deliveries, matching
    # _build_outbound.
    pr = packet.properties
    saved = (pr.subscription_ids, pr.topic_alias, pr.user_properties)
    try:
        pr.subscription_ids, pr.topic_alias = [], None
        pr.user_properties = []
        buf = bytearray()
        pr.encode(buf, PT.PUBLISH)
        props_a = _strip_props_varint(buf)
        pr.user_properties = saved[2]
        buf = bytearray()
        pr.encode(buf, PT.PUBLISH)
        both = _strip_props_varint(buf)
    finally:
        pr.subscription_ids, pr.topic_alias, pr.user_properties = saved
    return PublishTemplate(True, topic_seg, props_a,
                           both[len(props_a):], payload)


def publish_template(packet: Packet, version: int) -> PublishTemplate:
    """The (packet, version) shared template, built once and cached on
    the packet instance (same lifetime discipline as the QoS0 ``_wire0``
    wire cache: dies with the publish)."""
    key = 5 if version >= 5 else 4
    cache = packet.__dict__.get("_tmpl")
    if cache is None:
        cache = {}
        packet.__dict__["_tmpl"] = cache
    tmpl = cache.get(key)
    if tmpl is None:
        tmpl = _build_template(packet, key)
        cache[key] = tmpl
    return tmpl
