"""Operational utilities: snowflake IDs, structured logging, config, build
info. Parity surface: the reference's first-party shell — internal/snowflake,
internal/logger, internal/config, internal/build."""

from .snowflake import Snowflake
from .logger import Logger, new_logger
from .config import Config, load_config, read_config_file
from .build import get_info, BuildInfo

__all__ = ["Snowflake", "Logger", "new_logger", "Config", "load_config",
           "read_config_file", "get_info", "BuildInfo"]
