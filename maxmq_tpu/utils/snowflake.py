"""Snowflake unique-ID generator.

64-bit IDs with the same bit layout as the reference's generator
(internal/snowflake/snowflake.go:23-62): 42-bit millisecond timestamp since
the 2020-01-01 UTC epoch, 10-bit machine ID, 12-bit per-millisecond sequence.
IDs are time-sortable and unique per (machine, ms, seq). The reference uses a
lock-free CAS loop with 3 retries; here a mutex is the idiomatic equivalent —
contention is the metrics/log path, not the match hot path.
"""

from __future__ import annotations

import threading
import time

# 2020-01-01T00:00:00Z in milliseconds
EPOCH_MS = 1_577_836_800_000

TIMESTAMP_BITS = 42
MACHINE_BITS = 10
SEQUENCE_BITS = 12

MAX_MACHINE_ID = (1 << MACHINE_BITS) - 1
MAX_SEQUENCE = (1 << SEQUENCE_BITS) - 1
MAX_TIMESTAMP = (1 << TIMESTAMP_BITS) - 1

TIMESTAMP_SHIFT = MACHINE_BITS + SEQUENCE_BITS
MACHINE_SHIFT = SEQUENCE_BITS


class Snowflake:
    """Generates unique, roughly time-ordered 64-bit IDs."""

    def __init__(self, machine_id: int = 0) -> None:
        if not 0 <= machine_id <= MAX_MACHINE_ID:
            raise ValueError(
                f"machine_id must be in [0, {MAX_MACHINE_ID}], got {machine_id}")
        self.machine_id = machine_id
        self._lock = threading.Lock()
        self._last_ms = -1
        self._seq = 0

    def next_id(self) -> int:
        with self._lock:
            now = self._now_ms()
            if now < self._last_ms:
                # clock went backwards: wait it out (reference retries CAS)
                while now < self._last_ms:
                    time.sleep(0.0001)
                    now = self._now_ms()
            if now == self._last_ms:
                self._seq = (self._seq + 1) & MAX_SEQUENCE
                if self._seq == 0:
                    # sequence exhausted within this millisecond
                    while now <= self._last_ms:
                        now = self._now_ms()
            else:
                self._seq = 0
            self._last_ms = now
            return ((now & MAX_TIMESTAMP) << TIMESTAMP_SHIFT
                    | self.machine_id << MACHINE_SHIFT
                    | self._seq)

    # Field extractors (snowflake.go:45-62)
    @staticmethod
    def timestamp_ms(id_: int) -> int:
        """Unix milliseconds the ID was generated at."""
        return (id_ >> TIMESTAMP_SHIFT) + EPOCH_MS

    @staticmethod
    def machine_of(id_: int) -> int:
        return (id_ >> MACHINE_SHIFT) & MAX_MACHINE_ID

    @staticmethod
    def sequence_of(id_: int) -> int:
        return id_ & MAX_SEQUENCE

    @staticmethod
    def _now_ms() -> int:
        return time.time_ns() // 1_000_000 - EPOCH_MS
