"""Build/version information.

Parity surface: internal/build/info.go — version, revision, build time, and
distribution, injected at build time (the reference uses ``-ldflags -X``,
Makefile:38-43; here the injection points are module globals overridable via
``MAXMQ_BUILD_*`` env at packaging time) with short/long formatting
(info.go:66-84).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

VERSION = os.environ.get("MAXMQ_BUILD_VERSION", "0.1.0-dev")
REVISION = os.environ.get("MAXMQ_BUILD_REVISION", "")
BUILD_TIME = os.environ.get("MAXMQ_BUILD_TIME", "")
DISTRIBUTION = os.environ.get("MAXMQ_BUILD_DISTRIBUTION", "maxmq-tpu")


@dataclass(frozen=True)
class BuildInfo:
    version: str
    revision: str
    build_time: str
    distribution: str

    def short_version(self) -> str:
        return self.version

    def long_version(self) -> str:
        parts = [f"{self.distribution} {self.version}"]
        if self.revision:
            parts.append(f"({self.revision})")
        if self.build_time:
            parts.append(f"built at {self.build_time}")
        return " ".join(parts)


def get_info() -> BuildInfo:
    return BuildInfo(VERSION, REVISION, BUILD_TIME, DISTRIBUTION)
