"""Length-prefixed frame helpers shared by the ADR-005 fan-out bus and
the matcher service (ADR 005/006): ``>IB`` = payload length + type."""

from __future__ import annotations

import asyncio
import struct


def frame(ftype: int, payload: bytes) -> bytes:
    return struct.pack(">IB", len(payload) + 1, ftype) + payload


async def read_frame(reader) -> tuple[int, bytes] | None:
    """One frame, or None on EOF/connection loss."""
    try:
        head = await reader.readexactly(5)
        length, ftype = struct.unpack(">IB", head)
        return ftype, await reader.readexactly(length - 1)
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        return None
