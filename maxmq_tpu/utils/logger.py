"""Structured leveled logger.

Parity surface: internal/logger/logger.go in the reference — two output
formats (``pretty`` colorized console, ``json`` one-object-per-line), a global
severity level, hierarchical prefixes (``bootstrap``, ``mqtt``, ``metrics``),
and a per-event ``LogId`` injected from a pluggable generator (the snowflake
generator in production, logger.go:166-170).

Self-contained rather than a stdlib-logging wrapper: every event is a flat
dict of fields, which keeps the json format trivially machine-parseable and
the pretty format deterministic for tests.
"""

from __future__ import annotations

import io
import json
import sys
import threading
import time
from typing import Any, Callable, TextIO

TRACE = 0
DEBUG = 1
INFO = 2
WARN = 3
ERROR = 4
FATAL = 5

_LEVEL_NAMES = {TRACE: "trace", DEBUG: "debug", INFO: "info",
                WARN: "warn", ERROR: "error", FATAL: "fatal"}
_NAME_LEVELS = {v: k for k, v in _LEVEL_NAMES.items()}

_COLORS = {TRACE: "\x1b[35m", DEBUG: "\x1b[33m", INFO: "\x1b[32m",
           WARN: "\x1b[31m", ERROR: "\x1b[31;1m", FATAL: "\x1b[41;97m"}
_RESET = "\x1b[0m"
_DIM = "\x1b[2m"

_global_level = INFO
_level_lock = threading.Lock()


def set_severity_level(level: int | str) -> None:
    """Set the process-wide minimum severity (logger.go:85-93)."""
    global _global_level
    if isinstance(level, str):
        if level not in _NAME_LEVELS:
            raise ValueError(f"unknown log level {level!r}")
        level = _NAME_LEVELS[level]
    with _level_lock:
        _global_level = level


def severity_level() -> int:
    return _global_level


class Logger:
    """Leveled structured logger with prefix chaining and LogId injection."""

    def __init__(self, out: TextIO | None = None, fmt: str = "pretty",
                 prefix: str = "", log_id_gen: Callable[[], int] | None = None,
                 color: bool | None = None) -> None:
        if fmt == "text":
            fmt = "pretty"      # config spelling: log_format = json|text
        if fmt not in ("pretty", "json"):
            raise ValueError(f"unknown log format {fmt!r}")
        self._out = out if out is not None else sys.stderr
        self._fmt = fmt
        self._prefix = prefix
        self._log_id_gen = log_id_gen
        if color is None:
            color = hasattr(self._out, "isatty") and self._out.isatty()
        self._color = color and fmt == "pretty"
        self._lock = threading.Lock()

    def with_prefix(self, prefix: str) -> "Logger":
        """Child logger with a hierarchical prefix (logger.go:148-158)."""
        full = f"{self._prefix}.{prefix}" if self._prefix else prefix
        return Logger(self._out, self._fmt, full, self._log_id_gen,
                      self._color)

    # -- event emitters -----------------------------------------------------

    def trace(self, msg: str, **fields: Any) -> None:
        self._emit(TRACE, msg, fields)

    def debug(self, msg: str, **fields: Any) -> None:
        self._emit(DEBUG, msg, fields)

    def info(self, msg: str, **fields: Any) -> None:
        self._emit(INFO, msg, fields)

    def warn(self, msg: str, **fields: Any) -> None:
        self._emit(WARN, msg, fields)

    def error(self, msg: str, **fields: Any) -> None:
        self._emit(ERROR, msg, fields)

    def fatal(self, msg: str, **fields: Any) -> None:
        self._emit(FATAL, msg, fields)

    def log(self, level: int, msg: str, **fields: Any) -> None:
        self._emit(level, msg, fields)

    # -----------------------------------------------------------------------

    def _emit(self, level: int, msg: str, fields: dict[str, Any]) -> None:
        if level < _global_level:
            return
        now = time.time()
        event: dict[str, Any] = {
            "time": int(now * 1000),
            "level": _LEVEL_NAMES[level],
        }
        if self._prefix:
            event["prefix"] = self._prefix
        event.update(fields)
        if self._log_id_gen is not None:
            event["log_id"] = self._log_id_gen()
        event["message"] = msg
        line = (self._format_json(event) if self._fmt == "json"
                else self._format_pretty(level, now, event, msg))
        with self._lock:
            self._out.write(line + "\n")

    @staticmethod
    def _format_json(event: dict[str, Any]) -> str:
        return json.dumps(event, default=str, separators=(",", ":"))

    def _format_pretty(self, level: int, now: float, event: dict[str, Any],
                       msg: str) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(now))
        name = _LEVEL_NAMES[level].upper()[:3]
        buf = io.StringIO()
        if self._color:
            buf.write(f"{_DIM}{ts}{_RESET} {_COLORS[level]}{name}{_RESET}")
        else:
            buf.write(f"{ts} {name}")
        if self._prefix:
            buf.write(f" [{self._prefix}]")
        buf.write(f" {msg}")
        for k, v in event.items():
            if k in ("time", "level", "prefix", "message"):
                continue
            if self._color:
                buf.write(f" {_DIM}{k}={_RESET}{v}")
            else:
                buf.write(f" {k}={v}")
        return buf.getvalue()


def new_logger(fmt: str = "pretty", level: int | str = INFO,
               out: TextIO | None = None,
               log_id_gen: Callable[[], int] | None = None) -> Logger:
    """Construct the root logger (logger.go:116-136)."""
    set_severity_level(level)
    return Logger(out=out, fmt=fmt, log_id_gen=log_id_gen)
