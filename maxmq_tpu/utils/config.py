"""Flat configuration with TOML file + ``MAXMQ_*`` environment overlay.

Parity surface: internal/config/config.go in the reference — one flat struct
of snake_case keys covering logging, metrics, and broker settings; defaults
(config.go:98-119); a TOML ``maxmq.conf`` searched in the working directory,
``/etc/maxmq``, then ``/etc`` (126-142); environment variables named
``MAXMQ_<UPPER_KEY>`` override the file (149-183). The TPU build adds the
matcher/runtime knobs the reference has no equivalent for.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, fields

try:
    import tomllib
except ModuleNotFoundError:            # Python < 3.11
    import tomli as tomllib


@dataclass
class Config:
    # -- logging (config.go: log block) -------------------------------------
    log_format: str = "pretty"          # json | text ("pretty" = text)
    log_level: str = "info"             # trace|debug|info|warn|error|fatal
    machine_id: int = 0                 # snowflake machine id, [0,1023]

    # -- metrics HTTP server ------------------------------------------------
    metrics_enabled: bool = True
    metrics_address: str = ":8888"
    metrics_path: str = "/metrics"
    metrics_profiling: bool = False

    # -- broker listeners ---------------------------------------------------
    workers: int = 0                    # >1: SO_REUSEPORT delivery-worker
                                        # pool + fan-out bus (ADR 005)
    mqtt_tcp_address: str = ":1883"
    mqtt_ws_address: str = ""           # optional websocket listener
    mqtt_unix_socket: str = ""          # optional unix-socket listener
    mqtt_sys_http_address: str = ""     # optional $SYS JSON stats endpoint

    # -- broker capabilities (internal/mqtt/config.go fields → mochi
    #    Capabilities, server.go:76-91) --------------------------------------
    mqtt_shutdown_timeout: int = 15     # graceful-close deadline, seconds
    # per-connection read-chunk bytes. The reference's default (2048) is
    # a Go bufio size; asyncio pays a coroutine round-trip per read, so
    # the default stays at the historical 64KiB chunk — set explicitly
    # to bound per-connection buffering
    mqtt_buffer_size: int = 65536
    mqtt_min_protocol_version: int = 3
    mqtt_max_keep_alive: int = 7200
    mqtt_session_expiry_interval: int = 0xFFFFFFFF
    mqtt_max_message_expiry_interval: int = 0xFFFFFFFF
    mqtt_max_packet_size: int = 0       # 0 = unlimited
    mqtt_max_inflight_messages: int = 1024
    mqtt_receive_maximum: int = 1024
    mqtt_max_qos: int = 2
    mqtt_max_topic_alias: int = 65535
    mqtt_retain_available: bool = True
    mqtt_wildcard_subscription_available: bool = True
    mqtt_subscription_id_available: bool = True
    mqtt_shared_subscription_available: bool = True
    mqtt_max_outbound_queue: int = 1024
    mqtt_sys_topic_interval: int = 1    # seconds between $SYS refreshes

    # -- broker overload-protection ladder (ADR 012) -------------------------
    # per-client queued outbound wire bytes; oldest QoS0 deliveries are
    # shed first, then new deliveries refuse. 0 = count cap only.
    broker_client_byte_budget: int = 8 << 20
    broker_byte_budget: int = 0         # global queued-byte budget; 0 = off
    connect_rate: float = 0.0           # CONNECT admissions/sec/listener
    connect_burst: int = 0              # bucket depth; 0 = max(1, rate)
    connect_half_open_max: int = 0      # cap on handshakes awaiting CONNECT
    stall_deadline_ms: int = 60_000     # writer no-progress disconnect; 0 off
    broker_overload_high_water: float = 0.8   # shed above budget * high
    broker_overload_low_water: float = 0.5    # recover below budget * low

    # -- cluster federation (ADR 013) ----------------------------------------
    cluster_node_id: str = ""           # non-empty enables federation
    cluster_peers: str = ""             # "nodeB@host:1884,nodeC@host:1885"
    cluster_link_qos: int = 0           # forward QoS cap on bridge links
    cluster_max_hops: int = 3           # forwarded-publish hop ceiling
    cluster_link_byte_budget: int = 4 << 20  # per-link queued bytes; 0 off
    cluster_link_keepalive: float = 10.0     # bridge ping interval, seconds

    # -- federated sessions (ADR 016) ----------------------------------------
    # replicate session metadata + inflight windows to bridge peers so
    # a client reconnecting to ANY node resumes with session-present=1
    cluster_session_replication: bool = True
    # inflight replication policy: always = publisher QoS acks wait
    # (bounded) for peer replication acks — a SIGKILLed node's peer can
    # redeliver every PUBACKed message; batched = replicate async (a
    # crash can lose the in-flight window); off = metadata only
    cluster_session_sync: str = "batched"
    cluster_session_sync_timeout_ms: int = 750      # barrier degrade bound
    cluster_session_takeover_timeout_ms: int = 750  # state-pull wait bound

    # -- partition tolerance (ADR 018) ---------------------------------------
    # cross-node publish durability: coupled = when session_sync is
    # "always", QoS>0 forwards ride QoS1 links, park for retry-after-
    # heal when stranded, and the publisher's ack waits (bounded) for
    # the peers' forward acks; always = the fwd barrier regardless of
    # session_sync; off = pre-018 fire-and-forget forwards
    cluster_fwd_durability: str = "coupled"
    # replica-side expiry fallback for a DEAD owner's sessions that
    # carry no expiry metadata (seconds; 0 = keep such replicas
    # forever, the pre-018 behavior)
    cluster_replica_expiry_s: float = 3600.0
    # cluster-wide $share ownership: weighted = per-publish rotation
    # weighted by each node's live member count; pin = lowest node id
    # owns every pick (the pre-018 / ADR-005 trade)
    cluster_share_balance: str = "weighted"

    # -- WAN deployments (ADR 022) -------------------------------------------
    # per-link liveness/barrier deadlines stretch with the measured
    # peer RTT: deadline = floor + k x RTT (the floors are the knobs
    # above — link keepalive, sync/takeover timeouts, willfire grace).
    # 0 pins every deadline to its loopback floor (pre-022 behavior);
    # at loopback RTT the k-term is ~0 either way
    cluster_rtt_deadline_k: float = 4.0

    # -- cluster observability plane (ADR 017) --------------------------------
    # carry trace context on forwarded publishes to capability-
    # negotiated peers (one correlated trace across the cluster) and
    # return the remote span breakdowns to the origin
    cluster_trace_propagation: bool = True
    cluster_trace_return: bool = True
    # per-node metric-snapshot gossip feeding /cluster/metrics and
    # $SYS/broker/cluster/health/*; 0 disables the periodic gossip
    # (skew probes and trace returns stay on)
    cluster_telemetry_interval_s: float = 5.0
    cluster_telemetry_full_every: int = 10   # full snapshot every Nth send

    # -- publish-path tracing (ADR 015) ---------------------------------------
    # sample every Nth publish into the pipeline tracer (0 = off; off
    # costs one branch per stage). Sampled publishes feed the per-stage
    # latency histograms, the flight recorder (/traces, /traces/chrome
    # on the metrics server) and $SYS/broker/trace/*.
    trace_sample_n: int = 0
    trace_slow_ms: float = 0.0          # flight-record only e2e >= this;
                                        # 0 records every sampled publish
    trace_ring: int = 64                # flight-recorder entries kept

    # -- zero-copy fan-out (ADR 019) ------------------------------------------
    # assemble patched-template frame heads with the C encoder when the
    # native extension loads (any native error falls back per call to
    # the byte-identical Python builder); off forces pure Python
    broker_native_encode: bool = True
    # coalesce writer-task wake-ups to one per event-loop iteration so
    # a 1->N fan-out wakes each subscriber's writer once with its full
    # backlog queued; off restores the per-enqueue direct wake
    broker_flush_coalesce: bool = True

    # -- MQTT+ content plane (ADR 023) ----------------------------------------
    # parse ?$expr=/?$agg= subscription options and run the vectorized
    # payload-predicate / windowed-aggregation plane on the publish
    # batch path; off leaves '?' a plain topic character end to end
    filter_enabled: bool = True
    filter_backend: str = "numpy"       # numpy | jnp | auto (jnp rides
                                        # the device with a breaker
                                        # fallback to numpy, ADR 011)
    filter_max_subscriptions: int = 10000  # content subs per broker
    filter_max_expr_len: int = 512      # $expr source-length bound
    filter_max_fields: int = 64         # distinct decoded payload fields
    filter_batch_max: int = 256         # pipeline publishes per eval flush
    filter_window_min_s: float = 0.5    # accepted $win range, seconds
    filter_window_max_s: float = 3600.0
    # stretch (off by default): annotate route advertisements with the
    # predicates of fully-gated filters so a bridge peer skips forwards
    # no remote predicate can pass — counted, correctness-preserving
    cluster_content_routes: bool = False

    # -- event loop (ADR 023 satellite) ---------------------------------------
    # auto = uvloop when installed, else asyncio; uvloop warns + falls
    # back cleanly when the package is missing
    broker_event_loop: str = "auto"     # auto | asyncio | uvloop

    # -- persistence --------------------------------------------------------
    storage_backend: str = ""           # "" | memory | sqlite
    storage_path: str = "maxmq.db"

    # -- crash-consistent storage pipeline (ADR 014) --------------------------
    # durability policy: always = QoS acks release through a fsync
    # barrier (group-committed); batched = one fsync per batch window
    # (acks immediate, crash can lose the window); off = no fsync
    storage_sync: str = "batched"
    storage_batch_ms: int = 20          # group-commit window (batched/off)
    storage_batch_ops: int = 512        # max ops per backend transaction
    storage_queue_bytes: int = 4 << 20  # journal watermark; sheds above
    storage_breaker_threshold: int = 5  # consecutive commit failures
    storage_breaker_backoff_s: float = 1.0       # first reprobe delay
    storage_breaker_backoff_max_s: float = 30.0  # backoff doubles to here

    # -- auth ---------------------------------------------------------------
    auth_ledger: str = ""               # path to rules (.json/.yaml); empty
                                        # = allow-all

    # -- TPU matcher runtime (no reference equivalent: the north-star path) --
    matcher: str = "sig"                # trie | nfa | dense | sig | service
    matcher_batch_window_us: int = 200
    matcher_max_batch: int = 256
    # native decode emits fan-out-ready DeliveryIntents (ADR 007)
    # instead of merged SubscriberSet dicts on the publish hot path
    matcher_intents: bool = True
    matcher_max_levels: int = 16
    matcher_mesh: str = ""              # e.g. "2x4" to shard over a mesh
    matcher_socket: str = "/tmp/maxmq-matcher.sock"  # matcher = "service"

    # -- matcher degradation ladder (ADR 011) --------------------------------
    # wrap the device/service matcher in the supervisor: per-batch
    # deadline, trie hedge on error, circuit breaker, half-open reprobe
    matcher_supervised: bool = True
    matcher_deadline_ms: int = 250      # per-batch deadline; 0 disables
    matcher_breaker_threshold: int = 5  # failures in the window that trip
    matcher_breaker_window_s: float = 10.0
    matcher_breaker_backoff_s: float = 1.0      # first open interval
    matcher_breaker_backoff_max_s: float = 30.0  # backoff doubles to here

    # -- worker pool observability -------------------------------------------
    # optional metrics endpoint served by the POOL PARENT (worker 0 owns
    # conf.metrics_address): exposes maxmq_pool_* supervision counters
    pool_metrics_address: str = ""

    # -- in-box worker mesh (ADR 021) -----------------------------------------
    # workers > 1 federates the SO_REUSEPORT workers as cluster nodes
    # over unix-domain bridge links (the `local` link flavor); these
    # knobs tune ONLY the loopback links — the box's external cluster_*
    # knobs are untouched and compose (worker 0 carries cluster_peers)
    worker_link_keepalive: float = 1.0  # loopback ping interval, seconds
    worker_link_byte_budget: int = 0    # per-link queued bytes; 0 =
                                        # budget-exempt (loopback default;
                                        # LINK_QUEUE_MAX still bounds)
    # session replication policy on the worker mesh: always = QoS acks
    # ride the loopback replication barrier, so a SIGKILLed worker's
    # sibling redelivers every PUBACKed message (cheap on one box)
    worker_session_sync: str = "always"
    worker_link_dir: str = ""           # socket dir; "" = /tmp/maxmq-
                                        # pool-<pid>
    worker_journal_owner: int = 0       # which worker owns the ONE
                                        # ADR-014 journal writer

    # -- profiling ----------------------------------------------------------
    profile: bool = False
    profile_path: str = "."


DEFAULT_CONFIG_NAME = "maxmq.conf"
CONFIG_SEARCH_PATHS = (".", "/etc/maxmq", "/etc")


def default_config() -> Config:
    return Config()


def read_config_file(path: str | None = None) -> dict:
    """Read the TOML config file. With no explicit path, search the standard
    locations; a missing file is not an error (returns {})."""
    if path is not None:
        with open(path, "rb") as f:
            return tomllib.load(f)
    for d in CONFIG_SEARCH_PATHS:
        candidate = os.path.join(d, DEFAULT_CONFIG_NAME)
        if os.path.isfile(candidate):
            with open(candidate, "rb") as f:
                return tomllib.load(f)
    return {}


def _coerce(value, typ):
    if typ is bool:
        if isinstance(value, bool):
            return value
        return str(value).strip().lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return str(value)


# the reference spells a few keys differently (internal/config/
# config.go:27-94); accept its names verbatim so a maxmq.conf written
# for the reference drops in unchanged
_REFERENCE_ALIASES = {
    "mqtt_max_session_expiry_interval": "mqtt_session_expiry_interval",
    "mqtt_max_outbound_messages": "mqtt_max_outbound_queue",
    "mqtt_subscription_identifier_available":
        "mqtt_subscription_id_available",
    "mqtt_sys_topic_update_interval": "mqtt_sys_topic_interval",
}


def load_config(path: str | None = None,
                env: dict[str, str] | None = None) -> Config:
    """defaults ← TOML file ← MAXMQ_* env, in increasing precedence."""
    env = os.environ if env is None else env
    data = read_config_file(path)
    for ref_key, our_key in _REFERENCE_ALIASES.items():
        if ref_key in data and our_key not in data:
            data[our_key] = data[ref_key]
    conf = Config()
    defaults = Config()
    for f in fields(Config):
        typ = type(getattr(defaults, f.name))
        if f.name in data:
            setattr(conf, f.name, _coerce(data[f.name], typ))
        env_key = "MAXMQ_" + f.name.upper()
        if env_key in env:
            setattr(conf, f.name, _coerce(env[env_key], typ))
    for ref_key, our_key in _REFERENCE_ALIASES.items():
        env_key = "MAXMQ_" + ref_key.upper()
        if env_key in env and "MAXMQ_" + our_key.upper() not in env:
            typ = type(getattr(defaults, our_key))
            setattr(conf, our_key, _coerce(env[env_key], typ))
    return conf


def config_as_dict(conf: Config) -> dict:
    """The full effective config, for the DEBUG boot log (start.go:119-123)."""
    return dataclasses.asdict(conf)
