"""Cluster membership: static seed list + liveness from bridge
keepalives (ADR 013).

Membership here is deliberately NOT a consensus protocol: the peer set
is the operator-supplied seed list (``cluster_peers``), and the only
dynamic fact tracked per peer is link liveness — last successful
keepalive/connect, connection state, and the flap count. A peer whose
link is down keeps its routes in the table (delivery degrades to
local-only while forwards to it are skipped); a peer that RESTARTED is
detected by the higher epoch in its first snapshot, which flushes the
old incarnation's routes (routes.py).
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field

# node ids ride inside ``$cluster/...`` topic levels: one level, no
# wildcards, no separators
_NODE_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


class PeerSpecError(ValueError):
    pass


def valid_node_id(node_id: str) -> bool:
    return bool(_NODE_ID_RE.match(node_id))


@dataclass(frozen=True)
class PeerSpec:
    node_id: str
    host: str
    port: int
    # ADR 021 local link flavor: a non-empty unix-socket path replaces
    # host:port — the bridge connects over the loopback filesystem
    # (no TCP handshake, budget-exempt, clock skew pinned to zero)
    path: str = ""

    @property
    def local(self) -> bool:
        return bool(self.path)


def parse_peers(spec: str) -> list[PeerSpec]:
    """Parse ``cluster_peers``: comma-separated ``node@host:port``
    entries (``nodeB@10.0.0.2:1883,nodeC@10.0.0.3:1883``). An
    ``node@unix:/path.sock`` entry is an ADR-021 local (unix-domain)
    peer — the in-box worker mesh rides these."""
    peers: list[PeerSpec] = []
    seen: set[str] = set()
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        node_id, at, addr = entry.partition("@")
        if not at:
            raise PeerSpecError(
                f"bad peer {entry!r} (want node@host:port)")
        if not valid_node_id(node_id):
            raise PeerSpecError(f"bad peer node id {node_id!r}")
        if node_id in seen:
            raise PeerSpecError(f"duplicate peer node id {node_id!r}")
        seen.add(node_id)
        if addr.startswith("unix:"):
            path = addr[len("unix:"):]
            if not path:
                raise PeerSpecError(f"bad peer {entry!r} "
                                    f"(want node@unix:/path.sock)")
            peers.append(PeerSpec(node_id, "", 0, path=path))
            continue
        host, colon, port_s = addr.rpartition(":")
        if not colon or not host:
            raise PeerSpecError(
                f"bad peer {entry!r} (want node@host:port)")
        try:
            port = int(port_s)
        except ValueError:
            raise PeerSpecError(f"bad peer port {port_s!r}") from None
        peers.append(PeerSpec(node_id, host, port))
    return peers


@dataclass
class PeerState:
    spec: PeerSpec
    connected: bool = False
    last_seen: float = 0.0          # monotonic; last keepalive/connect
    epoch: int = 0                  # last snapshot epoch seen
    flaps: int = 0                  # up->down transitions
    connect_attempts: int = 0
    last_error: str = ""
    # ADR 017: wire capabilities the peer announced on $cluster/hello
    # (version negotiation — a peer that never said "fwd-trace" gets
    # pre-017 envelopes, so an old binary never sees the new segment)
    caps: frozenset = frozenset()
    # ADR 017: EWMA clock-skew estimate from the keepalive-driven
    # clock probes — peer_monotonic_ns minus ours at the RTT midpoint
    skew_ns: float = 0.0
    rtt_ns: float = 0.0
    skew_samples: int = 0
    extras: dict = field(default_factory=dict)


class Membership:
    """Peer liveness ledger, updated by the bridge links."""

    def __init__(self, peers: list[PeerSpec]) -> None:
        self.peers: dict[str, PeerState] = {
            p.node_id: PeerState(spec=p) for p in peers}

    def get(self, node_id: str) -> PeerState | None:
        return self.peers.get(node_id)

    def note_up(self, node_id: str) -> None:
        st = self.peers.get(node_id)
        if st is not None:
            st.connected = True
            st.last_seen = time.monotonic()

    def note_alive(self, node_id: str) -> None:
        st = self.peers.get(node_id)
        if st is not None:
            st.last_seen = time.monotonic()

    def note_down(self, node_id: str, error: str = "") -> None:
        st = self.peers.get(node_id)
        if st is None:
            return
        if st.connected:
            st.flaps += 1
        st.connected = False
        if error:
            st.last_error = error

    def live_nodes(self) -> list[str]:
        return [n for n, st in self.peers.items() if st.connected]
