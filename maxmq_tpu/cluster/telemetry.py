"""Cluster observability plane (ADR 017): federated metric snapshots,
per-peer clock-skew estimation, and the cross-node trace span-return
leg.

Three concerns, one module, because they share the same wire rails
(budget-exempt ``send_control`` over the ADR-013 bridge links, relayed
transitively with the fwd hop cap) and the same consumer (the operator
staring at ONE node while the cluster misbehaves):

* **Telemetry gossip** — each node broadcasts a debounced,
  delta-encoded, cardinality-bounded snapshot of its headline metrics
  on ``$cluster/telemetry/<node>`` (full snapshot every
  ``full_every``-th send so a delta lost to a link flap heals itself).
  Any node can then serve ``/cluster/metrics``: every live peer's
  counters with ``node=`` labels, in Prometheus text format, validated
  by the same ``check_metrics_exposition.py`` conformance gate as the
  local page.
* **Clock skew** — bridge keepalives drive an NTP-style probe
  (``$cluster/clock/<node>`` -> ``.../reply``): the requester stamps
  t0, the peer echoes it with its own clock tp, and the requester
  estimates ``skew = tp - (t0 + rtt/2)`` at the RTT midpoint, EWMA'd.
  The estimate translates cross-node trace timestamps into one
  timeline (monotonic clocks have per-process epochs — raw stamps from
  two nodes are not comparable) and is exposed as
  ``maxmq_cluster_peer_clock_skew_ms``.
* **Span returns** — when an ADOPTED trace (trace.py) finishes on a
  receiving node, its span breakdown is fire-and-forgotten back to the
  origin on ``$cluster/trace/<origin>`` (relayed toward it through the
  mesh, deduped per reporter), where ``PipelineTracer.attach_remote``
  lands it on the origin's flight-recorder entry and the per-hop
  cross-node e2e histograms. Budget-exempt but strictly bounded: a
  report whose trace already left the recorder is counted and dropped.
"""

from __future__ import annotations

import asyncio
import json
import time

from ..metrics import _fmt, _lbl    # the shared exposition formatters

# what this build can parse; announced on $cluster/hello at link-up.
# A peer that never announced "fwd-trace" receives pre-017 envelopes.
WIRE_CAPS = ("fwd-trace", "telemetry", "clock", "trace-return",
             "blip-hb")

TELEMETRY_MAX_KEYS = 48     # snapshot cardinality bound (per node)
TRACE_SPANS_MAX = 16        # spans carried per returned report
TRACE_DEDUP = 1024          # per-reporter trace-id dedup window
SKEW_EWMA_ALPHA = 0.3       # weight of the newest skew sample


class ClusterTelemetry:
    """The ADR-017 observability sidecar of one ClusterManager."""

    def __init__(self, manager, *, interval_s: float = 5.0,
                 full_every: int = 10, trace_return: bool = True,
                 max_keys: int = TELEMETRY_MAX_KEYS) -> None:
        self.manager = manager
        self.broker = manager.broker
        self.node_id = manager.node_id
        self.interval_s = max(float(interval_s), 0.0)
        self.full_every = max(int(full_every), 1)
        self.trace_return = trace_return
        self.max_keys = max(int(max_keys), 1)

        # node -> {"s": seq, "t": monotonic, "d": {name: [kind, value]}}
        self.peers: dict[str, dict] = {}
        self._last_sent: dict[str, list] = {}
        self._seq = 0
        self._sends = 0
        self._task: asyncio.Task | None = None
        # per-reporter dedup of returned span reports (redundant mesh
        # paths deliver copies; the cross-node histogram must observe
        # each report once)
        self._trace_seen: dict[str, object] = {}

        self.snapshots_sent = 0
        self.snapshots_applied = 0
        self.snapshots_stale = 0
        self.snapshot_relays = 0
        self.probes_sent = 0
        self.probe_replies = 0
        self.skew_updates = 0
        self.trace_reports_sent = 0
        self.trace_reports_relayed = 0
        self.trace_reports_received = 0
        self.inbound_rejected = 0

    # ------------------------------------------------------------------
    # Lifecycle (driven by ClusterManager.start/close)
    # ------------------------------------------------------------------

    def start(self) -> None:
        tracer = getattr(self.broker, "tracer", None)
        if tracer is not None:
            tracer.node_id = self.node_id
            if self.trace_return:
                tracer.on_adopted_finish = self._report_adopted
        if self.interval_s > 0:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name=f"cluster-telemetry-{self.node_id}")

    def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        tracer = getattr(self.broker, "tracer", None)
        if tracer is not None and tracer.on_adopted_finish is not None:
            tracer.on_adopted_finish = None

    async def _run(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.interval_s)
                self.gossip_tick()
        except asyncio.CancelledError:
            pass

    def on_link_up(self, link) -> None:
        """A fresh link: probe its clock and ship it a full snapshot so
        the peer's operator view converges without waiting a period."""
        self.probe_peer(link)
        self._send_snapshot(self._local_snapshot(), full=True,
                            only=link)

    def on_link_alive(self, link) -> None:
        """Keepalive round-trip completed: refresh the skew estimate
        (the probe rides the same cadence as the ping that proved the
        link, so a congested link's estimate decays with its RTT)."""
        self.probe_peer(link)

    # ------------------------------------------------------------------
    # Telemetry gossip
    # ------------------------------------------------------------------

    def _local_snapshot(self) -> dict[str, list]:
        """This node's headline counters as {family: [kind, value]} —
        a fixed, curated list (the cardinality bound is by
        construction; ``max_keys`` is the rail behind it)."""
        b = self.broker
        mgr = self.manager
        info = b.info
        d: dict[str, list] = {
            "maxmq_mqtt_messages_received":
                ["counter", info.messages_received],
            "maxmq_mqtt_messages_sent": ["counter", info.messages_sent],
            "maxmq_mqtt_messages_dropped":
                ["counter", info.messages_dropped],
            "maxmq_mqtt_clients_connected":
                ["gauge", info.clients_connected],
            "maxmq_mqtt_subscriptions":
                ["gauge", b.topics.subscription_count],
            "maxmq_mqtt_retained": ["gauge", b.topics.retained_count],
            "maxmq_mqtt_inflight": ["gauge", info.inflight],
            "maxmq_cluster_links_up": ["gauge", mgr.links_up],
            "maxmq_cluster_routes_held":
                ["gauge", mgr.routes.remote_route_count],
            "maxmq_cluster_forwards_sent_total":
                ["counter", mgr.forwards_sent],
            "maxmq_cluster_forwards_delivered_total":
                ["counter", mgr.forwards_delivered],
            "maxmq_cluster_loops_dropped_total":
                ["counter", mgr.loops_dropped],
        }
        over = getattr(b, "overload", None)
        if over is not None:
            d["maxmq_broker_overload_queued_bytes"] = \
                ["gauge", over.queued_bytes]
            d["maxmq_broker_overload_shedding"] = \
                ["gauge", int(over.shedding)]
        sess = getattr(mgr, "sessions", None)
        if sess is not None:
            d["maxmq_cluster_session_ledger"] = \
                ["gauge", sess.ledger_size]
            d["maxmq_cluster_session_local"] = \
                ["gauge", sess.local_sessions]
        jr = getattr(b, "_journal", None)
        if jr is not None:
            d["maxmq_storage_breaker_state"] = \
                ["gauge", jr.breaker_state]
            d["maxmq_storage_queue_depth"] = ["gauge", jr.queue_depth]
        if len(d) > self.max_keys:
            d = {k: d[k] for k in sorted(d)[:self.max_keys]}
        return d

    def gossip_tick(self) -> None:
        """One debounced pass: diff the live snapshot against what was
        last sent, broadcast the delta (or, every ``full_every``-th
        send, the whole snapshot so lost deltas self-heal)."""
        snap = self._local_snapshot()
        full = self._sends % self.full_every == 0
        if full:
            d = snap
        else:
            last = self._last_sent
            d = {k: v for k, v in snap.items() if last.get(k) != v}
        if not d:
            return                      # nothing changed: stay quiet
        self._sends += 1
        self._last_sent = snap
        self._send_snapshot(d, full=full)

    def _send_snapshot(self, d: dict, full: bool, only=None) -> None:
        self._seq += 1
        msg = {"v": 1, "o": self.node_id, "s": self._seq, "h": 1,
               "full": int(full), "d": d}
        payload = json.dumps(msg).encode()
        topic = f"$cluster/telemetry/{self.node_id}"
        links = ([only] if only is not None
                 else self.manager.links.values())
        for link in links:
            if link.connected and link.send_control(topic, payload):
                self.snapshots_sent += 1

    def handle_snapshot(self, sender: str, levels: list[str],
                        packet) -> None:
        try:
            msg = json.loads(packet.payload)
            origin = str(msg["o"])
            seq = int(msg["s"])
            hops = int(msg.get("h", 1))
            d = dict(msg.get("d") or {})
        except Exception:
            self.inbound_rejected += 1
            return
        if origin == self.node_id:
            return                      # our own gossip came back
        held = self.peers.get(origin)
        if held is not None and seq <= held["s"]:
            self.snapshots_stale += 1
            return
        if held is None or msg.get("full"):
            held = self.peers[origin] = {"s": seq, "t": 0.0, "d": {}}
            merged = d
        else:
            merged = held["d"]
            merged.update(d)
        if len(merged) > self.max_keys:     # hostile/buggy peer rail
            merged = {k: merged[k] for k in sorted(merged)
                      [:self.max_keys]}
        held["d"] = merged
        held["s"] = seq
        held["t"] = time.monotonic()
        self.snapshots_applied += 1
        if hops < self.manager.max_hops:
            self._relay_snapshot(msg, sender, origin, hops)

    def _relay_snapshot(self, msg: dict, sender: str, origin: str,
                        hops: int) -> None:
        out = dict(msg)
        out["h"] = hops + 1
        payload = json.dumps(out).encode()
        topic = f"$cluster/telemetry/{origin}"
        for peer, link in self.manager.links.items():
            if peer in (sender, origin) or not link.connected:
                continue
            if link.send_control(topic, payload):
                self.snapshot_relays += 1

    def cluster_exposition(self) -> str:
        """The ``/cluster/metrics`` page: every known family, one
        series per node (self from the live counters, peers from their
        latest applied snapshots), plus per-peer snapshot age. Emitted
        in Prometheus text format 0.0.4 — `check_metrics_exposition.py`
        conformant by construction."""
        now = time.monotonic()
        fams: dict[str, tuple[str, dict[str, float]]] = {}

        def fold(node: str, snap: dict) -> None:
            for name, kv in snap.items():
                try:
                    kind, value = str(kv[0]), float(kv[1])
                except (TypeError, ValueError, IndexError):
                    continue
                if kind not in ("counter", "gauge"):
                    kind = "gauge"
                fam = fams.get(name)
                if fam is None:
                    fam = fams[name] = (kind, {})
                fam[1][node] = value

        fold(self.node_id, self._local_snapshot())
        ages: dict[str, float] = {}
        for node, held in self.peers.items():
            fold(node, held["d"])
            ages[node] = max(now - held["t"], 0.0)
        out: list[str] = []
        for name in sorted(fams):
            kind, series = fams[name]
            out.append(f"# HELP {name} Cluster-aggregated from "
                       f"per-node telemetry snapshots (ADR 017)")
            out.append(f"# TYPE {name} {kind}")
            for node in sorted(series):
                out.append(f"{name}{{{_lbl({'node': node})}}} "
                           f"{_fmt(series[node])}")
        out.append("# HELP maxmq_cluster_telemetry_age_seconds Age of "
                   "the newest applied snapshot per peer")
        out.append("# TYPE maxmq_cluster_telemetry_age_seconds gauge")
        out.append(f"maxmq_cluster_telemetry_age_seconds"
                   f"{{{_lbl({'node': self.node_id})}}} 0")
        for node in sorted(ages):
            out.append(f"maxmq_cluster_telemetry_age_seconds"
                       f"{{{_lbl({'node': node})}}} "
                       f"{_fmt(round(ages[node], 3))}")
        return "\n".join(out) + "\n"

    # ------------------------------------------------------------------
    # Clock-skew probes
    # ------------------------------------------------------------------

    def _clock(self) -> int:
        tracer = getattr(self.broker, "tracer", None)
        if tracer is not None:
            return tracer.clock()
        from .. import faults
        return faults.REGISTRY.clock_ns()

    def probe_peer(self, link) -> None:
        if not link.connected:
            return
        if getattr(link, "local", False):
            # ADR 021: a loopback (unix-domain) worker link shares this
            # host's monotonic clock — skew is zero by construction.
            # Pin the estimate instead of probing so the correlated-
            # trace math and /cluster/metrics read the truth at zero
            # wire cost.
            st = self.manager.membership.get(link.peer)
            if st is not None:
                st.skew_ns, st.rtt_ns = 0.0, 0.0
                st.skew_samples += 1
            return
        payload = json.dumps({"t0": self._clock()}).encode()
        if link.send_control(f"$cluster/clock/{self.node_id}", payload):
            self.probes_sent += 1

    def handle_clock(self, sender: str, levels: list[str],
                     packet) -> None:
        """Both probe legs: a bare ``$cluster/clock/<peer>`` is a
        request (echo t0 + our clock back on OUR link to the peer); a
        ``.../reply`` closes the loop and updates the estimate."""
        try:
            msg = json.loads(packet.payload)
        except Exception:
            self.inbound_rejected += 1
            return
        if len(levels) >= 4 and levels[3] == "reply":
            self._apply_clock_reply(sender, msg)
            return
        link = self.manager.links.get(sender)
        if link is None or not link.connected:
            return                      # asymmetric wiring: no way back
        payload = json.dumps({"t0": msg.get("t0", 0),
                              "tp": self._clock()}).encode()
        link.send_control(f"$cluster/clock/{self.node_id}/reply",
                          payload)
        self.probe_replies += 1

    def _apply_clock_reply(self, sender: str, msg: dict) -> None:
        st = self.manager.membership.get(sender)
        if st is None:
            return
        try:
            t0, tp = int(msg["t0"]), int(msg["tp"])
        except (KeyError, TypeError, ValueError):
            self.inbound_rejected += 1
            return
        t1 = self._clock()
        rtt = t1 - t0
        if rtt < 0:
            self.inbound_rejected += 1  # echoed t0 from the future
            return
        skew = tp - (t0 + rtt / 2)      # peer clock at the midpoint
        if st.skew_samples == 0:
            st.skew_ns, st.rtt_ns = float(skew), float(rtt)
        else:
            a = SKEW_EWMA_ALPHA
            st.skew_ns += a * (skew - st.skew_ns)
            st.rtt_ns += a * (rtt - st.rtt_ns)
        st.skew_samples += 1
        self.skew_updates += 1

    def skew_ns(self, peer: str) -> int:
        st = self.manager.membership.get(peer)
        return int(st.skew_ns) if st is not None else 0

    # ------------------------------------------------------------------
    # Span-return leg
    # ------------------------------------------------------------------

    def _report_adopted(self, trace, entry: dict) -> None:
        """tracer.on_adopted_finish: ship this node's span breakdown
        of a remote-origin trace back toward the origin."""
        spans = [[s["stage"], s["off_us"], s["dur_us"]]
                 for s in entry["spans"][:TRACE_SPANS_MAX]]
        self.send_report(trace.origin, trace.id, spans,
                         e2e_us=int(entry["e2e_ms"] * 1000),
                         hops=trace.hops, degraded=entry["degraded"])

    def send_report(self, origin: str, trace_id: int, spans: list,
                    e2e_us: int, hops: int = 1, degraded: str = "",
                    kind: str = "pub") -> None:
        """Fire-and-forget one span report toward ``origin`` (used by
        the adopted-publish leg above and the ADR-016 session-state
        ship leg, kind="sess"). Floods this node's links; intermediates
        relay with the fwd hop cap and the origin dedups per
        reporter."""
        msg = {"v": 1, "o": origin, "i": trace_id, "n": self.node_id,
               "h": max(int(hops), 1), "rh": 1, "e2e_us": int(e2e_us),
               "deg": degraded, "k": kind, "spans": spans}
        self._flood_report(msg, exclude=set())
        self.trace_reports_sent += 1

    def _flood_report(self, msg: dict, exclude: set) -> None:
        payload = json.dumps(msg).encode()
        topic = f"$cluster/trace/{msg['o']}"
        # shortcut: a live direct link to the origin carries the report
        # alone — flooding is only for topologies where the origin is
        # hops away (a line's far end, a partitioned mesh corner)
        direct = self.manager.links.get(msg["o"])
        if direct is not None and direct.connected \
                and direct.send_control(topic, payload):
            return
        for peer, link in self.manager.links.items():
            if peer in exclude or not link.connected:
                continue
            link.send_control(topic, payload)

    def handle_trace(self, sender: str, levels: list[str],
                     packet) -> None:
        try:
            msg = json.loads(packet.payload)
            origin = str(msg["o"])
            reporter = str(msg["n"])
            trace_id = int(msg["i"])
            relay_hops = int(msg.get("rh", 1))
        except Exception:
            self.inbound_rejected += 1
            return
        if origin == self.node_id:
            from .manager import DedupWindow
            win = self._trace_seen.get(reporter)
            if win is None:
                win = self._trace_seen[reporter] = \
                    DedupWindow(cap=TRACE_DEDUP)
            if not win.admit(trace_id):
                return                  # redundant mesh path
            self.trace_reports_received += 1
            tracer = getattr(self.broker, "tracer", None)
            if tracer is not None:
                tracer.attach_remote(msg)
            return
        if relay_hops >= self.manager.max_hops:
            return
        out = dict(msg)
        out["rh"] = relay_hops + 1
        self.trace_reports_relayed += 1
        self._flood_report(out, exclude={sender, reporter})
