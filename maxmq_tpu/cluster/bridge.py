"""Outbound bridge link to one cluster peer (ADR 013).

A bridge is an ordinary MQTT v3.1.1 client connection (built on
``mqtt_client.MQTTClient``) from this node to a peer broker, carrying
three kinds of traffic on reserved ``$cluster/*`` topics: route
snapshots/deltas, sync requests, and forwarded publishes. The peer
recognizes the link by its ``$maxmq-cluster/<node>`` client id and
diverts those topics to its own ClusterManager before the normal
``$``-namespace drop (broker/server.py).

Robustness rails, mirroring the ADR-011 supervisor and the ADR-012
ledger:

* **Reconnect** — one supervisor task per link: capped exponential
  backoff between attempts, reset on a successful CONNACK; every
  attempt and flap is counted. The deterministic ``cluster.link``
  fault site (keyed per peer: ``cluster.link#<node>``) can kill or
  hang the link on demand.
* **Backpressure** — outbound traffic rides a byte-accounted
  :class:`~..broker.client.OutboundQueue` wired into the broker's
  ADR-012 overload ledger, so a slow/partitioned peer counts against
  the global watermarks instead of buffering unboundedly. Forwarded
  publishes past the link byte budget are refused (QoS0) or refused
  *and rolled back* (QoS1: the provisional ack entry is withdrawn —
  nothing leaks awaiting an ack that can never come); route/control
  messages are budget-exempt, like acks in the broker's own queues.
* **Liveness** — an idle link pings every ``keepalive`` seconds; a
  failed ping tears the link down into the reconnect loop and marks
  the peer down in the membership ledger.
* **Partition tolerance (ADR 018)** — the directed
  ``cluster.partition`` fault site fires at every boundary this link's
  bytes cross (connect, ping, per-item writer), so a chaos harness can
  blackhole or delay one direction deterministically; and QoS1
  forwards that a partition strands (refused by a down link, or
  unacked when the link dies) PARK in a bounded, journal-backed buffer
  and re-send on link-up — the receiver's per-(origin, epoch) msgid
  dedup makes the retry at-most-once-delivered, so a PUBACKed publish
  survives the partition instead of vanishing with the link.
* **WAN shaping (ADR 022)** — the directed ``cluster.shape`` spec
  (delay/jitter/token-bucket rate/loss) rides the same boundaries:
  connect and keepalive pay the emulated round trip (with the
  RTT-adaptive ping deadline keeping a healthy slow link alive), and
  the writer releases items through a non-blocking reorder-preserving
  deferral queue, so a shaped link throttles without wedging the
  event loop or reordering the FIFO stream the blip audit relies on.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque

from .. import faults
from ..broker.client import OutboundQueue
from ..mqtt_client import MQTTClient, MQTTError
from ..protocol.codec import FixedHeader, PacketType as PT
from ..protocol.packets import Packet

BRIDGE_ID_PREFIX = "$maxmq-cluster/"

# per-link queue entry cap (the byte budget is the real limit; this
# bounds entry-count bookkeeping the same way broker queues are capped)
LINK_QUEUE_MAX = 8192
BURST_BYTES = 65536

# parked-forward bound (ADR 018): QoS1 forwards stranded by a down or
# partitioned link awaiting retry-after-heal; oldest dropped (counted)
# past the cap — the bounded-staleness contract, never unbounded memory
PARKED_MAX = 2048

# journal bucket for parked forwards (survives the PARKING node's own
# crash; restored by ClusterManager.start)
FWD_BUCKET = "cluster_fwd"

# ADR 022: cap on wire items stamped into the writer's deferral queue
# ("in flight on the shaped link"). Items past the cap stay in the
# outbound queue — still byte-accounted on the ADR-012 ledger — so a
# slow shaped link back-pressures instead of un-accounting unboundedly,
# exactly like a full egress ring on a real NIC
DEFER_MAX = 512


class BridgeLink:
    """One supervised outbound link to a peer broker."""

    def __init__(self, manager, spec, *, node_id: str, qos: int = 0,
                 byte_budget: int = 4 << 20, keepalive: float = 10.0,
                 backoff_initial_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 connect_timeout: float = 5.0) -> None:
        self.manager = manager
        self.spec = spec
        self.node_id = node_id          # OUR node id (client identity)
        self.peer = spec.node_id
        self.qos = qos
        self.byte_budget = byte_budget
        self.keepalive = keepalive
        self.backoff_initial_s = backoff_initial_s
        self.backoff_max_s = backoff_max_s
        self.connect_timeout = connect_timeout
        # ADR 021: unix-domain loopback link (worker mesh) — connects
        # by path, skips clock-skew probes (one host, one clock), and
        # the pool wiring passes byte_budget=0 (budget-exempt)
        self.local = spec.local
        if self.local:
            # a sibling's socket appears within milliseconds of its
            # boot/respawn; the TCP backoff floor would dominate pool
            # start and post-crash reconvergence
            self.backoff_initial_s = min(self.backoff_initial_s, 0.05)

        broker = manager.broker
        self.outbound = OutboundQueue(
            LINK_QUEUE_MAX, overload=getattr(broker, "overload", None))
        self.client: MQTTClient | None = None
        self.connected = False
        # what this link last told the peer (split-horizon aggregated
        # set) + the per-link monotonic delta sequence; needs_snapshot
        # marks a link whose last snapshot failed to enqueue and must
        # be retried before any delta may flow
        self.advertised: set[str] = set()
        # ADR 023 stretch: the predicate annotations last sent on this
        # link (None while content routes are off / before the first
        # annotated snapshot) — annotation drift forces a snapshot
        self.advertised_preds: dict[str, list[str]] | None = None
        self.route_seq = 0
        self.needs_snapshot = False

        self.connect_attempts = 0
        self.forwards_sent = 0
        self.forwards_refused = 0
        self.forwards_acked = 0
        self.forward_ack_failures = 0
        self.control_sent = 0
        self.session_sent = 0       # ADR-016 session-federation messages
        # ADR 018: parked QoS1 forwards awaiting retry-after-heal
        # [(envelope topic, payload, journal key), ...]; the key set
        # mirrors it for O(1) already-parked checks
        self.parked: deque[tuple[str, bytes, str]] = deque()
        self._parked_keys: set[str] = set()
        self.forwards_parked = 0
        self.parked_dropped = 0     # oldest shed past PARKED_MAX
        self.parked_resent = 0
        self.partition_drops = 0    # writer items the fault blackholed
        # ADR 022 WAN shaping: the writer-side deferral queue —
        # [(depart_ns, wire item), ...] FIFO, release times monotonic
        # by construction (ShapeSpec clamps) — plus its counters and
        # the persistent outbound getter (never cancelled mid-get: a
        # cancelled get can lose an already-popped, de-accounted item)
        self._deferred: deque[tuple[int, bytes]] = deque()
        self._pending_get: asyncio.Future | None = None
        self.shape_deferrals = 0    # writer items the shape delayed
        # ADR 020 sub-keepalive blip detection: per-connection monotonic
        # heartbeat seq + cumulative data-item enqueue count (both reset
        # at connect — the peer's fresh server-side client resets its
        # mirror), and the debounce stamp for peer-reported blips
        self.hb_seq = 0
        self.items_sent = 0
        self.last_blip_resync = 0.0
        self._task: asyncio.Task | None = None
        self._closed = False

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name=f"cluster-link-{self.peer}")

    async def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self._teardown("closed")

    async def _run(self) -> None:
        backoff = self.backoff_initial_s
        while not self._closed:
            self.connect_attempts += 1
            st = self.manager.membership.get(self.peer)
            if st is not None:
                st.connect_attempts += 1
            try:
                await self._fire_link_fault()
                await self._connect_once()
                backoff = self.backoff_initial_s
                await self._pump()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                await self._teardown(repr(exc)[:200])
            if self._closed:
                return
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, self.backoff_max_s)

    async def _fire_link_fault(self) -> None:
        """Deterministic link fault (ADR 013): ``raise`` kills this
        attempt/iteration into the reconnect backoff, ``hang`` stalls
        the link without blocking the loop."""
        hit = faults.fire_detail(faults.CLUSTER_LINK, key=self.peer)
        if hit is not None and hit[0] == "hang":
            await asyncio.sleep(hit[1])

    async def _fire_partition(self, liveness: bool) -> None:
        """The ADR-018 directed-partition site on this link's outbound
        direction. ``liveness`` sites (connect, ping) RAISE under drop
        — a blackholed path fails its handshake/keepalive, so the link
        is detected down and enters reconnect backoff until healed;
        data sites handle drop themselves. ``hang`` delays either."""
        hit = faults.fire_detail(
            faults.CLUSTER_PARTITION,
            key=faults.partition_key(self.node_id, self.peer))
        if hit is None:
            return
        mode, delay = hit
        if mode == "hang":
            await asyncio.sleep(delay)
        elif liveness:
            raise ConnectionError(
                f"partitioned: {self.node_id}->{self.peer}")

    def _shape(self):
        """This link's outbound-direction WAN shape, or None (ADR 022;
        the common case is one dict get on an empty dict)."""
        return faults.REGISTRY.get_shape(
            faults.partition_key(self.node_id, self.peer))

    def _shape_rtt_s(self) -> float:
        """The emulated ping round trip on a shaped link: this
        direction's one-way propagation plus the reverse direction's
        when armed (asymmetric shapes yield asymmetric RTT halves)."""
        out = self._shape()
        if out is None:
            return 0.0
        back = faults.REGISTRY.get_shape(
            faults.partition_key(self.peer, self.node_id))
        return out.oneway_s + (back.oneway_s if back is not None else 0.0)

    async def _fire_shape_liveness(self) -> None:
        """ADR 022 liveness half of the shape site: a connect/ping
        probe crossing a shaped link pays the round trip in real time,
        and each loss draw (either direction) costs one RETRANSMIT
        round trip on top — TCP loss recovery never kills a healthy
        connection outright, it makes the probe slower, so sustained
        loss shows up as a blown deadline budget (the caller's ping
        timeout), not as an instant flap. Bounded at 8 retransmits so
        a pathological loss setting cannot wedge the keepalive loop
        past its own deadline check."""
        out = self._shape()
        if out is None:
            return
        rtt_s = self._shape_rtt_s()
        if rtt_s > 0:
            await asyncio.sleep(rtt_s)
        back = faults.REGISTRY.get_shape(
            faults.partition_key(self.peer, self.node_id))
        retransmits = 0
        while retransmits < 8 and (
                out.lose() or (back is not None and back.lose())):
            faults.REGISTRY.count_fired(
                f"{faults.CLUSTER_SHAPE}#"
                f"{faults.partition_key(self.node_id, self.peer)}")
            retransmits += 1
            if rtt_s > 0:
                await asyncio.sleep(rtt_s)

    async def _connect_once(self) -> None:
        await self._fire_partition(liveness=True)
        await self._fire_shape_liveness()
        client = MQTTClient(
            client_id=BRIDGE_ID_PREFIX + self.node_id,
            keepalive=max(int(self.keepalive * 3), 1))
        if self.local:
            # ADR 021 local flavor: unix-domain transport to a sibling
            # worker on this box — no TCP handshake, no network in the
            # failure model (the peer process dying IS the link dying)
            await client.connect(path=self.spec.path,
                                 timeout=self.connect_timeout)
        else:
            await client.connect(self.spec.host, self.spec.port,
                                 timeout=self.connect_timeout)
        self.client = client
        self.hb_seq = 0             # fresh connection, fresh audit frame
        self.items_sent = 0
        self.connected = True
        self.manager.membership.note_up(self.peer)
        self.manager.on_link_up(self)

    async def _teardown(self, reason: str) -> None:
        was_up = self.connected
        self.connected = False
        self.outbound.release_all()     # settle the ADR-012 ledger
        # deferred items were "in flight" on the shaped link: they die
        # with the connection like bytes in a dead TCP window (QoS1
        # forwards re-park through their failed ack futures below)
        self._deferred.clear()
        if self._closed and self._pending_get is not None:
            self._pending_get.cancel()
            self._pending_get = None
        client, self.client = self.client, None
        if client is not None:
            await client.close()
            # ack futures registered AFTER the client's read loop died
            # (the peer was SIGKILLed mid-burst) were missed by its own
            # shutdown sweep — fail them here or their forwards never
            # reclassify as stranded and a PUBACKed publish is lost
            # (ADR 018; found by the kill-restart verify drive)
            for fut in client._acks.values():
                if not fut.done():
                    fut.set_exception(MQTTError("bridge link down"))
            client._acks.clear()
        self.manager.membership.note_down(self.peer, reason)
        if was_up:
            self.manager.on_link_down(self, reason)

    # ------------------------------------------------------------------
    # Writer pump + keepalive
    # ------------------------------------------------------------------

    async def _pump(self) -> None:
        """Writer + keepalive, each its own task, first failure tears
        the link down. NOT wait_for(outbound.get(), ...): pre-3.12
        wait_for can cancel the inner await after get_nowait() already
        popped an item, silently losing an (already de-accounted)
        forward — the same reason the broker's writer loop awaits its
        queue bare (broker/client.py)."""
        tasks = [
            asyncio.get_running_loop().create_task(
                self._writer_loop(self.client),
                name=f"cluster-write-{self.peer}"),
            asyncio.get_running_loop().create_task(
                self._keepalive_loop(self.client),
                name=f"cluster-ping-{self.peer}")]
        try:
            done, _pending = await asyncio.wait(
                tasks, return_when=asyncio.FIRST_EXCEPTION)
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
            results = await asyncio.gather(*tasks,
                                           return_exceptions=True)
        for r in results:
            if isinstance(r, Exception):
                raise r
        raise ConnectionError("bridge pump ended")    # unreachable

    async def _writer_loop(self, client: MQTTClient) -> None:
        while True:
            item = await self._next_item()
            burst = 0
            while True:
                await self._fire_link_fault()
                if await self._partition_drops_item():
                    self.partition_drops += 1
                else:
                    client.writer.write(item)
                    burst += len(item)
                if burst >= BURST_BYTES:
                    break
                item = self._next_item_nowait()
                if item is None:
                    break
            await client.writer.drain()
            self.manager.membership.note_alive(self.peer)

    # -- WAN-shape deferral queue (ADR 022) ----------------------------

    def _stamp(self, item: bytes) -> None:
        """Stamp one item's departure time into the deferral queue (or
        behind the current tail when the shape was disarmed mid-drain —
        FIFO order survives an unshape)."""
        reg = faults.REGISTRY
        shp = self._shape()
        if shp is None:
            t = self._deferred[-1][0] if self._deferred else 0
        else:
            now = reg.clock_ns()
            t = shp.depart_ns(now, len(item))
            if t > now:
                self.shape_deferrals += 1
            if self._deferred and t < self._deferred[-1][0]:
                t = self._deferred[-1][0]
        self._deferred.append((t, item))

    def _stamp_available(self) -> None:
        """While the head of the deferral queue ripens, pull every
        immediately-available outbound item and stamp it NOW — the
        configured delay is pipeline latency (all items in a burst are
        in flight concurrently), not a per-item serial sleep. Bounded
        by DEFER_MAX so a slow link back-pressures on the ledger."""
        while len(self._deferred) < DEFER_MAX:
            try:
                item = self.outbound.get_nowait()
            except asyncio.QueueEmpty:
                return
            self._stamp(item)

    async def _next_item(self) -> bytes:
        """The writer's item source: the unshaped fast path is the old
        bare ``outbound.get()``; with a shape armed, items flow through
        the deferral queue and are released at their stamped departure
        times, in order, without ever blocking the event loop. The
        pending getter is NEVER cancelled between iterations (a
        cancelled get can drop an already-popped item — the same
        pre-3.12 hazard ``_pump`` documents); it persists across
        reconnects on the instance and is only cancelled at close."""
        reg = faults.REGISTRY
        while True:
            timeout = None
            if self._deferred:
                self._stamp_available()
                now = reg.clock_ns()
                head = self._deferred[0][0]
                if head <= now:
                    return self._deferred.popleft()[1]
                timeout = (head - now) / 1e9
            if self._pending_get is None:
                self._pending_get = asyncio.ensure_future(
                    self.outbound.get())
            done, _pending = await asyncio.wait({self._pending_get},
                                                timeout=timeout)
            if self._pending_get not in done:
                continue            # head came due; release it FIFO
            fut, self._pending_get = self._pending_get, None
            item = fut.result()
            if not self._deferred and self._shape() is None:
                return item         # unshaped fast path
            self._stamp(item)

    def _next_item_nowait(self) -> bytes | None:
        """Burst refill: the next item that may hit the wire right now,
        or None (queue empty, or the shaped head is still in flight)."""
        if self._deferred:
            self._stamp_available()
            if self._deferred[0][0] <= faults.REGISTRY.clock_ns():
                return self._deferred.popleft()[1]
            return None
        try:
            item = self.outbound.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if self._shape() is None:
            return item
        self._stamp(item)
        if self._deferred[0][0] <= faults.REGISTRY.clock_ns():
            return self._deferred.popleft()[1]
        return None

    async def _partition_drops_item(self) -> bool:
        """ADR 018: one writer item crossing the partitioned direction
        — drop blackholes it in flight (already de-accounted, exactly
        like bytes lost inside a dead TCP window), hang delays it."""
        hit = faults.fire_detail(
            faults.CLUSTER_PARTITION,
            key=faults.partition_key(self.node_id, self.peer))
        if hit is None:
            return False
        mode, delay = hit
        if mode == "hang":
            await asyncio.sleep(delay)
            return False
        return True

    async def _keepalive_loop(self, client: MQTTClient) -> None:
        while True:
            await asyncio.sleep(self.keepalive)
            self._send_hb()
            await self._fire_link_fault()
            await self._fire_partition(liveness=True)
            # ADR 022: the ping budget is the RTT-adaptive deadline
            # (floor + k x measured RTT); the emulated WAN round trip
            # spends part of it, and a shaped RTT the unstretched floor
            # cannot cover is exactly the false flap the adaptation
            # exists to prevent
            deadline = self.manager.link_deadline(self.peer,
                                                  self.connect_timeout)
            rtt_s = self._shape_rtt_s()
            if rtt_s >= deadline:
                raise ConnectionError(
                    f"keepalive past deadline: {self.node_id}->"
                    f"{self.peer} rtt {rtt_s:.3f}s >= {deadline:.3f}s")
            t0 = time.monotonic()
            await self._fire_shape_liveness()
            spent = time.monotonic() - t0
            if spent >= deadline:
                # emulated retransmits ate the whole budget: the link
                # is lossy past what the deadline tolerates
                raise ConnectionError(
                    f"keepalive past deadline: {self.node_id}->"
                    f"{self.peer} probe {spent:.3f}s >= {deadline:.3f}s")
            await client.ping(timeout=deadline - spent)
            self.manager.membership.note_alive(self.peer)
            # ADR 017: the proved-alive link refreshes its clock-skew
            # estimate at the keepalive cadence
            self.manager.on_link_alive(self)

    def _send_hb(self) -> None:
        """ADR 020: one audit heartbeat through the WRITER QUEUE (so it
        crosses the same partition drop site the data does — a healed
        blip shows as a seq gap), carrying this connection's monotonic
        seq and the cumulative data-item enqueue count. FIFO order
        makes the claim exact: everything counted in ``n`` was written
        (or blackholed) before this heartbeat. Uncounted on both ends;
        a full queue just skips the beat. Capability-gated like every
        post-013 wire kind: a pre-020 peer that never announced
        ``blip-hb`` is not sent frames it would count as rejected."""
        if not self.manager._peer_has_cap(self.peer, "blip-hb"):
            return
        payload = json.dumps({"seq": self.hb_seq + 1,
                              "n": self.items_sent}).encode()
        wire = self._encode_publish(f"$cluster/hb/{self.node_id}",
                                    payload, 0, False)
        try:
            self.outbound.put_nowait(wire, len(wire))
        except asyncio.QueueFull:
            return
        self.hb_seq += 1

    # ------------------------------------------------------------------
    # Enqueue side (called synchronously from the fan-out path)
    # ------------------------------------------------------------------

    def _encode_publish(self, topic: str, payload: bytes, qos: int,
                        retain: bool, packet_id: int = 0) -> bytes:
        return Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos,
                                        retain=retain),
                      protocol_version=4, topic=topic, payload=payload,
                      packet_id=packet_id).encode()

    def forward(self, topic: str, payload: bytes, qos: int = 0,
                collect: list | None = None, park: bool = False,
                _parked_key: str | None = None) -> bool:
        """Enqueue one forwarded publish; False = refused (link down,
        byte budget, or queue full). A refused QoS1 forward rolls its
        provisional ack entry back — the ADR-012 no-leak invariant
        applied to the bridge. Ledger charges are the EXACT encoded
        wire bytes (ADR 012's pre-encoded-wire discipline).

        ADR 018: ``collect`` (a list) receives the QoS1 PUBACK future —
        the fwd-durability barrier waits on it. ``park=True`` makes a
        refused or never-acked QoS1 forward PARK for retry-after-heal
        instead of being lost (the envelope's origin msgid makes the
        receiver dedup the retry)."""
        client = self.client
        if (not self.connected or client is None
                or client._closed.is_set()):
            # _closed: the client's read loop is already dead (peer
            # killed) even though the supervisor hasn't torn the link
            # down yet — an ack registered now could never resolve
            if park and qos > 0:
                self._park(topic, payload, _parked_key)
            return False
        pid = 0
        cb = None
        if qos > 0:
            pid = client._alloc_id()
            fut = client._await_ack(PT.PUBACK, pid)
            cb = self._fwd_ack_cb(topic, payload, park, _parked_key)
            fut.add_done_callback(cb)
            if collect is not None:
                collect.append(fut)
        wire = self._encode_publish(topic, payload, qos, False, pid)
        if ((self.byte_budget
                and self.outbound.bytes + len(wire) > self.byte_budget)
                or not self._try_put(wire)):
            self._handle_refusal(client, pid, qos, cb, collect, park,
                                 topic, payload, _parked_key)
            return False
        self.forwards_sent += 1
        return True

    def _handle_refusal(self, client: MQTTClient, pid: int, qos: int,
                        cb, collect: list | None, park: bool,
                        topic: str, payload: bytes,
                        parked_key: str | None) -> None:
        """One refused enqueue: count + roll the ack entry back, drop
        the cancelled future from the barrier's collect list (the
        caller counts this refusal's degrade exactly once off the
        False return), and park the copy for retry when asked."""
        self._refuse_forward(client, pid, qos, cb)
        if qos > 0:
            if collect is not None:
                collect.pop()
            if park:
                self._park(topic, payload, parked_key)

    def _try_put(self, wire: bytes) -> bool:
        try:
            self.outbound.put_nowait(wire, len(wire))
        except asyncio.QueueFull:
            return False
        self.items_sent += 1    # ADR 020: audited by the heartbeat
        return True

    def _fwd_ack_cb(self, topic: str, payload: bytes, park: bool,
                    parked_key: str | None):
        """The QoS1 forward's ack outcome: success settles (and clears
        a parked-retry journal row); a dead link's failed ack re-parks
        the forward when fwd durability is on (ADR 018) — the retry
        fires on the next link-up."""
        def cb(fut: asyncio.Future) -> None:
            if fut.cancelled() or fut.exception() is not None:
                self.forward_ack_failures += 1
                if park:
                    self._park(topic, payload, parked_key)
            else:
                self.forwards_acked += 1
                if parked_key is not None:
                    self._journal_delete(parked_key)
        return cb

    def _refuse_forward(self, client: MQTTClient, pid: int, qos: int,
                        cb=None) -> None:
        """One refused forward: count it, roll back a QoS1 ack entry,
        and attribute it to the bridge stage on the ADR-015 error
        counter so the loss shows up next to the bridge latency."""
        self.forwards_refused += 1
        tracer = getattr(self.manager.broker, "tracer", None)
        if tracer is not None:
            tracer.note_error("bridge", "refused")
        if qos > 0:
            self._rollback_refused_ack(client, pid, cb)

    def _rollback_refused_ack(self, client: MQTTClient, pid: int,
                              cb=None) -> None:
        """Withdraw the ack entry a refused QoS1 forward registered:
        the publish never hit the wire, so nothing may sit waiting for
        a PUBACK that cannot come (mirrors the broker's
        ``_rollback_refused_qos``). The park-on-failure callback is
        removed FIRST — the refusal path parks explicitly, and the
        cancel must not park a second copy."""
        fut = client._acks.pop((PT.PUBACK, pid), None)
        if fut is not None and not fut.done():
            if cb is not None:
                fut.remove_done_callback(cb)
            fut.cancel()

    # -- parked forwards (ADR 018) -------------------------------------

    def _park(self, topic: str, payload: bytes,
              key: str | None = None) -> None:
        """Park one stranded QoS1 forward for retry-after-heal: bounded
        (oldest dropped + counted past PARKED_MAX) and journaled (the
        ``cluster_fwd`` bucket — a crash of THIS node mid-partition
        still redelivers after restart; ADR-014 write-behind rules
        apply)."""
        if key is None:
            # `$cluster/fwd/<origin>/<epoch>/<msgid>/...`: the identity
            # the receiver dedups on — one journal row per message
            levels = topic.split("/", 5)
            ident = ":".join(levels[2:5]) if len(levels) > 5 else topic
            key = f"{self.peer}|{ident}"
        if key in self._parked_keys:
            return      # already parked (refused enqueue + failed ack)
        while len(self.parked) >= PARKED_MAX:
            _t, _p, old_key = self.parked.popleft()
            self._parked_keys.discard(old_key)
            self.parked_dropped += 1
            self._journal_delete(old_key)
        self.parked.append((topic, payload, key))
        self._parked_keys.add(key)
        self.forwards_parked += 1
        store = self._fwd_store()
        if store is not None:
            store.put(FWD_BUCKET, key,
                      json.dumps({"t": topic, "p": payload.hex()}))

    def drain_parked(self) -> int:
        """Re-send every parked forward on a fresh link (called from
        ClusterManager.on_link_up). Failures re-park with the same
        journal key; the receiver's per-(origin, epoch) msgid dedup
        drops any copy that did land before the partition."""
        items, self.parked = self.parked, deque()
        self._parked_keys.clear()
        n = 0
        for topic, payload, key in items:
            if self.forward(topic, payload, qos=1, park=True,
                            _parked_key=key):
                n += 1
        self.parked_resent += n
        return n

    def _fwd_store(self):
        hook = getattr(self.manager.broker, "_storage_hook", None)
        return None if hook is None else hook.store

    def _journal_delete(self, key: str) -> None:
        store = self._fwd_store()
        if store is not None:
            store.delete(FWD_BUCKET, key)

    def send_session(self, topic: str, payload: bytes,
                     on_ack=None) -> bool:
        """Enqueue one ADR-016 session-federation message. Budget-exempt
        like route control (dropping a session update would silently
        desync the ledger), but sent at QoS1 when ``on_ack`` is given:
        the peer broker's PUBACK is the replication acknowledgement the
        sync barrier couples publisher acks to. ``on_ack(ok)`` runs on
        the loop once the ack lands (or the link dies — ok=False, so a
        barrier never waits on a dead connection's ack)."""
        client = self.client
        if not self.connected or client is None:
            return False
        pid = 0
        if on_ack is not None:
            pid = client._alloc_id()
            fut = client._await_ack(PT.PUBACK, pid)

            def _done(f, cb=on_ack):
                cb(not f.cancelled() and f.exception() is None)

            fut.add_done_callback(_done)
        wire = self._encode_publish(topic, payload,
                                    1 if on_ack is not None else 0,
                                    False, pid)
        try:
            self.outbound.put_nowait(wire, len(wire))
        except asyncio.QueueFull:
            if pid:
                f = client._acks.pop((PT.PUBACK, pid), None)
                if f is not None and not f.done():
                    f.cancel()
            return False
        self.session_sent += 1
        self.items_sent += 1    # ADR 020: audited by the heartbeat
        return True

    def send_control(self, topic: str, payload: bytes,
                     retain: bool = False,
                     counted: bool = True) -> bool:
        """Enqueue a route/control message. Budget-exempt (dropping
        route deltas to save bytes would desync the mesh — the same
        reasoning that exempts acks from the broker's client budgets),
        but still accounted on the ledgers. ``counted=False`` keeps a
        message out of the ADR-020 heartbeat audit — only for the audit
        plane's OWN messages (blip notices), which the receiver equally
        excludes from its mirror count."""
        if not self.connected or self.client is None:
            return False
        wire = self._encode_publish(topic, payload, 0, retain)
        try:
            self.outbound.put_nowait(wire, len(wire))
        except asyncio.QueueFull:
            return False
        self.control_sent += 1
        if counted:
            self.items_sent += 1    # ADR 020: audited by the heartbeat
        return True
