"""Cluster federation layer (ADR 013 + 016): bridge links, aggregated
route propagation, cross-node publish forwarding, and federated
sessions (epoch-safe takeover, replicated inflight, cluster-wide
``$share``) over N broker processes."""

from .bridge import BRIDGE_ID_PREFIX, BridgeLink
from .manager import ClusterManager, DedupWindow
from .membership import (Membership, PeerSpec, PeerSpecError,
                         parse_peers, valid_node_id)
from .routes import (IncrementalCover, RouteTable, RouteWireError,
                     ShareLedger, decode_delta, decode_snapshot,
                     encode_delta, encode_snapshot, filter_subsumes,
                     minimal_cover)
from .sessions import SessionEntry, SessionFederation

__all__ = [
    "BRIDGE_ID_PREFIX", "BridgeLink", "ClusterManager", "DedupWindow",
    "Membership", "PeerSpec", "PeerSpecError", "parse_peers",
    "valid_node_id", "IncrementalCover", "RouteTable", "RouteWireError",
    "ShareLedger", "decode_delta", "decode_snapshot", "encode_delta",
    "encode_snapshot", "filter_subsumes", "minimal_cover",
    "SessionEntry", "SessionFederation",
]
