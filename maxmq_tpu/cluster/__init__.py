"""Cluster federation layer (ADR 013): bridge links, aggregated route
propagation, and cross-node publish forwarding over N broker
processes."""

from .bridge import BRIDGE_ID_PREFIX, BridgeLink
from .manager import ClusterManager, DedupWindow
from .membership import (Membership, PeerSpec, PeerSpecError,
                         parse_peers, valid_node_id)
from .routes import (RouteTable, RouteWireError, decode_delta,
                     decode_snapshot, encode_delta, encode_snapshot,
                     filter_subsumes, minimal_cover)

__all__ = [
    "BRIDGE_ID_PREFIX", "BridgeLink", "ClusterManager", "DedupWindow",
    "Membership", "PeerSpec", "PeerSpecError", "parse_peers",
    "valid_node_id", "RouteTable", "RouteWireError", "decode_delta",
    "decode_snapshot", "encode_delta", "encode_snapshot",
    "filter_subsumes", "minimal_cover",
]
