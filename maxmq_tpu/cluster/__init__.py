"""Cluster federation layer (ADR 013 + 016 + 017): bridge links,
aggregated route propagation, cross-node publish forwarding, federated
sessions (epoch-safe takeover, replicated inflight, cluster-wide
``$share``), and the cluster observability plane (cross-node trace
propagation, telemetry gossip, clock-skew estimation) over N broker
processes."""

from .bridge import BRIDGE_ID_PREFIX, BridgeLink
from .manager import ClusterManager, DedupWindow
from .membership import (Membership, PeerSpec, PeerSpecError,
                         parse_peers, valid_node_id)
from .routes import (IncrementalCover, RouteTable, RouteWireError,
                     ShareLedger, decode_delta, decode_snapshot,
                     decode_snapshot_preds, encode_delta,
                     encode_snapshot, filter_subsumes, minimal_cover)
from .sessions import SessionEntry, SessionFederation
from .telemetry import WIRE_CAPS, ClusterTelemetry

__all__ = [
    "BRIDGE_ID_PREFIX", "BridgeLink", "ClusterManager", "DedupWindow",
    "Membership", "PeerSpec", "PeerSpecError", "parse_peers",
    "valid_node_id", "IncrementalCover", "RouteTable", "RouteWireError",
    "ShareLedger", "decode_delta", "decode_snapshot",
    "decode_snapshot_preds", "encode_delta", "encode_snapshot",
    "filter_subsumes", "minimal_cover",
    "SessionEntry", "SessionFederation", "ClusterTelemetry",
    "WIRE_CAPS",
]
