"""Federated sessions: epoch-safe cross-node session takeover,
cluster-wide ``$share``, and replicated session/inflight state (ADR 016).

ADR 013 federates *publishes* but pins all session state — the
subscriptions, the inflight window, the ``$share`` memberships — to the
node the client happened to connect to. Behind a plain TCP load
balancer that breaks the moment a client reconnects elsewhere or a
node dies. This module closes that gap on top of the existing bridge
links:

* **Replication** — every locally-owned session's metadata
  (subscriptions, session-expiry, ``$share`` memberships, an
  inflight-window digest) and its QoS1/2 inflight records stream to
  bridge peers over the reserved ``$cluster/sess/*`` control
  namespace, relayed transitively (hop-capped, per-origin-epoch
  deduped, exactly like the ADR-013 forward rails) so a line topology
  converges end to end. Received state is journaled through the
  ADR-014 write-behind store (``cluster_sessions`` /
  ``cluster_inflight`` buckets), so a replica survives its holder's
  crash.
* **Epoch-fenced takeover** — a CONNECT at any node claims the session
  with a fencing token ``(session_epoch, boot_epoch, node_id)``,
  compared lexicographically; the highest token wins. ``session_epoch``
  increments on every claim (strictly increasing across takeovers),
  ``boot_epoch`` is the ADR-014 persisted monotonic boot counter (a
  restarted claimant can never be fenced by its own past), and the
  node id breaks exact ties deterministically on every node. The
  losing node disconnects its live client with v5 SessionTakenOver,
  ships its state to the winner (the *pull* leg), and drops its local
  replica. The winner installs subscriptions + parked inflight before
  CONNACK, so the client sees session-present=1 and the parked QoS1/2
  window survives the move.
* **Durability coupling** — with ``cluster_session_sync = always`` the
  publisher's QoS ack rides a *replication barrier* next to the
  ADR-014 journal barrier: the PUBACK releases only once every direct
  peer has acknowledged the inflight-record replication covering the
  publish (bounded by ``cluster_session_sync_timeout_ms``). That is
  what makes "SIGKILL the node, reconnect to a peer, zero PUBACKed
  loss" a property instead of a hope. ``batched`` replicates
  asynchronously (a crash can lose the in-flight window — documented
  in the ADR), ``off`` replicates metadata only.
* **Cluster-wide ``$share``** — memberships feed the
  :class:`~.routes.ShareLedger` in the route table; for every publish
  the lowest node id with live members owns the (group, filter) pick,
  so a group spanning nodes receives each matching publish exactly
  once cluster-wide instead of once per node. The in-process delivery
  pool (broker/workers.py) routes its worker gossip through the same
  ledger class, so pool and cluster ownership compose.

Degradation is first-class (the ADR 011/012/014 shape): a replication
send/apply can be failed or hung via the ``cluster.session_sync``
fault site (keyed per peer), the takeover handoff via
``cluster.takeover`` (keyed per prior owner). A partitioned or lagging
peer (past ``SYNC_LAG_WINDOW`` unacked messages) degrades the
replication barrier to local-only durability, a dead prior owner
degrades the takeover to the local replica (or a fresh session) after
a bounded wait — CONNECT never wedges, and every degrade is counted in
``maxmq_cluster_session_*`` and ``$SYS/broker/cluster/sessions/*``.
"""

from __future__ import annotations

import asyncio
import json
import time

from .. import faults
from ..hooks.base import Hook
from ..hooks.storage import MessageRecord, SubscriptionRecord
from ..matching.topics import parse_share, valid_filter, valid_topic_name
from ..protocol import codes
from ..protocol.codec import FixedHeader, PacketType as PT
from ..protocol.packets import Packet, ProtocolError, Subscription
from .bridge import BRIDGE_ID_PREFIX

SESS_WIRE_VERSION = 1
SYNC_POLICIES = ("always", "batched", "off")

# dead-owner lifecycle (ADR 018): sweep cadence for the replica-side
# expiry/will timers, and the base grace between "owner link down" and
# the first judge acting. Judges stagger by rank (lowest live node id
# acts first; the willfire/purge broadcast clears the others before
# their slot), so grace also spaces the ranks.
REPLICA_SWEEP_S = 0.25
WILL_FIRE_GRACE_S = 1.0

# unacked replication messages per peer before it is considered
# LAGGING and excluded from new replication barriers (degraded,
# counted) — replication lag must slow the dashboard, not the broker
SYNC_LAG_WINDOW = 512

# inflight replication ops per wire message (bounds one message's size;
# a resync of a deep parked window ships several)
OPS_PER_MESSAGE = 200

# delay before the per-link resync that heals a refused replication
# send on a live link — long enough for the refusing outbound queue to
# drain, short next to any takeover/barrier timeout
RESYNC_DELAY_S = 0.05

# journal buckets for replicated (remote-owned) state
SESS_BUCKET = "cluster_sessions"
INFLIGHT_BUCKET = "cluster_inflight"

# purge tombstones remembered (cid -> last session_epoch) so a session
# RE-CREATED after its purge claims above the old epoch even if a peer
# missed the purge broadcast — without this the stale replica's higher
# token fences the new incarnation forever
TOMBSTONES_MAX = 4096


class SessionEntry:
    """One session as the cluster ledger sees it: who owns it, under
    which fencing token, and the replicated state a takeover installs.
    ``inflight`` (pid -> MessageRecord json) is populated only for
    remote-owned entries — a locally-owned session's inflight lives in
    its :class:`~..broker.client.Client`."""

    __slots__ = ("cid", "owner", "session_epoch", "boot_epoch", "expiry",
                 "expiry_set", "protocol_version", "connected", "subs",
                 "shares", "digest", "will", "inflight", "pubrec",
                 "applied_seq", "infl_seq", "disconnected_seen")

    def __init__(self, cid: str, owner: str, session_epoch: int = 1,
                 boot_epoch: int = 0, expiry: int = 0,
                 expiry_set: bool = False, protocol_version: int = 4,
                 connected: bool = False, subs=None, shares=None,
                 digest=(0, 0), will=None) -> None:
        self.cid = cid
        self.owner = owner
        self.session_epoch = session_epoch
        self.boot_epoch = boot_epoch
        self.expiry = expiry
        self.expiry_set = expiry_set
        self.protocol_version = protocol_version
        self.connected = connected
        # [[filter, qos, no_local, retain_as_published, retain_handling,
        #   identifier], ...]
        self.subs: list = list(subs or [])
        self.shares: list = list(shares or [])   # [[group, filter], ...]
        self.digest = tuple(digest)              # (count, xor of pids)
        # ADR 018 will transfer: [topic, payload_hex, qos, retain,
        # delay_s] while the owner's client is connected with a will —
        # or, once disconnected, while the will sits in the owner's
        # _will_delays countdown with delay_s the REMAINING delay
        # (ADR 019 satellite) plus a 6th element: the absolute
        # wall-clock DEADLINE (ADR 020 satellite), so a judge that
        # applied the entry cold (restart, late resync — no local
        # disconnect observation) still fires on the owner's schedule
        # instead of re-charging the full delay — else None. A replica
        # can fire it if the owner node dies.
        self.will = list(will) if will else None
        self.inflight: dict[int, str] = {}
        self.pubrec: list[int] = []
        # wire seqs of the last applied update / inflight chunk
        # (transient, not serialized): fence same-token messages a
        # redundant relay path delivered out of order
        self.applied_seq = 0
        self.infl_seq = 0
        # local monotonic time we learned the session is disconnected
        # (transient): seeds the replica-side expiry countdown (ADR 018)
        self.disconnected_seen = 0.0

    @property
    def token(self) -> tuple:
        return (self.session_epoch, self.boot_epoch, self.owner)

    def share_keys(self) -> set[tuple[str, str]]:
        return {(g, f) for g, f in self.shares}

    def meta_json(self) -> str:
        return json.dumps({
            "v": SESS_WIRE_VERSION, "cid": self.cid, "owner": self.owner,
            "se": self.session_epoch, "be": self.boot_epoch,
            "exp": self.expiry, "exps": int(self.expiry_set),
            "pv": self.protocol_version, "conn": int(self.connected),
            "subs": self.subs, "shares": self.shares,
            "dig": list(self.digest), "will": self.will})

    @classmethod
    def from_meta_json(cls, raw: str) -> "SessionEntry":
        d = json.loads(raw)
        return cls(str(d["cid"]), str(d["owner"]), int(d["se"]),
                   int(d.get("be", 0)), int(d.get("exp", 0)),
                   bool(d.get("exps", 0)), int(d.get("pv", 4)),
                   bool(d.get("conn", 0)), d.get("subs") or [],
                   d.get("shares") or [], d.get("dig") or (0, 0),
                   d.get("will"))


def _entry_update_dict(entry: SessionEntry) -> dict:
    return {"cid": entry.cid, "se": entry.session_epoch,
            "be": entry.boot_epoch, "exp": entry.expiry,
            "exps": int(entry.expiry_set), "pv": entry.protocol_version,
            "conn": int(entry.connected), "subs": entry.subs,
            "shares": entry.shares, "dig": list(entry.digest),
            "will": entry.will}


class SessionFederation(Hook):
    """Session replication + takeover protocol for one broker, attached
    to its :class:`~.manager.ClusterManager` and registered as a broker
    hook (the QoS/subscription/disconnect events feed replication)."""

    id = "cluster-sessions"

    def __init__(self, manager, *, sync: str = "batched",
                 sync_timeout_ms: int = 750,
                 takeover_timeout_ms: int = 750,
                 replica_expiry_s: float = 3600.0) -> None:
        if sync not in SYNC_POLICIES:
            raise ValueError(f"unknown cluster_session_sync {sync!r} "
                             f"(want one of {SYNC_POLICIES})")
        self.manager = manager
        self.broker = manager.broker
        self.node_id = manager.node_id
        self.sync = sync
        self.sync_timeout = max(sync_timeout_ms, 1) / 1000.0
        self.takeover_timeout = max(takeover_timeout_ms, 1) / 1000.0
        # ADR 018 dead-owner lifecycle: fallback expiry for replicas
        # whose session carries no expiry metadata (0 = never), and the
        # death-detection grace before the elected judge acts
        self.replica_expiry = max(float(replica_expiry_s), 0.0)
        self.will_grace = WILL_FIRE_GRACE_S

        self.ledger: dict[str, SessionEntry] = {}
        self._seen: dict[str, object] = {}      # origin -> DedupWindow
        self._next_seq = 0                      # per-origin message seq
        self._pending_ops: list = []            # inflight replication ops
        self._dirty_cids: set[str] = set()
        self._flush_scheduled = False
        self._peer_acked: dict[str, int] = {}
        # per-peer highest ACK-REQUESTED seq: barriers wait on this, not
        # on _next_seq — claim/purge/state broadcasts are never acked,
        # and a per-link resync's seqs exist only on that link
        self._peer_ack_target: dict[str, int] = {}
        self._peer_send_failed: set[str] = set()
        self._resync_pending: set[str] = set()
        self._sync_barriers: list = []          # [targets, required, fut]
        self._pulls: dict[str, asyncio.Future] = {}
        self._suppress_purge: set[str] = set()
        # cid -> session_epoch at purge (journaled in SESS_BUCKET as a
        # {"tomb": se} row, superseded by any later live entry's put)
        self._tombstones: dict[str, int] = {}
        # per-owner aggregated live $share counts feeding routes.shares
        self._share_counts: dict[str, dict[tuple[str, str], int]] = {}
        self._started = False
        self._started_mono = 0.0
        # wall clock, swappable so scripted-clock tests can drive the
        # replicated will-DEADLINE comparison (ADR 020 satellite)
        self._wall = time.time
        self._expiry_task: asyncio.Task | None = None

        # counters (read tear-free by the metrics scrape thread)
        self.takeovers = 0              # remote sessions taken locally
        self.takeovers_degraded = 0     # takeover fell to fresh/replica
        self.takeovers_stale = 0        # pull timed out; replica used
        self.sessions_lost = 0          # local sessions claimed away
        self.state_transfers = 0        # full state handoffs received
        self.claims_rejected = 0        # stale claims fenced off
        self.purges = 0                 # purge broadcasts applied
        self.relays = 0                 # messages relayed onward
        self.sync_flushes = 0
        self.sync_ops = 0               # inflight ops replicated out
        self.sync_acks = 0
        self.sync_degraded = 0          # barriers released undurable
        self.sync_timeouts = 0
        self.sync_faults = 0            # injected session_sync trips
        self.sync_send_failures = 0     # link refused a sess message
        self.sync_resyncs = 0           # live-link gap-healing resyncs
        self.sync_barrier_waits = 0
        self.digest_mismatches = 0      # installed inflight != digest
        self.restore_errors = 0         # journal rows that failed parse
        self.inbound_rejected = 0
        # ADR 018 dead-owner lifecycle
        self.replica_expiries = 0       # orphaned replicas purged by the
                                        # replica-side expiry timer
        self.wills_fired = 0            # transferred wills fired here
                                        # for a dead owner's session
        self.wills_cleared = 0          # replica wills cleared by a
                                        # peer's willfire broadcast
        self.trace_ops_applied = 0      # ADR 017: replicated inflight
                                        # ops that carried trace identity

    # ------------------------------------------------------------------
    # Lifecycle (driven by ClusterManager.start/close)
    # ------------------------------------------------------------------

    def start(self) -> None:
        """Rebuild the ledger from the journal (runs after the broker's
        own restore + boot-epoch bump). Self-owned rows keep only their
        epoch — the broker's restore is authoritative for local state —
        and are marked disconnected until the client returns."""
        self._started = True
        self._started_mono = time.monotonic()
        loop = getattr(self.broker, "loop", None)
        if loop is not None:
            # ADR 018: the dead-owner sweep — replica-side expiry
            # timers + transferred-will firing
            self._expiry_task = loop.create_task(
                self._sweep_loop(), name="cluster-sess-sweep")
        hook = getattr(self.broker, "_storage_hook", None)
        if hook is None:
            return
        for cid, raw in hook.store.all(SESS_BUCKET).items():
            try:
                d = json.loads(raw)
                if "tomb" in d:
                    self._note_tombstone(cid, int(d["tomb"]),
                                         journal=False)
                    continue
                entry = SessionEntry.from_meta_json(raw)
            except Exception:
                self.restore_errors += 1
                continue
            entry.connected = False
            self._apply_entry(entry, journal=False)
        for key, raw in hook.store.all(INFLIGHT_BUCKET).items():
            cid, _, pid = key.rpartition("|")
            entry = self.ledger.get(cid)
            if entry is None or entry.owner == self.node_id:
                continue
            try:
                if pid.startswith("r"):
                    # ADR 018: streamed PUBREC-pending (QoS2 release-
                    # leg dedup) rows ride the same bucket as r<pid>
                    p = int(pid[1:])
                    if p not in entry.pubrec:
                        entry.pubrec.append(p)
                else:
                    entry.inflight[int(pid)] = raw
            except ValueError:
                self.restore_errors += 1

    def close(self) -> None:
        self._started = False
        if self._expiry_task is not None:
            self._expiry_task.cancel()
            self._expiry_task = None
        for b in self._sync_barriers:
            if not b[2].done():
                b[2].set_result(None)
        self._sync_barriers.clear()
        for fut in self._pulls.values():
            if not fut.done():
                fut.cancel()
        self._pulls.clear()

    def stop(self) -> None:            # Hook contract (broker close)
        self.close()

    # ------------------------------------------------------------------
    # Aggregates ($SYS / metrics)
    # ------------------------------------------------------------------

    @property
    def ledger_size(self) -> int:
        return len(self.ledger)

    @property
    def local_sessions(self) -> int:
        return sum(1 for e in self.ledger.values()
                   if e.owner == self.node_id)

    @property
    def share_groups(self) -> int:
        return self.manager.routes.shares.group_count

    @property
    def ack_coupled(self) -> bool:
        """True when QoS acks must ride the replication barrier
        (``cluster_session_sync = always`` with peers configured)."""
        return self.sync == "always" and bool(self.manager.links)

    # ------------------------------------------------------------------
    # $share ownership (consulted by Broker._fan_out_shared)
    # ------------------------------------------------------------------

    def owns_share(self, group: str, filt: str,
                   token: int | None = None) -> bool:
        """``token`` (a per-publish content hash, identical on every
        node) drives the ADR-018 weighted rotation; None falls back to
        lowest-member-id pinning."""
        return self.manager.routes.shares.owns((group, filt), token)

    # ------------------------------------------------------------------
    # CONNECT-side takeover (called by Broker._attach_client)
    # ------------------------------------------------------------------

    def _tracked(self, client) -> bool:
        return (not getattr(client, "inline", False)
                and not client.id.startswith(BRIDGE_ID_PREFIX))

    async def on_local_connect(self, client, session_present: bool) -> bool:
        """Claim the session cluster-wide and, when a peer owned it,
        run the epoch-fenced takeover BEFORE the caller sends CONNACK.
        Bounded: every remote leg degrades on fault/timeout instead of
        wedging the handshake."""
        if not self._tracked(client) or not self.manager.links:
            return session_present
        cid = client.id
        entry = self.ledger.get(cid)
        clean = client.properties.clean_start
        # a re-created session claims ABOVE its purge tombstone: a peer
        # that missed the purge still holds the old epoch, and a fresh
        # epoch-1 claim would be fenced by that stale replica forever
        new_epoch = (entry.session_epoch + 1) if entry is not None \
            else self._tombstones.get(cid, 0) + 1
        remote = entry is not None and entry.owner != self.node_id
        if remote and not clean:
            session_present = await self._traced_takeover(
                client, entry, new_epoch, session_present)
        else:
            self._send_claim(cid, new_epoch, purge=clean)
        self._become_owner(client, new_epoch)
        return session_present

    async def _traced_takeover(self, client, entry: SessionEntry,
                               new_epoch: int,
                               session_present: bool) -> bool:
        """The remote-takeover leg with its ADR-015 span + the
        fresh-session degrade on an injected fault. When sampling is
        on, the takeover rides a full trace whose id travels on the
        claim (ADR 017): the prior owner's state-ship leg reports its
        span back, so one correlated trace shows claim -> remote ship
        -> install."""
        tracer = getattr(self.broker, "tracer", None)
        t0 = tracer.clock() if tracer is not None else 0
        tr = None
        if tracer is not None and tracer.sample_n:
            tr = tracer.sample(f"$takeover/{client.id}", 0, client.id,
                               start_ns=t0)
        try:
            installed = await self._take_over(client, entry, new_epoch,
                                              trace=tr)
            session_present = session_present or installed
            self.takeovers += 1
        except faults.InjectedFault:
            # fresh session + counted loss, never a wedged CONNECT
            self.takeovers_degraded += 1
            if tracer is not None:
                tracer.note_error("takeover", "fault")
        except Exception:
            # same contract for anything a peer's replica can throw at
            # the handoff (malformed state, a codec bug): the client
            # gets a fresh session, the CONNECT never fails over it
            self.takeovers_degraded += 1
            if tracer is not None:
                tracer.note_error("takeover", "error")
        if tracer is not None:
            now = tracer.clock()
            tracer.observe("takeover", (now - t0) / 1e9)
            if tr is not None:
                tr.span("takeover", t0, now)
                tracer.finish(tr, now)
        return session_present

    async def _take_over(self, client, entry: SessionEntry,
                         new_epoch: int, trace=None) -> bool:
        """One remote takeover: claim (with pull), wait bounded for the
        prior owner's state handoff, install the freshest copy we hold.
        ``cluster.takeover`` fault site keyed by the prior owner."""
        cid, owner = entry.cid, entry.owner
        hit = faults.fire_detail(faults.CLUSTER_TAKEOVER, key=owner)
        if hit is not None:
            if hit[0] == "hang":
                await asyncio.sleep(hit[1])
            else:   # drop: the handoff path is unusable this time —
                # still claim ownership, then degrade through the same
                # except-branch as raise mode so the takeovers /
                # takeovers_degraded counters agree across fault modes
                self._send_claim(cid, new_epoch, purge=False)
                raise faults.InjectedFault(faults.CLUSTER_TAKEOVER)
        fut = self.broker.loop.create_future()
        self._pulls[cid] = fut
        try:
            self._send_claim(cid, new_epoch, pull=True, trace=trace)
            if any(lk.connected for lk in self.manager.links.values()):
                try:
                    # ADR 022: the pull round-trips the prior owner's
                    # link — stretch by the mesh's max measured RTT so
                    # a WAN roam doesn't absorb stale replica state
                    state = await asyncio.wait_for(
                        asyncio.shield(fut),
                        self.manager.link_deadline(
                            None, self.takeover_timeout))
                    self._absorb_state_into(entry, state)
                except (asyncio.TimeoutError, TimeoutError):
                    # dead/partitioned prior owner: the replicated
                    # ledger copy is the best state that exists
                    self.takeovers_stale += 1
        finally:
            # a concurrent takeover for the same cid (double-CONNECT on
            # this node) may have replaced the waiter — pop only our own
            if self._pulls.get(cid) is fut:
                del self._pulls[cid]
            if not fut.done():
                fut.cancel()
        self._install(client, entry)
        # ADR 022: WE are the winner — parked forwards pinned to the
        # dead prior owner's link for this session's topics re-enter
        # the local fan-out the install just wired up
        link = self.manager.links.get(owner)
        if link is not None and not link.connected and entry.subs:
            self.manager.rehome_for_takeover(
                owner, self.node_id,
                [str(rec[0]) for rec in entry.subs if rec])
        return bool(entry.subs) or bool(entry.inflight)

    def _absorb_state_into(self, entry: SessionEntry, d: dict) -> None:
        """Fold a state handoff into the entry about to be installed
        (fresher than the asynchronously-replicated ledger copy)."""
        entry.subs = d.get("subs") or entry.subs
        entry.digest = tuple(d.get("dig") or entry.digest)
        infl = d.get("infl") or {}
        for pid, raw in infl.items():
            entry.inflight[int(pid)] = raw
        entry.pubrec = [int(p) for p in d.get("pubrec") or []]
        self.state_transfers += 1

    def _install(self, client, entry: SessionEntry) -> None:
        """Materialize the replicated session on this node: trie
        subscriptions (advertised to peers), parked inflight into the
        client's window, QoS2 dedup set — and persist it all through
        OUR storage hook, because this node now owes it durability."""
        broker = self.broker
        hook = getattr(broker, "_storage_hook", None)
        cid = client.id
        for rec in entry.subs:
            try:
                filt = str(rec[0])
                if not valid_filter(filt):
                    continue  # a peer must not smuggle junk in the trie
                sub = Subscription(
                    filter=filt, qos=int(rec[1]), no_local=bool(rec[2]),
                    retain_as_published=bool(rec[3]),
                    retain_handling=int(rec[4]), identifier=int(rec[5]))
            except (IndexError, ValueError, TypeError):
                self.restore_errors += 1
                continue    # a malformed replicated row degrades to a
                            # skipped subscription, never a failed CONNECT
            if broker.topics.subscribe(cid, sub):
                broker.info.subscriptions += 1
                self.manager.note_subscribe(filt)
            client.subscriptions[filt] = sub
            if hook is not None:
                hook.store.put(
                    "subscriptions", f"{cid}|{filt}",
                    SubscriptionRecord(
                        client_id=cid, filter=filt, qos=sub.qos,
                        no_local=sub.no_local,
                        retain_as_published=sub.retain_as_published,
                        retain_handling=sub.retain_handling,
                        identifier=sub.identifier).to_json())
        self._install_inflight(client, entry, hook)
        client.pubrec_inbound.update(entry.pubrec)
        if entry.digest and tuple(entry.digest) != client.inflight.digest():
            self.digest_mismatches += 1
        # the replicated copy's journal rows moved into the live
        # buckets above; drop the remote-owned shadow
        if hook is not None:
            hook.store.delete_prefix(INFLIGHT_BUCKET, cid + "|")

    def _install_inflight(self, client, entry: SessionEntry,
                          hook) -> None:
        """Materialize the replicated window into the live client:
        parked messages enter the inflight dict (re-journaled under the
        live bucket), quota-parked (held) records re-park in held_pids
        (ADR 018 — resend skips them, _release_held drains them under
        the receive window)."""
        broker = self.broker
        cid = client.id
        for pid in sorted(entry.inflight):
            raw = entry.inflight[pid]
            try:
                rec = MessageRecord.from_json(raw)
                packet = rec.to_packet()
            except Exception:
                self.restore_errors += 1
                continue
            # resend encodes with the packet's own version: it must
            # match the session's protocol or a v5 client reads a v4
            # wire (no properties block) as malformed
            packet.protocol_version = client.properties.protocol_version
            if client.inflight.set(packet):
                broker.info.inflight += 1
            if rec.held:
                client.held_pids.append(pid)
            if hook is not None:
                hook.store.put("inflight", f"{cid}|{pid}", raw)
                client.inflight.note_stored(pid)

    def _become_owner(self, client, epoch: int) -> None:
        entry = self._entry_from_client(client, epoch, connected=True)
        clean = client.properties.clean_start
        if clean:
            # a clean start discards the replicated shadow window too:
            # peers purge via the claim's purge flag, this is OUR copy
            # (or a later double-failover resurrects pre-clean parked
            # messages the client asked to forget)
            hook = getattr(self.broker, "_storage_hook", None)
            if hook is not None:
                hook.store.delete_prefix(INFLIGHT_BUCKET,
                                         client.id + "|")
        self._apply_entry(entry, keep_inflight=not clean)
        self._mark_dirty(client.id)

    @staticmethod
    def _subs_shares(client) -> tuple[list, list]:
        """A live client's replicated subscription rows + ``$share``
        keys — ONE shape for the replication and state-pull legs."""
        subs, shares = [], []
        for filt, sub in client.subscriptions.items():
            subs.append([filt, sub.qos, int(sub.no_local),
                         int(sub.retain_as_published),
                         sub.retain_handling, sub.identifier or 0])
            group, _inner = parse_share(filt)
            if group:
                shares.append([group, filt])
        return subs, shares

    def _entry_from_client(self, client, epoch: int,
                           connected: bool) -> SessionEntry:
        subs, shares = self._subs_shares(client)
        p = client.properties
        will = None
        if connected and p.will is not None and p.will.topic:
            # ADR 018 will transfer: the will rides the replicated
            # metadata while the client is live, so a replica can fire
            # it if this whole node dies. A disconnect (normal close
            # fired/discarded it locally, abnormal close fired it
            # locally) replicates will=None — peers stand down.
            will = [p.will.topic, p.will.payload.hex(),
                    int(p.will.qos), int(p.will.retain),
                    float(p.will_delay or 0)]
        elif not connected:
            # ADR 019 (satellite): a will-delay-parked will is STILL
            # pending on this owner (_queue_will parked it before the
            # disconnect hook fired), so it must keep riding the
            # replicated entry with its REMAINING delay — an owner
            # dying mid-countdown used to lose the will cluster-wide.
            # The judge resumes the countdown from disconnected_seen;
            # the owner's own local fire replicates the stand-down
            # (on_will_sent below).
            parked = self.broker._will_delays.get(client.id)
            if parked is not None:
                due, wp = parked
                # 6th element (ADR 020 satellite): the ABSOLUTE
                # wall-clock deadline, so a cold-applied replica fires
                # on schedule instead of re-charging the duration
                will = [wp.topic, wp.payload.hex(), int(wp.fixed.qos),
                        int(wp.fixed.retain),
                        max(due - self._wall(), 0.0), float(due)]
        return SessionEntry(
            client.id, self.node_id, epoch, self.broker.boot_epoch,
            p.session_expiry, p.session_expiry_set, p.protocol_version,
            connected, subs, shares, client.inflight.digest(), will)

    # ------------------------------------------------------------------
    # Hook events (replication feed; the broker calls these)
    # ------------------------------------------------------------------

    def on_subscribed(self, client, packet, reason_codes, counts) -> None:
        self._note_client(client)

    def on_unsubscribed(self, client, packet) -> None:
        self._note_client(client)

    def on_disconnect(self, client, err, expire: bool) -> None:
        if not expire:      # expiry rides the purge path instead
            self._note_client(client, connected=False)

    def on_will_sent(self, client, packet) -> None:
        """ADR 019 (satellite): the owner's own will fired locally —
        including a delayed will whose _will_delays countdown just
        elapsed, where the client object is already gone. Clear the
        replicated copy and broadcast the stand-down, or a judge
        sweeping this node's later death would fire the will a second
        time from the stale entry."""
        cid = getattr(packet, "origin", "")
        if not cid:
            return
        entry = self.ledger.get(cid)
        if entry is None or entry.owner != self.node_id \
                or entry.will is None:
            return
        entry.will = None
        hook = getattr(self.broker, "_storage_hook", None)
        if hook is not None:
            hook.store.put(SESS_BUCKET, entry.cid, entry.meta_json())
        if self.manager.links:
            self._mark_dirty(cid)

    def on_qos_publish(self, client, packet, sent: float,
                       resends: int) -> None:
        if resends or self.sync == "off" or not self._tracked(client) \
                or not self.manager.links:
            return
        rec = MessageRecord.from_packet(packet, client.id)
        if packet.packet_id in getattr(client, "held_pids", ()):
            # ADR 018 (satellite): quota-parked held-but-unsent state
            # replicates, so a takeover re-parks instead of resending
            # past the client's receive maximum (or dropping it)
            rec.held = True
        op = [client.id, packet.packet_id, "set", rec.to_json()]
        # ADR 017: a sampled publish's replication op carries its trace
        # identity (stamped on the delivery copy by _build_outbound) so
        # the REPLICA side can correlate; zero cost untraced
        ref = packet.__dict__.get("_trace_ref")
        if ref is not None:
            op.append(list(ref))
        self._note_op(op)

    def on_qos_complete(self, client, packet) -> None:
        self._note_del(client, packet)

    def on_qos_dropped(self, client, packet) -> None:
        self._note_del(client, packet)

    def _note_del(self, client, packet) -> None:
        if self.sync == "off" or not self._tracked(client) \
                or not self.manager.links:
            return
        self._note_op([client.id, packet.packet_id, "del"])

    def note_pubrec(self, client, pid: int, add: bool) -> None:
        """ADR 018 (satellite): stream broker-side inbound PUBREC-
        pending changes (the QoS2 release-leg dedup set) as inflight
        ops instead of the pull-only transfer — a dead-owner failover
        keeps the receiver-side dedup set, so a publisher retrying
        PUBLISH/PUBREL against the new owner is deduped, not
        redelivered."""
        if self.sync == "off" or not self._tracked(client) \
                or not self.manager.links:
            return
        self._note_op([client.id, pid, "rec" if add else "recdel"])

    def _note_client(self, client, connected: bool | None = None) -> None:
        if not self._tracked(client) or not self.manager.links:
            return
        entry = self.ledger.get(client.id)
        if entry is None or entry.owner != self.node_id:
            return
        live = not client.closed if connected is None else connected
        self._apply_entry(self._entry_from_client(
            client, entry.session_epoch, connected=live))
        self._mark_dirty(client.id)

    def note_purge(self, cid: str) -> None:
        """Called by Broker._purge_session: the session expired or was
        cleanly discarded — remove the ledger entry and tell the
        cluster (suppressed while a takeover-away is mid-transfer)."""
        if cid in self._suppress_purge:
            return
        entry = self.ledger.get(cid)
        if entry is None or entry.owner != self.node_id:
            return
        self._remove_entry(cid)
        self._note_tombstone(cid, entry.session_epoch)
        if self.manager.links:
            self._broadcast("purge", {"cid": cid, "se": entry.session_epoch,
                                      "be": entry.boot_epoch})

    def _note_tombstone(self, cid: str, epoch: int,
                        journal: bool = True) -> None:
        """Remember a purged session's last epoch (bounded, journaled):
        the purge broadcast is fire-and-forget and resyncs replay only
        live sessions, so a re-created session must claim ABOVE the old
        epoch or a peer's missed-purge replica fences it forever."""
        while len(self._tombstones) >= TOMBSTONES_MAX:
            self._tombstones.pop(next(iter(self._tombstones)))
        self._tombstones[cid] = max(self._tombstones.get(cid, 0), epoch)
        if journal:
            hook = getattr(self.broker, "_storage_hook", None)
            if hook is not None:
                hook.store.put(SESS_BUCKET, cid, json.dumps(
                    {"v": SESS_WIRE_VERSION, "tomb": epoch}))

    # ------------------------------------------------------------------
    # Ledger bookkeeping (+ $share counts + journal)
    # ------------------------------------------------------------------

    def _apply_entry(self, entry: SessionEntry, journal: bool = True,
                     keep_inflight: bool = True) -> None:
        """Install/replace one ledger entry (always a FRESH object —
        in-place mutation would corrupt the share-count diff below).
        ``keep_inflight`` carries the old replicated inflight window
        forward (metadata updates don't restate it); purge paths pass
        False."""
        self._tombstones.pop(entry.cid, None)   # a live entry supersedes
        old = self.ledger.get(entry.cid)
        if not entry.connected:
            # seed/carry the replica-expiry countdown (ADR 018): the
            # clock starts when we FIRST see the session disconnected
            # and survives metadata refreshes; any connected update
            # resets it (the returning owner/client wins)
            entry.disconnected_seen = (
                old.disconnected_seen
                if old is not None and not old.connected
                and old.disconnected_seen else time.monotonic())
        if old is not None:
            assert old is not entry, "ledger entries are replaced, not mutated"
            if keep_inflight and not entry.inflight:
                entry.inflight = old.inflight
                if old.owner == entry.owner:
                    # seqs are PER-ORIGIN: carrying the old owner's
                    # fence across a takeover would drop every chunk
                    # from the new owner until its counter caught up
                    entry.infl_seq = old.infl_seq
            self._share_account(old, -1)
        self.ledger[entry.cid] = entry
        self._share_account(entry, +1)
        if journal:
            hook = getattr(self.broker, "_storage_hook", None)
            if hook is not None:
                hook.store.put(SESS_BUCKET, entry.cid, entry.meta_json())

    def _remove_entry(self, cid: str) -> None:
        entry = self.ledger.pop(cid, None)
        if entry is None:
            return
        self._share_account(entry, -1)
        hook = getattr(self.broker, "_storage_hook", None)
        if hook is not None:
            hook.store.delete(SESS_BUCKET, cid)
            hook.store.delete_prefix(INFLIGHT_BUCKET, cid + "|")

    def _share_account(self, entry: SessionEntry, sign: int) -> None:
        if not entry.connected or not entry.shares:
            return
        counts = self._share_counts.setdefault(entry.owner, {})
        shares = self.manager.routes.shares
        for key in entry.share_keys():
            n = counts.get(key, 0) + sign
            if n > 0:
                counts[key] = n
            else:
                counts.pop(key, None)
                n = 0
            shares.set_member(entry.owner, key, n)

    # ------------------------------------------------------------------
    # Outbound wire (broadcast + transitive relay over bridge links)
    # ------------------------------------------------------------------

    def _send_claim(self, cid: str, epoch: int, purge: bool = False,
                    pull: bool = False, trace=None) -> None:
        d = {"cid": cid, "se": epoch, "be": self.broker.boot_epoch,
             "purge": int(purge), "pull": int(pull)}
        if trace is not None:
            # ADR 017: the takeover trace's identity travels with the
            # claim so the prior owner's ship leg can report its span
            # back to this (origin) node
            d["tr"] = [self.node_id, trace.id]
        self._broadcast("claim", d)

    def _envelope(self, d: dict, to: str | None = None) -> dict:
        """One ``$cluster/sess`` wire envelope (bumps the per-origin
        seq — every envelope built is considered sent)."""
        self._next_seq += 1
        msg = {"v": SESS_WIRE_VERSION, "o": self.node_id,
               "e": self.broker.boot_epoch, "q": self._next_seq,
               "h": 1, "d": d}
        if to is not None:
            msg["to"] = to
        return msg

    def _broadcast(self, kind: str, d: dict, to: str | None = None,
                   ack: bool = False) -> int:
        msg = self._envelope(d, to)
        payload = json.dumps(msg).encode()
        topic = f"$cluster/sess/{self.node_id}/{kind}"
        for link in self.manager.links.values():
            self._send_to_link(link, topic, payload,
                               msg["q"] if ack else None)
        return msg["q"]

    def _send_to_link(self, link, topic: str, payload: bytes,
                      ack_seq: int | None) -> None:
        peer = link.peer
        if ack_seq is not None:
            # raise the peer's barrier target even when the message
            # ends up dropped/faulted: a barrier must then time out
            # (degraded, counted), never pass against a stale target
            self._peer_ack_target[peer] = ack_seq
        try:
            hit = faults.fire_detail(faults.CLUSTER_SESSION_SYNC, key=peer)
        except faults.InjectedFault:
            self.sync_faults += 1
            return
        if hit is not None:
            mode, delay = hit
            self.sync_faults += 1
            if mode == "hang" and self.broker.loop is not None:
                self.broker.loop.call_later(
                    delay, self._deliver_to_link, link, topic, payload,
                    ack_seq)
            return      # drop (and hang delivers late, out of band)
        self._deliver_to_link(link, topic, payload, ack_seq)

    def _deliver_to_link(self, link, topic: str, payload: bytes,
                         ack_seq: int | None) -> None:
        peer = link.peer
        on_ack = None
        if ack_seq is not None:
            def on_ack(ok, p=peer, s=ack_seq):
                self._on_sync_ack(p, s, ok)
        if link.send_session(topic, payload, on_ack=on_ack):
            self._peer_send_failed.discard(peer)
        else:
            self.sync_send_failures += 1
            self._peer_send_failed.add(peer)
            # the peer's replica now has a GAP that later acks would
            # silently mask (acks are a high-watermark) — heal it with
            # a debounced full per-link resync once the queue drains
            self._schedule_resync(link)

    def _relay(self, kind: str, msg: dict, exclude: set[str]) -> None:
        if msg["h"] >= self.manager.max_hops:
            return
        out = dict(msg)
        out["h"] = msg["h"] + 1
        payload = json.dumps(out).encode()
        topic = f"$cluster/sess/{self.node_id}/{kind}"
        sent = False
        for peer, link in self.manager.links.items():
            if peer in exclude:
                continue
            self._send_to_link(link, topic, payload, None)
            sent = True
        if sent:
            self.relays += 1

    # ------------------------------------------------------------------
    # Replication batching + the ack-coupled sync barrier
    # ------------------------------------------------------------------

    def _note_op(self, op: list) -> None:
        self._pending_ops.append(op)
        self.sync_ops += 1
        self._schedule_flush()

    def _mark_dirty(self, cid: str) -> None:
        self._dirty_cids.add(cid)
        self._schedule_flush()

    def _schedule_flush(self) -> None:
        if self._flush_scheduled or not self._started:
            return
        loop = getattr(self.broker, "loop", None)
        if loop is None:
            return
        self._flush_scheduled = True
        loop.call_soon(self._flush)

    def _flush(self) -> None:
        """Drain pending session updates + inflight ops onto the wire
        (one debounced pass per loop turn; the ack-coupled barrier
        flushes eagerly so its target seq is known)."""
        # ADR 024 crash point: replication accepted and debounced but
        # not yet on the wire — a node dying here is the widest
        # replica-lag window a single loop turn can leave
        faults.crash_point("replica_flush")
        self._flush_scheduled = False
        if self._dirty_cids:
            for cid in list(self._dirty_cids):
                entry = self.ledger.get(cid)
                if entry is not None and entry.owner == self.node_id:
                    self._broadcast("up", _entry_update_dict(entry),
                                    ack=True)
            self._dirty_cids.clear()
            self.sync_flushes += 1
        digests = self._flush_digests()
        while self._pending_ops:
            chunk = self._pending_ops[:OPS_PER_MESSAGE]
            del self._pending_ops[:OPS_PER_MESSAGE]
            cids = {op[0] for op in chunk}
            self._broadcast(
                "infl",
                {"ops": chunk,  # only THIS chunk's digests ride along
                 "dig": {c: d for c, d in digests.items() if c in cids}},
                ack=True)
            self.sync_flushes += 1
        self._check_barriers()

    def _flush_digests(self) -> dict:
        """Flush-time digests ride WITH the ops so a replica's digest
        tracks the window it actually holds (a digest only refreshed by
        metadata updates would go stale as parked messages accumulate
        and trip the install check spuriously)."""
        digests: dict = {}
        for op in self._pending_ops:
            cid = op[0]
            if cid not in digests:
                cl = self.broker.clients.get(cid)
                if cl is not None:
                    digests[cid] = list(cl.inflight.digest())
        return digests

    def sync_barrier(self, loop) -> asyncio.Future | None:
        """A future resolved once every reachable direct peer has acked
        the replication covering everything enqueued so far, or
        ``None`` when no wait is required (policy, no reachable peers —
        degraded and counted — or everything already acked). Bounded by
        ``sync_timeout``: a peer that stops acking costs latency, not a
        wedged publisher."""
        if self.sync != "always":
            return None
        if self._pending_ops or self._dirty_cids:
            self._flush()
        required = self._barrier_required()
        if not required:
            return None
        # each peer waits on its OWN last ack-requested seq — never on
        # _next_seq, which also counts unacked claim/purge/state
        # broadcasts and other links' resync messages that this peer
        # can never ack (a barrier against those would always time out)
        targets = {p: self._peer_ack_target.get(p, 0) for p in required}
        if all(self._peer_acked.get(p, 0) >= targets[p]
               for p in required):
            return None
        fut = loop.create_future()
        self._sync_barriers.append([targets, required, fut])
        self.sync_barrier_waits += 1
        # ADR 022: replication acks ride the slowest shaped link —
        # the barrier timeout stretches with the mesh's max RTT
        loop.call_later(
            self.manager.link_deadline(None, self.sync_timeout),
            self._barrier_timeout, fut)
        return fut

    def _barrier_required(self) -> set[str]:
        """The peers a fresh sync barrier must wait on: connected, not
        lagging, no refused send outstanding. Excluding ANY configured
        peer is a degrade (down, lagging, or refused a send) even when
        other peers still cover the release — the operator must see it."""
        required = {p for p, lk in self.manager.links.items()
                    if lk.connected and p not in self._peer_send_failed
                    and not self._peer_lagging(p)}
        if len(required) < len(self.manager.links):
            self.sync_degraded += 1
        return required

    def _peer_lagging(self, peer: str) -> bool:
        return (self._peer_ack_target.get(peer, 0)
                - self._peer_acked.get(peer, 0) > SYNC_LAG_WINDOW)

    def _barrier_timeout(self, fut) -> None:
        if fut.done():
            return
        fut.set_result(None)
        self.sync_timeouts += 1
        self.sync_degraded += 1
        self._sync_barriers = [b for b in self._sync_barriers
                               if b[2] is not fut]

    def _on_sync_ack(self, peer: str, seq: int, ok: bool) -> None:
        if ok and seq > self._peer_acked.get(peer, 0):
            self._peer_acked[peer] = seq
            self.sync_acks += 1
        self._check_barriers()

    def _check_barriers(self) -> None:
        done = []
        for b in self._sync_barriers:
            targets, required, fut = b
            if fut.done():
                done.append(b)
                continue
            degraded = False
            satisfied = True
            for p in required:
                link = self.manager.links.get(p)
                if link is None or not link.connected:
                    degraded = True     # partitioned peer: don't wait
                elif self._peer_acked.get(p, 0) < targets[p]:
                    satisfied = False
                    break
            if satisfied:
                if degraded:
                    self.sync_degraded += 1
                fut.set_result(None)
                done.append(b)
        for b in done:
            self._sync_barriers.remove(b)

    # ------------------------------------------------------------------
    # Link lifecycle (called by ClusterManager)
    # ------------------------------------------------------------------

    def on_link_up(self, link) -> None:
        """Full per-link resync: ship every locally-owned session's
        metadata + live inflight snapshot so a (re)joined peer's
        replica converges; the final message's ack fast-forwards the
        peer's acked seq past everything it may have missed."""
        resynced = False
        for entry in self.ledger.values():
            if entry.owner != self.node_id:
                continue
            msg = self._envelope(_entry_update_dict(entry))
            self._send_to_link(link, f"$cluster/sess/{self.node_id}/up",
                               json.dumps(msg).encode(), msg["q"])
            resynced = True
            ops = self._live_inflight_ops(entry.cid)
            cl = self.broker.clients.get(entry.cid)
            dig = {entry.cid: list(cl.inflight.digest())} \
                if cl is not None else {}
            for i in range(0, len(ops), OPS_PER_MESSAGE):
                msg = self._envelope({"ops": ops[i:i + OPS_PER_MESSAGE],
                                      "dig": dig})
                self._send_to_link(
                    link, f"$cluster/sess/{self.node_id}/infl",
                    json.dumps(msg).encode(), msg["q"])
        if not resynced:
            # nothing owned = nothing the peer owes an ack for: clear
            # any stale target left by an ack lost to the link's death
            # (its session may since have been purged/claimed away), or
            # every future barrier would stall the full sync timeout
            self._peer_ack_target[link.peer] = \
                self._peer_acked.get(link.peer, 0)
        self._peer_send_failed.discard(link.peer)

    def _schedule_resync(self, link) -> None:
        """Debounced gap-healer for a live link that refused a
        replication send: without it the peer's replica would stay
        permanently short one op while its high-watermark acks make it
        look caught up — exactly the silent hole ``sync=always``
        promises not to have."""
        peer = link.peer
        if peer in self._resync_pending or not self._started:
            return
        loop = getattr(self.broker, "loop", None)
        if loop is None:
            return
        self._resync_pending.add(peer)
        loop.call_later(RESYNC_DELAY_S, self._run_resync, link)

    def _run_resync(self, link) -> None:
        self._resync_pending.discard(link.peer)
        if self._started and link.connected:
            self.sync_resyncs += 1
            self.on_link_up(link)   # a failing resync reschedules itself

    def on_link_down(self, link) -> None:
        self._check_barriers()      # partitioned peers must not wedge acks

    # ------------------------------------------------------------------
    # Dead-owner lifecycle (ADR 018): replica expiry + will firing
    # ------------------------------------------------------------------

    async def _sweep_loop(self) -> None:
        """Periodic replica-side sweep: for every remote-owned session
        whose owner's link is down, run the expiry countdown and the
        transferred-will timer. A sweep bug degrades to a logged skip,
        never a dead task."""
        try:
            while True:
                await asyncio.sleep(REPLICA_SWEEP_S)
                try:
                    self._sweep(time.monotonic())
                except Exception as exc:
                    log = self.manager.log
                    if log is not None:
                        log.warn("session sweep failed",
                                 error=repr(exc)[:200])
        except asyncio.CancelledError:
            pass

    def _judge_rank(self, dead_owner: str) -> int | None:
        """This node's deterministic stagger slot among the peers that
        can judge ``dead_owner`` dead, or None when we hold no direct
        link to it (a transitive replica trusts the judges). Rank 0
        acts first; higher ranks wait one extra grace each, and the
        rank-0 node's willfire/purge broadcast stands them down — so
        one death yields one will even though election needs no
        topology knowledge. Two judges partitioned from EACH OTHER
        both see rank 0 and both act (documented split-brain floor)."""
        if dead_owner not in self.manager.links:
            return None
        ids = sorted({self.node_id}
                     | {p for p, lk in self.manager.links.items()
                        if p != dead_owner and lk.connected})
        return ids.index(self.node_id)

    def _sweep(self, now: float) -> None:
        for cid in list(self.ledger):
            entry = self.ledger.get(cid)
            if entry is None or entry.owner == self.node_id:
                continue
            link = self.manager.links.get(entry.owner)
            if link is None or link.connected:
                continue        # owner reachable (or not ours to judge)
            rank = self._judge_rank(entry.owner)
            if rank is not None:
                self._sweep_entry(entry, now, rank)

    def _sweep_entry(self, entry: SessionEntry, now: float,
                     rank: int) -> None:
        """One dead-owner replica at this judge's stagger slot: fire a
        due transferred will, then run the expiry countdown."""
        st = self.manager.membership.get(entry.owner)
        last = st.last_seen if st is not None and st.last_seen \
            else self._started_mono
        down_for = now - last
        # ADR 022: the grace stretches with the dead owner's measured
        # link RTT — on a 150ms WAN link the death observation itself
        # lags by round trips, and a loopback-tuned grace would fire
        # wills for owners that are merely far away. A truly dead
        # peer's last RTT estimate is finite, so detection stays
        # bounded (floor + k x RTT), just WAN-honest.
        grace = self.manager.link_deadline(entry.owner, self.will_grace)
        stagger = grace * (1 + rank)
        if entry.will is not None:
            try:
                delay = float(entry.will[4]) \
                    if len(entry.will) > 4 else 0.0
            except (TypeError, ValueError):
                # malformed replicated delay (hostile/buggy peer): act
                # now — the fire path validates the rest and degrades
                # to a counted skip, so one bad entry can never wedge
                # the whole sweep round
                delay = 0.0
            if entry.connected:
                # died with the client attached: the will-delay clock
                # starts at the owner's death
                if down_for >= stagger + delay:
                    self._fire_replica_will(entry)
            elif entry.disconnected_seen:
                # ADR 019 (satellite): the owner died while the will
                # sat in ITS _will_delays countdown — the replicated
                # entry carries the delay REMAINING at disconnect, so
                # the judge resumes that countdown from the disconnect
                # it observed instead of restarting it at owner death
                # (which double-charged the delay and, pre-fix, never
                # fired at all: disconnected entries were skipped).
                # The rank stagger applies at the FIRE instant — every
                # judge's countdown expires at the same moment, so
                # staggering only the death observation would let all
                # ranks fire together before the stand-down lands
                if (down_for >= stagger
                        and now - entry.disconnected_seen
                        >= delay + grace * rank):
                    self._fire_replica_will(entry)
            else:
                # no observed disconnect instant (entry applied cold:
                # judge restarted or joined late). ADR 020 satellite —
                # prefer the replicated wall-clock DEADLINE (6th
                # element) so the fire stays on the owner's original
                # schedule; restarting the countdown at owner death
                # double-charged the delay. 5-element entries from
                # older peers keep the duration fallback.
                wd = None
                if len(entry.will) > 5:
                    try:
                        wd = float(entry.will[5])
                    except (TypeError, ValueError):
                        wd = None
                if wd is not None:
                    if (down_for >= stagger and self._wall()
                            >= wd + grace * rank):
                        self._fire_replica_will(entry)
                elif down_for >= stagger + delay:
                    self._fire_replica_will(entry)
        self._maybe_expire(entry, now, down_for, stagger)

    def _maybe_expire(self, entry: SessionEntry, now: float,
                      down_for: float, stagger: float) -> None:
        """The replica-side expiry timer: seeded from the replicated
        expiry metadata (``cluster_replica_expiry_s`` fallback when the
        session carries none; 0 disables the fallback), counted from
        the disconnect we observed — or from the owner's death when it
        died with the client attached. Tombstone-fenced like any purge:
        a returning owner's live update supersedes, a re-created
        session claims above the purged epoch."""
        if entry.expiry_set:
            limit = float(entry.expiry)
        elif self.replica_expiry > 0:
            limit = self.replica_expiry
        else:
            return
        elapsed = (now - entry.disconnected_seen) \
            if (not entry.connected and entry.disconnected_seen) \
            else down_for
        if elapsed < limit + stagger:
            return
        if entry.will is not None:
            # ADR 019 (satellite): an expiring session with a still-
            # pending transferred will fires it on the way out — expiry
            # ends the will delay early per [MQTT-3.1.2-10] (session
            # end publishes the will), and silently purging it lost
            # the will entirely
            self._fire_replica_will(entry)
        self.replica_expiries += 1
        self._remove_entry(entry.cid)
        self._note_tombstone(entry.cid, entry.session_epoch)
        # third-party purge: ``ow`` + the exact token tell transitive
        # replica holders (who may hold no link to the dead owner)
        # which incarnation was judged expired — fenced so a newer
        # claim/update is never purged by a stale judgement
        self._broadcast("purge", {"cid": entry.cid,
                                  "se": entry.session_epoch,
                                  "be": entry.boot_epoch,
                                  "ow": entry.owner})

    def _fire_replica_will(self, entry: SessionEntry) -> None:
        """Fire a dead owner's transferred will exactly once: the will
        is consumed locally FIRST (reentrancy-safe), broadcast-cleared
        on every replica (epoch-fenced), then fanned out through the
        normal will path — local subscribers, retained store, and the
        ADR-013 forward rails for remote subscribers."""
        w, entry.will = entry.will, None
        hook = getattr(self.broker, "_storage_hook", None)
        if hook is not None:
            hook.store.put(SESS_BUCKET, entry.cid, entry.meta_json())
        self._broadcast("willfire", {"cid": entry.cid,
                                     "se": entry.session_epoch,
                                     "be": entry.boot_epoch,
                                     "ow": entry.owner})
        try:
            topic = str(w[0])
            payload = bytes.fromhex(str(w[1]))
            qos, retain = int(w[2]), bool(w[3])
        except (IndexError, ValueError, TypeError):
            self.restore_errors += 1
            return
        if not valid_topic_name(topic) or topic.startswith("$"):
            self.restore_errors += 1    # a peer must not smuggle junk
            return
        self.wills_fired += 1
        packet = Packet(
            fixed=FixedHeader(type=PT.PUBLISH, qos=min(qos, 2),
                              retain=retain),
            topic=topic, payload=payload, origin=entry.cid,
            created=time.time())
        self.broker._fire_will(None, packet)
        log = self.manager.log
        if log is not None:
            log.warn("transferred will fired", cid=entry.cid,
                     owner=entry.owner, topic=topic)

    def _apply_willfire(self, origin: str, d: dict) -> None:
        """A judge fired (or is about to fire) this session's will:
        stand down — but only for the exact incarnation it judged; a
        takeover or reconnect since then owns a fresh will."""
        entry = self.ledger.get(str(d["cid"]))
        if entry is None or entry.will is None:
            return
        token = (int(d["se"]), int(d.get("be", 0)),
                 str(d.get("ow", "")))
        if entry.token != token:
            return
        entry.will = None
        self.wills_cleared += 1
        hook = getattr(self.broker, "_storage_hook", None)
        if hook is not None:
            hook.store.put(SESS_BUCKET, entry.cid, entry.meta_json())

    def _live_inflight_ops(self, cid: str) -> list:
        client = self.broker.clients.get(cid)
        if client is None:
            return []
        ops = []
        held = set(client.held_pids)
        for p in client.inflight.all():
            rec = MessageRecord.from_packet(p, cid)
            if p.packet_id in held:
                rec.held = True     # ADR 018: held-ness survives resync
            ops.append([cid, p.packet_id, "set", rec.to_json()])
        for pid in sorted(client.pubrec_inbound):
            ops.append([cid, pid, "rec"])   # ADR 018: QoS2 dedup set
        return ops

    # ------------------------------------------------------------------
    # Inbound dispatch (from ClusterManager.handle_inbound)
    # ------------------------------------------------------------------

    async def handle_inbound(self, sender: str, levels: list[str],
                             packet) -> None:
        kind = levels[3]
        msg = self._admit_envelope(packet.payload)
        if msg is None:
            return
        origin = str(msg["o"])
        to = msg.get("to")
        if to is None or to == self.node_id:
            try:
                hit = faults.fire_detail(faults.CLUSTER_SESSION_SYNC,
                                         key=origin)
            except faults.InjectedFault:
                self.sync_faults += 1
                return
            if hit is not None:
                self.sync_faults += 1
                if hit[0] == "hang":
                    await asyncio.sleep(hit[1])
                else:
                    return      # drop: the update never applies here
            self._dispatch(kind, origin, msg.get("d") or {},
                           int(msg["q"]))
        if to != self.node_id:
            self._relay(kind, msg, exclude={sender, origin})

    def _admit_envelope(self, payload: bytes) -> dict | None:
        """Parse + dedup one sess envelope: per-(origin, boot-epoch)
        windows exactly like the ADR-013 forward rails, so redundant
        relay paths and stale-incarnation replays apply once/never."""
        from .manager import DedupWindow
        try:
            msg = json.loads(payload)
            origin = str(msg["o"])
            epoch = int(msg["e"])
            seq = int(msg["q"])
        except Exception:
            self.inbound_rejected += 1
            return None
        if origin == self.node_id:
            return None     # our own message relayed around a cycle
        win = self._seen.get(origin)
        if win is None or epoch > win.epoch:
            win = self._seen[origin] = DedupWindow(epoch=epoch)
        elif epoch < win.epoch:
            return None     # stale incarnation replay
        if not win.admit(seq):
            return None     # redundant relay path
        return msg

    def _dispatch(self, kind: str, origin: str, d: dict,
                  seq: int = 0) -> None:
        try:
            if kind == "up":
                self._apply_update(origin, d, seq)
            elif kind == "claim":
                self._apply_claim(origin, d)
            elif kind == "state":
                self._apply_state(origin, d)
            elif kind == "infl":
                self._apply_inflight(origin, d, seq)
            elif kind == "purge":
                self._apply_purge(origin, d)
            elif kind == "willfire":
                self._apply_willfire(origin, d)
            else:
                self.inbound_rejected += 1
        except (KeyError, ValueError, TypeError):
            self.inbound_rejected += 1

    def _entry_from_wire(self, origin: str, d: dict) -> SessionEntry:
        return SessionEntry(
            str(d["cid"]), origin, int(d["se"]), int(d.get("be", 0)),
            int(d.get("exp", 0)), bool(d.get("exps", 0)),
            int(d.get("pv", 4)), bool(d.get("conn", 0)),
            d.get("subs") or [], d.get("shares") or [],
            d.get("dig") or (0, 0), d.get("will"))

    def _apply_update(self, origin: str, d: dict, seq: int = 0) -> None:
        new = self._entry_from_wire(origin, d)
        new.applied_seq = seq
        cur = self.ledger.get(new.cid)
        if cur is not None:
            if new.token < cur.token:
                return      # fenced: an older incarnation's update
            if (new.token == cur.token and seq and cur.applied_seq
                    and seq < cur.applied_seq):
                return      # same-owner updates reordered by a relay
            if cur.owner == self.node_id and new.token > cur.token:
                # an update outran its claim: treat it as one
                self._lose_session(new.cid, to=origin, pull=False,
                                   purge=False, token=new.token)
        self._apply_entry(new)

    def _apply_claim(self, origin: str, d: dict) -> None:
        cid = str(d["cid"])
        token = (int(d["se"]), int(d.get("be", 0)), origin)
        purge = bool(d.get("purge", 0))
        pull = bool(d.get("pull", 0))
        cur = self.ledger.get(cid)
        if cur is not None and cur.owner == self.node_id:
            if token > cur.token:
                self._lose_session(cid, to=origin, pull=pull,
                                   purge=purge, token=token,
                                   on_shipped=self._ship_reporter(
                                       d.get("tr")))
            else:
                # stale claimant: correct it with our own state record
                self.claims_rejected += 1
                self._broadcast("up", _entry_update_dict(cur), to=origin)
            return
        if cur is not None and token <= cur.token:
            self.claims_rejected += 1
            return
        # ADR 022 (closes the ADR-021 dead-owner blackhole): this claim
        # moved the session off a DEAD prior owner — any QoS1 forwards
        # we parked against that owner's link for the session's topics
        # now have a live home at the claimant
        if (cur is not None and not purge and cur.subs
                and cur.owner not in (origin, self.node_id)):
            self.manager.rehome_for_takeover(
                cur.owner, origin, [str(rec[0]) for rec in cur.subs
                                    if rec])
        entry = self._reowned_entry(cid, cur, token, purge)
        if purge:
            hook = getattr(self.broker, "_storage_hook", None)
            if hook is not None:
                hook.store.delete_prefix(INFLIGHT_BUCKET, cid + "|")
        self._apply_entry(entry, keep_inflight=not purge)

    @staticmethod
    def _reowned_entry(cid: str, cur: SessionEntry | None, token: tuple,
                       purge: bool) -> SessionEntry:
        """A fresh entry for a session whose ownership just moved:
        state carries over from the previous replica unless purged.
        The WILL never carries over (ADR 018): a claim means a live
        client at the claimant, whose own CONNECT will replicates with
        the claimant's next update — and a reconnect cancels a pending
        dead-owner will, exactly like a local reconnect cancels a
        delayed will."""
        keep = cur is not None and not purge
        return SessionEntry(
            cid, token[2], token[0], token[1],
            cur.expiry if keep else 0, cur.expiry_set if keep else False,
            cur.protocol_version if keep else 4, True,
            cur.subs if keep else [], cur.shares if keep else [],
            cur.digest if keep else (0, 0))

    def _lose_session(self, cid: str, to: str, pull: bool, purge: bool,
                      token: tuple, on_shipped=lambda: None) -> None:
        """A higher fencing token seized a session we own: disconnect
        the live client with v5 SessionTakenOver, hand the state to the
        winner when asked, and drop every local trace — the session now
        lives (and persists) at the claimant. ``on_shipped`` fires once
        the handoff is on the wire (the ADR-017 ship-leg span reporter,
        a no-op for untraced claims)."""
        self.sessions_lost += 1
        broker = self.broker
        client = broker.clients.get(cid)
        state = None
        if client is not None and pull and not purge:
            state = self._state_dict(client, token)
        if client is not None:
            self._evict_lost_client(cid, client)
        if state is not None:
            self._broadcast("state", state, to=to)
        on_shipped()
        self._seed_replica_of_winner(cid, token, purge, state)

    def _evict_lost_client(self, cid: str, client) -> None:
        """Disconnect + deregister the local client whose session was
        claimed away: trie subscriptions withdrawn (and un-advertised),
        live storage rows dropped — the claimant persists it now."""
        broker = self.broker
        client.taken_over = True
        # ADR 019 (satellite): a pending delayed will is cancelled by
        # the takeover — the session lives on at the claimant, and the
        # will-delay contract [MQTT-3.1.3-9] says a session resumption
        # before the delay elapses suppresses the will
        broker._will_delays.pop(cid, None)
        if not client.closed:
            broker.disconnect_client(client, codes.ErrSessionTakenOver)
            broker._spawn(
                client.stop(ProtocolError(codes.ErrSessionTakenOver)),
                "sess-takeover-stop")
        self._suppress_purge.add(cid)
        try:
            for filt in list(client.subscriptions):
                if broker.topics.unsubscribe(cid, filt):
                    broker.info.subscriptions -= 1
                    self.manager.note_unsubscribe(filt)
            client.subscriptions.clear()
            broker.info.inflight -= len(client.inflight)
            broker.clients.delete(cid)
            hook = getattr(broker, "_storage_hook", None)
            if hook is not None:
                hook.store.delete("clients", cid)
                hook.store.delete_prefix("subscriptions", cid + "|")
                hook.store.delete_prefix("inflight", cid + "|")
        finally:
            self._suppress_purge.discard(cid)

    def _seed_replica_of_winner(self, cid: str, token: tuple,
                                purge: bool, state: dict | None) -> None:
        """Install our replica of the session at its new owner — seeded
        from the SAME accurate snapshot we just shipped it (the old
        self-owned entry's dict may predate acks the live client
        drained), journal mirrored."""
        entry = self._reowned_entry(cid, self.ledger.get(cid), token,
                                    purge)
        keep = not purge
        if state is not None and not purge:
            entry.subs = state["subs"]
            entry.shares = state["shares"]
            entry.digest = tuple(state["dig"])
            entry.inflight = {int(p): str(r)
                              for p, r in (state.get("infl") or {}).items()}
            entry.pubrec = [int(p) for p in state.get("pubrec") or []]
            keep = False
        self._apply_entry(entry, keep_inflight=keep)
        if not keep:
            hook = getattr(self.broker, "_storage_hook", None)
            if hook is not None:
                hook.store.delete_prefix(INFLIGHT_BUCKET, cid + "|")
                for pid, raw in entry.inflight.items():
                    hook.store.put(INFLIGHT_BUCKET, f"{cid}|{pid}", raw)
                for pid in entry.pubrec:
                    # the QoS2 dedup set must survive OUR crash too —
                    # the prefix delete above swept its r-rows
                    hook.store.put(INFLIGHT_BUCKET, f"{cid}|r{pid}",
                                   "1")

    def _ship_reporter(self, trace):
        """ADR 017: a closure reporting the ship-leg span back to the
        claimant — how long the prior owner spent disconnecting +
        packaging the handoff. A claim without trace identity gets a
        no-op, so _lose_session stays branch-free about tracing."""
        tracer = getattr(self.broker, "tracer", None)
        if trace is None or tracer is None:
            return lambda: None
        t_ship0 = tracer.clock()

        def fire() -> None:
            try:
                dur_us = max(tracer.clock() - t_ship0, 0) // 1000
                self.manager.telemetry.send_report(
                    str(trace[0]), int(trace[1]),
                    [["sess_ship", 0, dur_us]], e2e_us=dur_us,
                    kind="sess")
            except (TypeError, ValueError, IndexError):
                pass    # malformed trace identity: the handoff stands

        return fire

    def _state_dict(self, client, token: tuple) -> dict:
        subs, shares = self._subs_shares(client)
        infl = {}
        held = set(client.held_pids)
        for p in client.inflight.all():
            rec = MessageRecord.from_packet(p, client.id)
            if p.packet_id in held:
                rec.held = True     # ADR 018: held-ness survives the
            infl[str(p.packet_id)] = rec.to_json()  # state-pull leg too
        return {"cid": client.id, "se": token[0], "be": token[1],
                "subs": subs, "shares": shares,
                "dig": list(client.inflight.digest()),
                "pubrec": sorted(client.pubrec_inbound),
                "infl": infl}

    def _apply_state(self, origin: str, d: dict) -> None:
        fut = self._pulls.get(str(d.get("cid", "")))
        if fut is not None and not fut.done():
            fut.set_result(d)
        # no waiter: a late handoff — the claim already resolved the
        # ownership, and the owner's next update supersedes this

    def _apply_inflight(self, origin: str, d: dict, seq: int = 0) -> None:
        hook = getattr(self.broker, "_storage_hook", None)
        for op in d.get("ops") or []:
            cid, pid, kind = str(op[0]), int(op[1]), str(op[2])
            entry = self.ledger.get(cid)
            if entry is None or entry.owner != origin:
                continue    # stale: the session moved since this op
            if seq and entry.infl_seq > seq:
                continue    # a relay path reordered this chunk behind
                            # a newer one: a late 'set' must not
                            # resurrect a completed message
            entry.infl_seq = max(entry.infl_seq, seq)
            self._apply_one_op(entry, cid, pid, kind, op, hook)
        self._apply_digests(origin, d.get("dig") or {}, hook, seq)

    def _apply_one_op(self, entry: SessionEntry, cid: str, pid: int,
                      kind: str, op: list, hook) -> None:
        """One replicated inflight op against one replica entry:
        ``set``/``del`` maintain the parked window, ``rec``/``recdel``
        (ADR 018) the streamed receiver-side QoS2 dedup set — each
        mirrored into the cluster_inflight journal bucket."""
        if kind == "set":
            raw = str(op[3])
            entry.inflight[pid] = raw
            self._note_trace_op(cid, pid, op)
            if hook is not None:
                hook.store.put(INFLIGHT_BUCKET, f"{cid}|{pid}", raw)
        elif kind == "rec":
            if pid not in entry.pubrec:
                entry.pubrec.append(pid)
            if hook is not None:
                hook.store.put(INFLIGHT_BUCKET, f"{cid}|r{pid}", "1")
        elif kind == "recdel":
            if pid in entry.pubrec:
                entry.pubrec.remove(pid)
            if hook is not None:
                hook.store.delete(INFLIGHT_BUCKET, f"{cid}|r{pid}")
        else:       # "del"
            entry.inflight.pop(pid, None)
            if hook is not None:
                hook.store.delete(INFLIGHT_BUCKET, f"{cid}|{pid}")

    def _note_trace_op(self, cid: str, pid: int, op: list) -> None:
        """ADR 017: when the op carried its publish's trace identity,
        count + log it — one grep of trace=<origin>:<id> correlates
        the replica write with the origin's pipeline trace across
        nodes."""
        if len(op) <= 4 or not op[4]:
            return
        ref = op[4]
        self.trace_ops_applied += 1
        log = self.manager.log
        if log is not None:
            try:
                log.debug("inflight replica applied", cid=cid, pid=pid,
                          trace=f"{ref[0]}:{ref[1]}")
            except (IndexError, TypeError):
                pass

    def _apply_digests(self, origin: str, digests: dict, hook,
                       seq: int = 0) -> None:
        """Flush-time digests riding the ops keep the replica's digest
        aligned with the window it holds (ADR 016)."""
        for cid, dig in digests.items():
            entry = self.ledger.get(str(cid))
            if entry is not None and entry.owner == origin \
                    and not (seq and entry.infl_seq > seq):
                entry.digest = tuple(dig)
                if hook is not None:    # same-key writes coalesce in
                    hook.store.put(SESS_BUCKET, str(cid),  # the journal
                                   entry.meta_json())

    def _apply_purge(self, origin: str, d: dict) -> None:
        cid = str(d["cid"])
        entry = self.ledger.get(cid)
        if entry is None:
            return
        if "ow" in d:
            # ADR 018: a third-party purge — a judge expired a dead
            # owner's replica on our behalf (we may hold no link to
            # the owner). Fenced to the EXACT incarnation it judged:
            # any later claim/update owns a higher token and survives.
            if (entry.owner != str(d["ow"])
                    or entry.session_epoch != int(d.get("se", 0))
                    or entry.boot_epoch != int(d.get("be", 0))):
                return
        elif entry.owner != origin:
            return      # we (or a third node) own a newer incarnation
        self.purges += 1
        self._remove_entry(cid)
        self._note_tombstone(cid, int(d.get("se", 0)))
