"""Aggregated cluster route table + snapshot/delta wire codec (ADR 013).

Each node advertises the set of topic filters it (or anything reachable
through it) has subscribers for. The table here stores what REMOTE
peers advertised — keyed by direct-peer node id — and answers the one
hot-path question ``nodes_for(topic)``: which peers need a copy of this
publish. Remote filters live in a :class:`~..matching.trie.TopicIndex`
whose "client ids" are node ids, so matching reuses the exact wildcard
semantics (and C-backed SubscriberSet) of the local matcher instead of
a second, subtly different matcher; results are memoized in a
``VersionedTopicCache`` keyed on the index's subscription version.

Advertisements are *aggregated*: a filter subsumed by a broader one
from the same advertiser is never put on the wire (``sport/#`` at a
peer subsumes ``sport/+/score`` — arXiv:1811.07088's subscription
aggregation), so route-table size tracks the distinct filter shapes,
not the subscription count.

Wire format (versioned, JSON payloads on reserved ``$cluster/routes/*``
topics):

* snapshot — zlib-compressed ``{"v":1,"node","epoch","seq","filters"}``
  published to ``$cluster/routes/<node>`` (retained on the receiving
  broker for observability); replaces everything known about the node.
* delta — plain ``{"v":1,"node","epoch","seq","add","del"}`` published
  to ``$cluster/routes/<node>/delta``; applies only when ``epoch``
  matches and ``seq`` is exactly ``last_seq + 1`` — any gap is a
  desync and the receiver must request a fresh snapshot.

Epochs are per-process-boot monotonic stamps: a restarted peer's first
snapshot carries a higher epoch, flushing every stale route the old
incarnation advertised (including a stale RETAINED snapshot replayed
by a broker — lower epochs are ignored).
"""

from __future__ import annotations

import json
import zlib

from ..matching.topics import filter_matches_topic, split_levels
from ..matching.trie import TopicIndex, VersionedTopicCache
from ..protocol.packets import Subscription

WIRE_VERSION = 1

# topic level budget for route payloads; a snapshot beyond this refuses
# to decode rather than let one peer OOM the cluster control plane
MAX_SNAPSHOT_BYTES = 8 << 20


class RouteWireError(ValueError):
    """A route snapshot/delta payload that failed to decode."""


def filter_subsumes(general: str, specific: str) -> bool:
    """True when every topic matching ``specific`` also matches
    ``general`` (so advertising ``general`` alone loses nothing).
    Level rules mirror the trie walk: ``#`` covers the parent level and
    everything deeper [MQTT-4.7.1.2], ``+`` covers exactly one level
    [MQTT-4.7.1-3]. ``$``-prefixed filters are never advertised (the
    cluster refuses to forward ``$`` topics), so the root-level
    dollar exception never arises here."""
    if general == specific:
        return True
    glv = split_levels(general)
    slv = split_levels(specific)
    for i, gl in enumerate(glv):
        if gl == "#":
            return True
        if i >= len(slv):
            return False
        sl = slv[i]
        if gl == "+":
            if sl == "#":
                return False    # specific reaches deeper than one level
            continue
        if gl != sl:
            return False        # literal mismatch, or specific is the
    return len(glv) == len(slv)  # broader one ('+'/'#' vs literal)


def minimal_cover(filters) -> set[str]:
    """The aggregated advertisement: drop every filter subsumed by a
    DIFFERENT filter in the set. O(n^2) level walks — kept as the
    reference implementation the incremental :class:`IncrementalCover`
    is equivalence-tested against; the hot path no longer calls it per
    change (ADR 016 / the ROADMAP open item)."""
    fs = set(filters)
    out = set()
    for f in fs:
        if not any(g != f and filter_subsumes(g, f) for g in fs):
            out.add(f)
    return out


class IncrementalCover:
    """Refcounted filter set with an incrementally-maintained minimal
    cover (the ROADMAP open item: the O(n^2) per-change recompute dies
    before per-user filter shapes meet the session ledger).

    * ``add(f)``    — one subsumption scan of the current cover: either
      ``f`` hides behind an existing cover member (recorded with that
      member as its *witness*), or ``f`` joins the cover and demotes
      every member it subsumes (their hidden filters are re-witnessed
      by ``f`` — subsumption is transitive, so witnesses stay valid).
    * ``remove(f)`` — when a cover member's refcount hits zero, only
      the filters it witnessed are re-examined: each re-hides behind a
      surviving cover member or promotes (promotion reuses the add
      path, so two re-exposed filters that subsume each other still
      collapse).

    Both operations are O(cover + re-exposed) instead of O(n^2) over
    the whole set. Invariant (equivalence-tested in test_cluster.py):
    ``self.cover == minimal_cover(self.refs.keys())`` after any
    sequence of add/remove."""

    __slots__ = ("refs", "cover", "_witness")

    def __init__(self, filters=()) -> None:
        self.refs: dict[str, int] = {}
        self.cover: set[str] = set()
        self._witness: dict[str, str] = {}   # hidden filter -> cover member
        for f in filters:
            self.add(f)

    def add(self, filt: str) -> None:
        n = self.refs.get(filt, 0)
        self.refs[filt] = n + 1
        if n:
            return                          # already placed
        for c in self.cover:
            if c != filt and filter_subsumes(c, filt):
                self._witness[filt] = c
                return
        self._promote(filt)

    def _promote(self, filt: str) -> None:
        """Install ``filt`` as a cover member, demoting every member it
        subsumes (and re-witnessing their hidden filters to ``filt``)."""
        demoted = [c for c in self.cover
                   if c != filt and filter_subsumes(filt, c)]
        for c in demoted:
            self.cover.discard(c)
            self._witness[c] = filt
        if demoted:
            for h, w in self._witness.items():
                if w in demoted:
                    self._witness[h] = filt
        self.cover.add(filt)

    def remove(self, filt: str) -> None:
        n = self.refs.get(filt, 0)
        if n > 1:
            self.refs[filt] = n - 1
            return
        if n == 0:
            return
        del self.refs[filt]
        if filt in self._witness:
            del self._witness[filt]
            return
        self.cover.discard(filt)
        exposed = [h for h, w in self._witness.items() if w == filt]
        for h in exposed:
            del self._witness[h]
        for h in exposed:
            for c in self.cover:
                if c != h and filter_subsumes(c, h):
                    self._witness[h] = c
                    break
            else:
                self._promote(h)


class ShareLedger:
    """Cluster-wide ``$share`` group-membership ledger (ADR 016/018).

    Maps ``(group, filter)`` to live-member counts per *member id* —
    node ids for the federation, worker ids for the in-process delivery
    pool (broker/workers.py routes its gossip through this same class,
    so a filter shared across both a pool and a peer node resolves
    ownership through one set of rules). Ownership is deterministic
    with no coordination round; two balance modes (ADR 018):

    * ``pin`` — the lowest member id with a live count owns the pick
      for every publish (the ADR-005 fairness trade; the in-process
      worker pool keeps this mode).
    * ``weighted`` — the owner rotates per publish, weighted by each
      member's live-subscriber count: every node derives the same
      owner from the same ``token`` (a content hash of the publish)
      and the same converged ledger, so the exactly-once invariant
      holds while a node with 3 live group members receives ~3x the
      picks of a node with 1. A ``token=None`` caller (or a
      single-member key) falls back to ``pin``.

    A key nobody (else) claims is owned locally. Divergence window
    (both modes, ADR 016/018): while gossip is in flight two nodes can
    disagree on the ledger and a publish can double- or zero-deliver
    for that round — ``pin`` diverges only on membership-set changes,
    ``weighted`` also on member-count changes (and on mixed-version
    clusters: run ``pin`` until every node speaks ADR 018 — see
    migration.md). The window is one gossip round, bounded by the
    session-replication debounce."""

    __slots__ = ("self_id", "_members", "balance")

    def __init__(self, self_id, balance: str = "pin") -> None:
        self.self_id = self_id
        self.balance = balance
        # (group, filter) -> member id -> live local-subscription count
        self._members: dict[tuple[str, str], dict] = {}

    def set_member(self, member, key: tuple[str, str], n: int) -> None:
        per = self._members.get(key)
        if n > 0:
            if per is None:
                per = self._members[key] = {}
            per[member] = n
        elif per is not None:
            per.pop(member, None)
            if not per:
                del self._members[key]

    def set_local(self, key: tuple[str, str], n: int) -> None:
        self.set_member(self.self_id, key, n)

    def replace_member(self, member, counts: dict) -> None:
        """Full per-member replacement: keys absent from ``counts`` are
        cleared (a restarted member's stale claims must not linger)."""
        for key in [k for k, per in self._members.items()
                    if member in per and k not in counts]:
            self.set_member(member, key, 0)
        for key, n in counts.items():
            self.set_member(member, key, int(n))

    def drop_member(self, member) -> None:
        self.replace_member(member, {})

    def members_for(self, key: tuple[str, str]) -> list:
        per = self._members.get(key)
        return sorted(m for m, n in (per or {}).items() if n > 0)

    def owner_for(self, key: tuple[str, str], token: int | None = None):
        """The member that owns this publish's pick, or None when the
        key has no live members. Deterministic on every node from the
        (converged) ledger: ``weighted`` walks the sorted members with
        their live counts as weights, indexed by ``token``; anything
        else — or no token — pins to the lowest member id."""
        per = self._members.get(key)
        members = sorted(m for m, n in (per or {}).items() if n > 0)
        if not members:
            return None
        if (token is None or self.balance != "weighted"
                or len(members) == 1):
            return members[0]
        weights = [per[m] for m in members]
        slot = token % sum(weights)
        for m, w in zip(members, weights):
            slot -= w
            if slot < 0:
                return m
        return members[-1]      # unreachable (slot < sum of weights)

    def owns(self, key: tuple[str, str],
             token: int | None = None) -> bool:
        owner = self.owner_for(key, token)
        # nobody claims it: local delivery is safe
        return owner is None or owner == self.self_id

    @property
    def group_count(self) -> int:
        return len(self._members)


# ----------------------------------------------------------------------
# Wire codec
# ----------------------------------------------------------------------


def encode_snapshot(node: str, epoch: int, seq: int, filters,
                    preds=None) -> bytes:
    d = {"v": WIRE_VERSION, "node": node, "epoch": epoch, "seq": seq,
         "filters": sorted(filters)}
    if preds:
        # ADR 023 stretch: per-filter predicate annotations, present
        # only for filters whose EVERY local holder is content-gated.
        # Decoders that predate the key ignore it (same wire version:
        # the snapshot stays fully readable without it).
        d["preds"] = {f: sorted(preds[f]) for f in sorted(preds)}
    return zlib.compress(json.dumps(d).encode())


def decode_snapshot(payload: bytes) -> tuple[str, int, int, list[str]]:
    node, epoch, seq, filters, _preds = decode_snapshot_preds(payload)
    return node, epoch, seq, filters


def decode_snapshot_preds(
        payload: bytes
) -> tuple[str, int, int, list[str], dict[str, tuple[str, ...]]]:
    """Snapshot decode that also surfaces the optional ADR-023
    predicate annotations ({} when the sender carried none)."""
    try:
        raw = zlib.decompress(payload, bufsize=65536)
        if len(raw) > MAX_SNAPSHOT_BYTES:
            raise RouteWireError("snapshot too large")
        d = json.loads(raw)
        if d.get("v") != WIRE_VERSION:
            raise RouteWireError(f"unknown wire version {d.get('v')!r}")
        preds = {str(f): tuple(str(e) for e in exprs)
                 for f, exprs in (d.get("preds") or {}).items()}
        return (str(d["node"]), int(d["epoch"]), int(d["seq"]),
                [str(f) for f in d["filters"]], preds)
    except RouteWireError:
        raise
    except Exception as exc:
        raise RouteWireError(f"bad snapshot: {exc!r}") from exc


def encode_delta(node: str, epoch: int, seq: int,
                 add, remove) -> bytes:
    return json.dumps(
        {"v": WIRE_VERSION, "node": node, "epoch": epoch, "seq": seq,
         "add": sorted(add), "del": sorted(remove)}).encode()


def decode_delta(payload: bytes
                 ) -> tuple[str, int, int, list[str], list[str]]:
    try:
        d = json.loads(payload)
        if d.get("v") != WIRE_VERSION:
            raise RouteWireError(f"unknown wire version {d.get('v')!r}")
        return (str(d["node"]), int(d["epoch"]), int(d["seq"]),
                [str(f) for f in d["add"]], [str(f) for f in d["del"]])
    except RouteWireError:
        raise
    except Exception as exc:
        raise RouteWireError(f"bad delta: {exc!r}") from exc


# ----------------------------------------------------------------------
# The table
# ----------------------------------------------------------------------


class NodeRoutes:
    """What one direct peer currently advertises. ``preds`` holds the
    ADR-023 content-gating annotations: filter -> predicate exprs for
    filters whose every holder at the peer requires a predicate."""

    __slots__ = ("epoch", "seq", "filters", "preds")

    def __init__(self, epoch: int, seq: int, filters: set[str],
                 preds: dict[str, tuple[str, ...]] | None = None) -> None:
        self.epoch = epoch
        self.seq = seq
        self.filters = filters
        self.preds = preds or {}


class RouteTable:
    """Local aggregated filters + per-peer advertised filter sets.

    Single-threaded: every mutation and query runs on the broker's
    asyncio loop (the inner TopicIndex carries its own lock, but this
    class adds no cross-thread contract)."""

    def __init__(self, node_id: str, epoch: int) -> None:
        self.node_id = node_id
        self.epoch = epoch
        # local aggregated refcounts: filter -> live subscription count
        self.local: dict[str, int] = {}
        self.nodes: dict[str, NodeRoutes] = {}
        self._index = TopicIndex()          # remote filters, cid=node
        self._cache = VersionedTopicCache(maxsize=2048)
        # per-peer incrementally-maintained advertisement covers
        # (ADR 016): each holds local filters + every OTHER peer's
        # filters (split horizon), updated in O(cover) per change
        # instead of the old O(n^2) minimal_cover recompute per link
        self._covers: dict[str, IncrementalCover] = {}
        # cluster-wide $share group-membership ledger (ADR 016): fed by
        # cluster/sessions.py, consulted by the broker's shared fan-out
        self.shares = ShareLedger(node_id)

    # -- local side ----------------------------------------------------

    def note_local_subscribe(self, filt: str) -> bool:
        """Count one local subscription under its aggregated filter;
        True when the filter is new (advertisements may change)."""
        n = self.local.get(filt, 0)
        self.local[filt] = n + 1
        if n == 0:
            for cov in self._covers.values():
                cov.add(filt)
        return n == 0

    def note_local_unsubscribe(self, filt: str) -> bool:
        n = self.local.get(filt, 0)
        if n <= 1:
            existed = self.local.pop(filt, None) is not None
            if existed:
                for cov in self._covers.values():
                    cov.remove(filt)
            return existed
        self.local[filt] = n - 1
        return False

    def _cover_update(self, node: str, add, remove) -> None:
        """Apply one remote node's effective filter changes to every
        per-peer cover except the node's own (split horizon)."""
        for peer, cov in self._covers.items():
            if peer == node:
                continue
            for f in add:
                cov.add(f)
            for f in remove:
                cov.remove(f)

    def advertisement_for(self, peer: str) -> set[str]:
        """The aggregated filter set this node advertises to ``peer``:
        local filters plus everything learned from OTHER peers (routes
        are transitive — a line topology forwards across the middle
        node), minus anything learned only from ``peer`` itself (split
        horizon: never advertise a peer's own routes back at it).
        Maintained incrementally per peer (ADR 016); the one full
        build happens lazily at first ask for that peer."""
        cov = self._covers.get(peer)
        if cov is None:
            cov = self._covers[peer] = IncrementalCover(self.local)
            for node, nr in self.nodes.items():
                if node != peer:
                    for f in nr.filters:
                        cov.add(f)
        return set(cov.cover)

    # -- remote side ---------------------------------------------------

    def apply_snapshot(self, node: str, epoch: int, seq: int,
                       filters, preds=None) -> bool:
        """Replace everything known about ``node``. False = stale
        (older epoch, or an older seq within the same epoch — e.g. a
        retained snapshot from before the peer restarted)."""
        nr = self.nodes.get(node)
        if nr is not None and (epoch < nr.epoch
                               or (epoch == nr.epoch and seq < nr.seq)):
            return False
        fresh = set(filters)
        if nr is not None:
            removed = nr.filters - fresh
            for f in removed:
                self._index.unsubscribe(node, f)
            add = fresh - nr.filters
        else:
            removed = set()
            add = fresh
        for f in add:
            self._index.subscribe(node, Subscription(filter=f))
        kept = ({f: tuple(exprs) for f, exprs in preds.items()
                 if f in fresh} if preds else None)
        self.nodes[node] = NodeRoutes(epoch, seq, fresh, kept)
        self._cover_update(node, add, removed)
        return True

    def apply_delta(self, node: str, epoch: int, seq: int,
                    add, remove) -> bool:
        """Apply an incremental update; False = desync (unknown node,
        epoch mismatch, or a sequence gap) — the caller must flush and
        request a fresh snapshot."""
        nr = self.nodes.get(node)
        if nr is None or epoch != nr.epoch or seq != nr.seq + 1:
            return False
        removed, added = [], []
        for f in remove:
            if f in nr.filters:
                nr.filters.discard(f)
                nr.preds.pop(f, None)
                self._index.unsubscribe(node, f)
                removed.append(f)
        for f in add:
            if f not in nr.filters:
                nr.filters.add(f)
                # deltas never carry annotations (ADR 023): a delta-added
                # filter is conservatively un-gated until the next
                # snapshot re-establishes it
                nr.preds.pop(f, None)
                self._index.subscribe(node, Subscription(filter=f))
                added.append(f)
        nr.seq = seq
        self._cover_update(node, added, removed)
        return True

    def flush_node(self, node: str) -> int:
        """Drop everything a peer advertised (restart with a fresh
        epoch, or a desync awaiting resync). Returns routes dropped."""
        nr = self.nodes.pop(node, None)
        if nr is None:
            return 0
        for f in nr.filters:
            self._index.unsubscribe(node, f)
        self._cover_update(node, (), nr.filters)
        return len(nr.filters)

    def nodes_for(self, topic: str) -> frozenset[str]:
        """Direct peers whose advertised filters match ``topic`` — the
        forward target set, memoized per (topic, table version)."""
        version = self._index.sub_version
        hit = self._cache.get(topic, version)
        if hit is not None:
            return hit
        matched = self._index.subscribers(topic)
        result = frozenset(matched.subscriptions)
        self._cache.put(topic, version, result)
        return result

    def pred_gate(self, node: str, topic: str
                  ) -> tuple[str, ...] | None:
        """ADR 023 stretch: when EVERY advertised filter of ``node``
        matching ``topic`` carries a predicate annotation, return the
        union of those predicate expressions — the forwarder may skip
        the peer when none passes, because the peer's own content
        plane would mask every delivery anyway. None = not fully gated
        (a matching filter with a plain holder, a transitive route, or
        an annotation-free advertisement): the forward must go."""
        nr = self.nodes.get(node)
        if nr is None or not nr.preds:
            return None
        tlevels = split_levels(topic)
        dollar = topic.startswith("$")
        exprs: list[str] = []
        matched = False
        for f in nr.filters:
            if not filter_matches_topic(split_levels(f), tlevels,
                                        dollar):
                continue
            matched = True
            fexprs = nr.preds.get(f)
            if fexprs is None:
                return None
            exprs.extend(fexprs)
        if not matched:
            return None
        return tuple(dict.fromkeys(exprs))

    @property
    def remote_route_count(self) -> int:
        return sum(len(nr.filters) for nr in self.nodes.values())
