"""Aggregated cluster route table + snapshot/delta wire codec (ADR 013).

Each node advertises the set of topic filters it (or anything reachable
through it) has subscribers for. The table here stores what REMOTE
peers advertised — keyed by direct-peer node id — and answers the one
hot-path question ``nodes_for(topic)``: which peers need a copy of this
publish. Remote filters live in a :class:`~..matching.trie.TopicIndex`
whose "client ids" are node ids, so matching reuses the exact wildcard
semantics (and C-backed SubscriberSet) of the local matcher instead of
a second, subtly different matcher; results are memoized in a
``VersionedTopicCache`` keyed on the index's subscription version.

Advertisements are *aggregated*: a filter subsumed by a broader one
from the same advertiser is never put on the wire (``sport/#`` at a
peer subsumes ``sport/+/score`` — arXiv:1811.07088's subscription
aggregation), so route-table size tracks the distinct filter shapes,
not the subscription count.

Wire format (versioned, JSON payloads on reserved ``$cluster/routes/*``
topics):

* snapshot — zlib-compressed ``{"v":1,"node","epoch","seq","filters"}``
  published to ``$cluster/routes/<node>`` (retained on the receiving
  broker for observability); replaces everything known about the node.
* delta — plain ``{"v":1,"node","epoch","seq","add","del"}`` published
  to ``$cluster/routes/<node>/delta``; applies only when ``epoch``
  matches and ``seq`` is exactly ``last_seq + 1`` — any gap is a
  desync and the receiver must request a fresh snapshot.

Epochs are per-process-boot monotonic stamps: a restarted peer's first
snapshot carries a higher epoch, flushing every stale route the old
incarnation advertised (including a stale RETAINED snapshot replayed
by a broker — lower epochs are ignored).
"""

from __future__ import annotations

import json
import zlib

from ..matching.topics import split_levels
from ..matching.trie import TopicIndex, VersionedTopicCache
from ..protocol.packets import Subscription

WIRE_VERSION = 1

# topic level budget for route payloads; a snapshot beyond this refuses
# to decode rather than let one peer OOM the cluster control plane
MAX_SNAPSHOT_BYTES = 8 << 20


class RouteWireError(ValueError):
    """A route snapshot/delta payload that failed to decode."""


def filter_subsumes(general: str, specific: str) -> bool:
    """True when every topic matching ``specific`` also matches
    ``general`` (so advertising ``general`` alone loses nothing).
    Level rules mirror the trie walk: ``#`` covers the parent level and
    everything deeper [MQTT-4.7.1.2], ``+`` covers exactly one level
    [MQTT-4.7.1-3]. ``$``-prefixed filters are never advertised (the
    cluster refuses to forward ``$`` topics), so the root-level
    dollar exception never arises here."""
    if general == specific:
        return True
    glv = split_levels(general)
    slv = split_levels(specific)
    for i, gl in enumerate(glv):
        if gl == "#":
            return True
        if i >= len(slv):
            return False
        sl = slv[i]
        if gl == "+":
            if sl == "#":
                return False    # specific reaches deeper than one level
            continue
        if gl != sl:
            return False        # literal mismatch, or specific is the
    return len(glv) == len(slv)  # broader one ('+'/'#' vs literal)


def minimal_cover(filters) -> set[str]:
    """The aggregated advertisement: drop every filter subsumed by a
    DIFFERENT filter in the set. O(n^2) level walks over the distinct
    filter shapes — advertisements aggregate per filter, never per
    subscription, so n stays small even at 1M subscriptions."""
    fs = set(filters)
    out = set()
    for f in fs:
        if not any(g != f and filter_subsumes(g, f) for g in fs):
            out.add(f)
    return out


# ----------------------------------------------------------------------
# Wire codec
# ----------------------------------------------------------------------


def encode_snapshot(node: str, epoch: int, seq: int, filters) -> bytes:
    return zlib.compress(json.dumps(
        {"v": WIRE_VERSION, "node": node, "epoch": epoch, "seq": seq,
         "filters": sorted(filters)}).encode())


def decode_snapshot(payload: bytes) -> tuple[str, int, int, list[str]]:
    try:
        raw = zlib.decompress(payload, bufsize=65536)
        if len(raw) > MAX_SNAPSHOT_BYTES:
            raise RouteWireError("snapshot too large")
        d = json.loads(raw)
        if d.get("v") != WIRE_VERSION:
            raise RouteWireError(f"unknown wire version {d.get('v')!r}")
        return (str(d["node"]), int(d["epoch"]), int(d["seq"]),
                [str(f) for f in d["filters"]])
    except RouteWireError:
        raise
    except Exception as exc:
        raise RouteWireError(f"bad snapshot: {exc!r}") from exc


def encode_delta(node: str, epoch: int, seq: int,
                 add, remove) -> bytes:
    return json.dumps(
        {"v": WIRE_VERSION, "node": node, "epoch": epoch, "seq": seq,
         "add": sorted(add), "del": sorted(remove)}).encode()


def decode_delta(payload: bytes
                 ) -> tuple[str, int, int, list[str], list[str]]:
    try:
        d = json.loads(payload)
        if d.get("v") != WIRE_VERSION:
            raise RouteWireError(f"unknown wire version {d.get('v')!r}")
        return (str(d["node"]), int(d["epoch"]), int(d["seq"]),
                [str(f) for f in d["add"]], [str(f) for f in d["del"]])
    except RouteWireError:
        raise
    except Exception as exc:
        raise RouteWireError(f"bad delta: {exc!r}") from exc


# ----------------------------------------------------------------------
# The table
# ----------------------------------------------------------------------


class NodeRoutes:
    """What one direct peer currently advertises."""

    __slots__ = ("epoch", "seq", "filters")

    def __init__(self, epoch: int, seq: int, filters: set[str]) -> None:
        self.epoch = epoch
        self.seq = seq
        self.filters = filters


class RouteTable:
    """Local aggregated filters + per-peer advertised filter sets.

    Single-threaded: every mutation and query runs on the broker's
    asyncio loop (the inner TopicIndex carries its own lock, but this
    class adds no cross-thread contract)."""

    def __init__(self, node_id: str, epoch: int) -> None:
        self.node_id = node_id
        self.epoch = epoch
        # local aggregated refcounts: filter -> live subscription count
        self.local: dict[str, int] = {}
        self.nodes: dict[str, NodeRoutes] = {}
        self._index = TopicIndex()          # remote filters, cid=node
        self._cache = VersionedTopicCache(maxsize=2048)

    # -- local side ----------------------------------------------------

    def note_local_subscribe(self, filt: str) -> bool:
        """Count one local subscription under its aggregated filter;
        True when the filter is new (advertisements may change)."""
        n = self.local.get(filt, 0)
        self.local[filt] = n + 1
        return n == 0

    def note_local_unsubscribe(self, filt: str) -> bool:
        n = self.local.get(filt, 0)
        if n <= 1:
            existed = self.local.pop(filt, None) is not None
            return existed
        self.local[filt] = n - 1
        return False

    def advertisement_for(self, peer: str) -> set[str]:
        """The aggregated filter set this node advertises to ``peer``:
        local filters plus everything learned from OTHER peers (routes
        are transitive — a line topology forwards across the middle
        node), minus anything learned only from ``peer`` itself (split
        horizon: never advertise a peer's own routes back at it)."""
        pool = set(self.local)
        for node, nr in self.nodes.items():
            if node != peer:
                pool |= nr.filters
        return minimal_cover(pool)

    # -- remote side ---------------------------------------------------

    def apply_snapshot(self, node: str, epoch: int, seq: int,
                       filters) -> bool:
        """Replace everything known about ``node``. False = stale
        (older epoch, or an older seq within the same epoch — e.g. a
        retained snapshot from before the peer restarted)."""
        nr = self.nodes.get(node)
        if nr is not None and (epoch < nr.epoch
                               or (epoch == nr.epoch and seq < nr.seq)):
            return False
        fresh = set(filters)
        if nr is not None:
            for f in nr.filters - fresh:
                self._index.unsubscribe(node, f)
            add = fresh - nr.filters
        else:
            add = fresh
        for f in add:
            self._index.subscribe(node, Subscription(filter=f))
        self.nodes[node] = NodeRoutes(epoch, seq, fresh)
        return True

    def apply_delta(self, node: str, epoch: int, seq: int,
                    add, remove) -> bool:
        """Apply an incremental update; False = desync (unknown node,
        epoch mismatch, or a sequence gap) — the caller must flush and
        request a fresh snapshot."""
        nr = self.nodes.get(node)
        if nr is None or epoch != nr.epoch or seq != nr.seq + 1:
            return False
        for f in remove:
            if f in nr.filters:
                nr.filters.discard(f)
                self._index.unsubscribe(node, f)
        for f in add:
            if f not in nr.filters:
                nr.filters.add(f)
                self._index.subscribe(node, Subscription(filter=f))
        nr.seq = seq
        return True

    def flush_node(self, node: str) -> int:
        """Drop everything a peer advertised (restart with a fresh
        epoch, or a desync awaiting resync). Returns routes dropped."""
        nr = self.nodes.pop(node, None)
        if nr is None:
            return 0
        for f in nr.filters:
            self._index.unsubscribe(node, f)
        return len(nr.filters)

    def nodes_for(self, topic: str) -> frozenset[str]:
        """Direct peers whose advertised filters match ``topic`` — the
        forward target set, memoized per (topic, table version)."""
        version = self._index.sub_version
        hit = self._cache.get(topic, version)
        if hit is not None:
            return hit
        matched = self._index.subscribers(topic)
        result = frozenset(matched.subscriptions)
        self._cache.put(topic, version, result)
        return result

    @property
    def remote_route_count(self) -> int:
        return sum(len(nr.filters) for nr in self.nodes.values())
