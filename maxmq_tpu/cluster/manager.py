"""ClusterManager: the federation brain wired into one broker (ADR 013).

Links N broker processes into one logical broker: outbound
:class:`~.bridge.BridgeLink` per seed peer, an aggregated
:class:`~.routes.RouteTable` answering "which peers need this publish",
and the ``$cluster/*`` inbound dispatch the broker diverts to us from
``process_publish``. Forwarding is route-driven and transitive (a
middle node re-forwards using its own table, so a line topology spans
hops), with three loop-prevention rails proven by the 3-node-cycle
test: an origin-node guard (a node never accepts or forwards its own
publishes back), a hop cap (``cluster_max_hops``), and per-origin
message-id dedup (redundant paths in a cyclic mesh deliver once).

Reserved wire topics (all inside the operator-reserved ``$cluster/#``
namespace; ordinary clients cannot publish ``$`` topics):

* ``$cluster/routes/<node>``          retained compressed snapshot
* ``$cluster/routes/<node>/delta``    incremental add/del, per-link seq
* ``$cluster/sync/<node>``            "resend me your snapshot"
* ``$cluster/fwd/<origin>/<epoch>/<msgid>/<hops>/<flags>/<topic...>``
  forwarded publish: origin node id, origin's boot epoch, per-origin
  monotonic message id, hops traversed, flags = original QoS digit
  (+ ``r`` for retained, + ``t`` when an ADR-017 trace segment
  ``<trace_id>.<t0_ns>`` is inserted before the topic — sent only to
  peers that announced the ``fwd-trace`` capability, so an old binary
  never sees the extra segment), then the original topic verbatim.
  The epoch scopes the dedup window: a restarted origin restarts its
  message ids, and without the epoch every peer would silently drop
  its first window of forwards as replayed duplicates.
* ``$cluster/hello/<node>`` wire-capability announcement (ADR 017),
  sent at link-up; ``$cluster/telemetry/<node>``, ``$cluster/clock/
  <node>[/reply]`` and ``$cluster/trace/<origin>`` are the federated-
  metrics gossip, clock-skew probes and trace span-return legs — all
  handled by :class:`~.telemetry.ClusterTelemetry`.
"""

from __future__ import annotations

import asyncio
import json
import time
from collections import deque

from .. import faults
from ..matching.topics import (filter_matches_topic, parse_share,
                               valid_topic_name)
from ..protocol.codec import FixedHeader, PacketType as PT
from ..protocol.packets import Packet
from .bridge import BRIDGE_ID_PREFIX, BridgeLink
from .membership import Membership, PeerSpec, valid_node_id
from ..filtering.expr import ExprError, compile_expr, decode_payload
from .routes import (RouteTable, RouteWireError, decode_delta,
                     decode_snapshot_preds, encode_delta,
                     encode_snapshot)

DEDUP_WINDOW = 8192     # per-origin forwarded-message-id memory
REHOME_INTENT_TTL_S = 60.0   # how long a deferred takeover-rehome waits
                             # for the winner to advertise the route


class DedupWindow:
    """Bounded per-(origin, boot-epoch) seen-set: admits each message
    id once. The epoch tags which origin incarnation the window
    belongs to — a fresh epoch replaces the window wholesale."""

    __slots__ = ("_seen", "_order", "cap", "epoch")

    def __init__(self, cap: int = DEDUP_WINDOW, epoch: int = 0) -> None:
        self._seen: set[int] = set()
        self._order: deque[int] = deque()
        self.cap = cap
        self.epoch = epoch

    def admit(self, msgid: int) -> bool:
        if msgid in self._seen:
            return False
        self._seen.add(msgid)
        self._order.append(msgid)
        if len(self._order) > self.cap:
            self._seen.discard(self._order.popleft())
        return True


class ClusterManager:
    """Federation state + forwarding policy for one broker process."""

    def __init__(self, broker, node_id: str, peers: list[PeerSpec], *,
                 link_qos: int = 0, max_hops: int = 3,
                 link_byte_budget: int = 4 << 20,
                 keepalive: float = 10.0,
                 backoff_initial_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 epoch: int | None = None, logger=None,
                 session_replication: bool = True,
                 session_sync: str = "batched",
                 session_sync_timeout_ms: int = 750,
                 session_takeover_timeout_ms: int = 750,
                 fwd_durability: str = "coupled",
                 replica_expiry_s: float = 3600.0,
                 share_balance: str = "weighted",
                 trace_propagation: bool = True,
                 trace_return: bool = True,
                 telemetry_interval_s: float = 5.0,
                 telemetry_full_every: int = 10,
                 rtt_deadline_k: float = 4.0,
                 content_routes: bool = False) -> None:
        if not valid_node_id(node_id):
            raise ValueError(f"bad cluster node id {node_id!r}")
        if any(p.node_id == node_id for p in peers):
            raise ValueError("cluster_peers lists this node itself")
        if fwd_durability not in ("coupled", "always", "chained", "off"):
            raise ValueError(f"unknown cluster_fwd_durability "
                             f"{fwd_durability!r} "
                             f"(want coupled/always/chained/off)")
        if share_balance not in ("weighted", "pin"):
            raise ValueError(f"unknown cluster_share_balance "
                             f"{share_balance!r} (want weighted/pin)")
        self.broker = broker
        self.node_id = node_id
        self.link_qos = min(max(link_qos, 0), 1)
        self.max_hops = max_hops
        self.log = logger
        # ADR 018: cross-node publish durability policy — when active,
        # QoS>0 forwards ride QoS1 on the link, strand-park for
        # retry-after-heal, and (when coupled) the publisher's ack
        # waits on the peers' forward PUBACKs
        self.fwd_durability = fwd_durability
        self.fwd_timeout = max(session_sync_timeout_ms, 1) / 1000.0
        # ADR 022: per-link deadline stretch — every liveness/barrier
        # timeout becomes floor + k x measured RTT (the PeerState EWMA
        # the keepalive-driven clock probes maintain), so a healthy
        # 150ms link never flaps as dead while a truly dead link is
        # still detected at the floor
        self.rtt_deadline_k = max(float(rtt_deadline_k), 0.0)
        # ADR 023 stretch: predicate-annotated routes — snapshots carry
        # the local content plane's fully-gated filter->exprs map, and
        # the forwarder reference-evaluates a peer's annotations to
        # skip forwards its content plane would fully mask. Off by
        # default; purely an optimization (fail open on any doubt).
        self.content_routes = content_routes
        self._pred_cache: dict[str, object] = {}
        self.routes = RouteTable(
            node_id, epoch if epoch is not None
            else int(time.time() * 1000))
        self.routes.shares.balance = share_balance
        self._epoch_pinned = epoch is not None
        self.membership = Membership(peers)
        self._link_kw = dict(node_id=node_id, qos=self.link_qos,
                             byte_budget=link_byte_budget,
                             keepalive=keepalive,
                             backoff_initial_s=backoff_initial_s,
                             backoff_max_s=backoff_max_s)
        self.links: dict[str, BridgeLink] = {
            p.node_id: BridgeLink(self, p, **self._link_kw)
            for p in peers}
        self._seen: dict[str, DedupWindow] = {}
        self._next_msg_id = 0
        self._refresh_pending = False
        self._retry_pending = False
        self._started = False
        # federated sessions (ADR 016): replication + takeover +
        # cluster-wide $share, registered as a broker hook so the
        # QoS/subscription/disconnect events feed replication
        self.sessions = None
        if session_replication:
            from .sessions import SessionFederation
            self.sessions = SessionFederation(
                self, sync=session_sync,
                sync_timeout_ms=session_sync_timeout_ms,
                takeover_timeout_ms=session_takeover_timeout_ms,
                replica_expiry_s=replica_expiry_s)
            broker.add_hook(self.sessions)
        # cluster observability plane (ADR 017): telemetry gossip,
        # clock-skew probes, and the trace span-return leg. Always
        # constructed — skew/trace handling have no periodic cost;
        # telemetry_interval_s = 0 disables only the gossip task.
        self.trace_propagation = trace_propagation
        from .telemetry import ClusterTelemetry
        self.telemetry = ClusterTelemetry(
            self, interval_s=telemetry_interval_s,
            full_every=telemetry_full_every, trace_return=trace_return)

        # counters (read tear-free by the metrics scrape thread)
        self.forwards_delivered = 0     # remote publishes fanned out here
        self.loops_dropped = 0          # origin echo + duplicate path
        self.hops_dropped = 0           # onward forward past the cap
        self.forwards_skipped_down = 0  # target peer's link was down
        self.snapshots_applied = 0
        self.deltas_applied = 0
        self.route_desyncs = 0
        self.route_apply_failures = 0
        self.syncs_sent = 0
        self.inbound_rejected = 0       # malformed/spoofed $cluster wire
        self.content_route_skips = 0    # ADR 023: pred-gated forwards
        # ADR 018: fwd-durability barrier + partition-harness health
        self.fwd_barrier_waits = 0      # publisher acks that waited on
                                        # a cross-node forward PUBACK
        self.fwd_barrier_timeouts = 0   # barriers released by timeout
        self.fwd_barrier_degraded = 0   # barriers released without
                                        # full peer forward durability
        self.fwd_restore_errors = 0     # parked-forward journal rows
                                        # that failed to parse at boot
        self.partition_drops_in = 0     # inbound $cluster messages the
                                        # partition site dropped in flight
        # WAN shaping + RTT-adaptive liveness (ADR 022)
        self.shape_drops_in = 0         # inbound $cluster messages the
                                        # shape's loss draw ate in flight
        self.rtt_adaptive_extended = 0  # deadline computations stretched
                                        # past their floor by k x RTT
        self.fwd_parked_rehomed = 0     # parked forwards re-routed off a
                                        # dead owner's link after an
                                        # epoch-fenced takeover moved the
                                        # subscription (closes the ADR-021
                                        # dead-owner blackhole)
        self._rehome_pending = False
        self._pending_rehomes: list = []  # [dead, winner, filters, deadline]
        # chained multi-hop durability (ADR 020): relay-side upstream
        # PUBACKs held for the downstream forward chain
        self.relay_chain_waits = 0      # relayed fwds whose upstream ack
                                        # waited on the downstream chain
        self.relay_chain_timeouts = 0   # relay waits released degraded
                                        # by the bounded timeout
        # sub-keepalive blip detection (ADR 020): heartbeat-gap resyncs
        self.blip_resyncs = 0           # debounced resyncs triggered by
                                        # a peer's blip notice
        self.blips_detected = 0         # hb seq gaps / item deficits
                                        # seen on inbound links
        # relay route-sync gate (ADR 020): a freshly restarted relay
        # can receive an upstream's parked-forward drain BEFORE the
        # downstream peer's route snapshot arrives — it would fan out
        # to nobody, relay nothing onward, and still ack upstream,
        # losing a PUBACKed message forever. Inbound forwards wait
        # (bounded) until every configured peer's first route
        # advertisement landed; a node with fewer than two peers can
        # never relay and is ready immediately.
        self.route_sync_waits = 0       # inbound fwds held for the
                                        # initial route convergence
        self.route_sync_timeouts = 0    # holds released degraded by
                                        # the bounded timeout
        self._route_synced: set[str] = set()
        self._routes_ready = asyncio.Event()
        if len(self.links) < 2:
            self._routes_ready.set()

    # ------------------------------------------------------------------
    # Lifecycle (driven by Broker.serve / Broker.close)
    # ------------------------------------------------------------------

    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        # adopt the broker's PERSISTED monotonic boot epoch (ADR 014;
        # closes the ADR-013 wall-clock limitation: a clock that steps
        # backwards across a restart can no longer make peers swallow
        # this incarnation's routes/messages as stale replays). Runs
        # before any link starts, so no advertisement has carried the
        # constructor's wall-clock fallback yet. An explicit epoch=
        # constructor arg (tests) stays authoritative.
        boot_epoch = getattr(self.broker, "boot_epoch", 0)
        if boot_epoch and not self._epoch_pinned:
            self.routes.epoch = boot_epoch
        # seed the aggregated local set from pre-existing (restored)
        # subscriptions; everything after flows through note_subscribe
        for filt, _cid, _sub, _group in \
                self.broker.topics.all_subscriptions():
            self._note_filter(filt, add=True, refresh=False)
        if self.sessions is not None:
            # after the epoch adoption above and the broker's own
            # restore: the ledger rebuild must see the final boot epoch
            self.sessions.start()
        self._restore_parked_forwards()
        self.telemetry.start()
        for link in self.links.values():
            link.start()

    def _restore_parked_forwards(self) -> None:
        """ADR 018: reload journal-parked forwards (QoS1 forwards a
        partition stranded before this node crashed/restarted) into
        their links' park buffers — drained at each link-up, deduped
        by the receiver's per-(origin, epoch) msgid window."""
        if not self.fwd_park_active:
            return
        hook = getattr(self.broker, "_storage_hook", None)
        if hook is None:
            return
        from .bridge import FWD_BUCKET, PARKED_MAX
        for key, raw in hook.store.all(FWD_BUCKET).items():
            peer, _, _ident = key.partition("|")
            link = self.links.get(peer)
            if link is None:
                hook.store.delete(FWD_BUCKET, key)  # peer left the
                continue                            # seed list
            try:
                d = json.loads(raw)
                topic, payload = str(d["t"]), bytes.fromhex(d["p"])
            except Exception:
                self.fwd_restore_errors += 1
                hook.store.delete(FWD_BUCKET, key)
                continue
            if len(link.parked) < PARKED_MAX \
                    and key not in link._parked_keys:
                link.parked.append((topic, payload, key))
                link._parked_keys.add(key)

    async def close(self) -> None:
        self._started = False
        if self.sessions is not None:
            self.sessions.close()
        self.telemetry.close()
        for link in self.links.values():
            await link.close()

    def add_peer(self, spec: PeerSpec) -> BridgeLink:
        """Dynamically admit a peer beyond the boot seed list (node
        join): registers it in membership and starts its bridge link.
        Existing peers learn the newcomer's routes transitively."""
        from .membership import PeerState
        if spec.node_id == self.node_id or spec.node_id in self.links:
            raise ValueError(f"peer {spec.node_id!r} already present")
        self.membership.peers[spec.node_id] = PeerState(spec=spec)
        link = BridgeLink(self, spec, **self._link_kw)
        self.links[spec.node_id] = link
        if self._started:
            link.start()
        return link

    def is_bridge_client(self, client) -> bool:
        cid = getattr(client, "id", "")
        return (cid.startswith(BRIDGE_ID_PREFIX)
                and cid[len(BRIDGE_ID_PREFIX):] in self.membership.peers)

    @staticmethod
    def bridge_peer(client) -> str:
        """The peer node id behind a recognized bridge client."""
        return client.id[len(BRIDGE_ID_PREFIX):]

    # ------------------------------------------------------------------
    # Local subscription tracking (called by broker/server.py)
    # ------------------------------------------------------------------

    def note_subscribe(self, filt: str) -> None:
        self._note_filter(filt, add=True)

    def note_unsubscribe(self, filt: str) -> None:
        self._note_filter(filt, add=False)

    def _note_filter(self, filt: str, add: bool,
                     refresh: bool = True) -> None:
        group, inner = parse_share(filt)
        filt = inner if group else filt
        if not filt or filt.startswith("$"):
            return      # $-topics are never federated
        if add:
            changed = self.routes.note_local_subscribe(filt)
        else:
            changed = self.routes.note_local_unsubscribe(filt)
        # under content_routes every subscription change may flip a
        # filter's gating (a plain holder joining a gated filter must
        # un-gate it at the peers) even when the aggregated set is
        # unchanged — the refresh pass diffs annotations per link
        if refresh and (changed or self.content_routes):
            self._schedule_refresh()

    def note_content_change(self) -> None:
        """ADR 023 stretch: the content plane's registry changed —
        re-advertise so peers see the fresh gating annotations."""
        if self.content_routes:
            self._schedule_refresh()

    # ------------------------------------------------------------------
    # Route advertisement (split-horizon deltas, snapshot on link-up)
    # ------------------------------------------------------------------

    def _schedule_refresh(self) -> None:
        """Debounced re-advertisement: one pass per loop turn no matter
        how many subscriptions changed in it."""
        if self._refresh_pending or not self._started:
            return
        self._refresh_pending = True
        loop = getattr(self.broker, "loop", None)
        if loop is None:
            self._refresh_pending = False
            return
        loop.call_soon(self._refresh_advertisements)

    def _refresh_advertisements(self) -> None:
        self._refresh_pending = False
        preds_map = self._content_preds()
        for link in self.links.values():
            if not link.connected:
                continue    # the reconnect snapshot will catch it up
            if link.needs_snapshot:
                self._send_snapshot(link)   # unsent snapshot first: a
                continue                    # delta atop it would gap
            desired = self.routes.advertisement_for(link.peer)
            if preds_map is not None:
                pdes = {f: preds_map[f]
                        for f in desired if f in preds_map}
                if pdes != link.advertised_preds:
                    # deltas never carry annotations (ADR 023): any
                    # gating change rides a full snapshot
                    self._send_snapshot(link)
                    continue
            if desired == link.advertised:
                continue
            add = desired - link.advertised
            rem = link.advertised - desired
            ok = link.send_control(
                f"$cluster/routes/{self.node_id}/delta",
                encode_delta(self.node_id, self.routes.epoch,
                             link.route_seq + 1, add, rem))
            if ok:
                link.route_seq += 1
                link.advertised = desired
            else:
                # a delta we couldn't queue would silently desync the
                # peer: fall back to a full snapshot on this link
                self._send_snapshot(link)

    def _content_preds(self) -> dict[str, list[str]] | None:
        """The local content plane's fully-gated filter->exprs map, or
        None when predicate-annotated routes are off (ADR 023). Only
        LOCAL filters ever carry annotations: transitive routes from
        other peers stay un-annotated, so a relay never gates traffic
        on behalf of a node it cannot see into."""
        if not self.content_routes:
            return None
        cp = getattr(self.broker, "content", None)
        if cp is None:
            return None
        try:
            gated = cp.gated_filters()
        except Exception:
            return None     # fail open: plain, annotation-free routes
        if gated:
            # a remote holder of the same filter string rides our
            # transitive advertisement — its subscribers are not
            # gated by OUR predicates, so the filter must stay plain
            for nr in self.routes.nodes.values():
                for f in nr.filters & gated.keys():
                    gated.pop(f, None)
        return gated

    def _send_snapshot(self, link: BridgeLink) -> bool:
        """Send the full advertisement on one link. ``advertised``/
        ``route_seq`` advance ONLY on a successful enqueue — marking a
        never-sent snapshot as delivered would leave the peer
        routeless while we believe it is caught up; failures mark the
        link and retry shortly."""
        desired = self.routes.advertisement_for(link.peer)
        preds_map = self._content_preds()
        pdes = ({f: preds_map[f] for f in desired if f in preds_map}
                if preds_map is not None else None)
        ok = link.send_control(
            f"$cluster/routes/{self.node_id}",
            encode_snapshot(self.node_id, self.routes.epoch,
                            link.route_seq + 1, desired, preds=pdes),
            retain=True)
        if ok:
            link.route_seq += 1
            link.advertised = desired
            link.advertised_preds = pdes
            link.needs_snapshot = False
        else:
            link.needs_snapshot = True
            self._retry_refresh_later()
        return ok

    def _retry_refresh_later(self) -> None:
        """A failed control enqueue (wedged link queue) retries on a
        short delay instead of spinning the loop turn."""
        loop = getattr(self.broker, "loop", None)
        if loop is None or self._retry_pending:
            return
        self._retry_pending = True

        def fire() -> None:
            self._retry_pending = False
            self._refresh_advertisements()

        loop.call_later(0.1, fire)

    def on_link_up(self, link: BridgeLink) -> None:
        self._send_hello(link)
        self._send_snapshot(link)
        if self.sessions is not None:
            self.sessions.on_link_up(link)
        if self.fwd_park_active:
            # ADR 018: retry the forwards the partition stranded —
            # before new traffic piles in behind them
            link.drain_parked()
        self.telemetry.on_link_up(link)

    def on_link_alive(self, link: BridgeLink) -> None:
        """Keepalive ping round-tripped (bridge.py): refresh the
        ADR-017 clock-skew estimate at the keepalive cadence."""
        self.telemetry.on_link_alive(link)

    # ------------------------------------------------------------------
    # RTT-adaptive deadlines (ADR 022)
    # ------------------------------------------------------------------

    def peer_rtt_s(self, peer: str) -> float:
        """The peer's measured round trip (ADR-017 clock-probe EWMA),
        seconds; 0 until the first probe lands. A DEAD peer keeps its
        last estimate — its deadlines stay stretched by the RTT it had,
        which is exactly the bound a judge should honor."""
        st = self.membership.get(peer)
        if st is None or not st.skew_samples:
            return 0.0
        return st.rtt_ns / 1e9

    def max_rtt_s(self) -> float:
        """The slowest measured peer RTT — the stretch for barriers
        that wait on ALL peers at once (fwd/sync/route-sync gates)."""
        return max((st.rtt_ns for st in self.membership.peers.values()
                    if st.skew_samples), default=0.0) / 1e9

    def link_deadline(self, peer: str | None, floor_s: float) -> float:
        """ADR 022: one liveness/barrier deadline, stretched per link —
        ``floor + k x RTT`` (``peer=None`` takes the slowest peer, for
        whole-mesh barriers). At loopback RTT the k-term is ~0 and
        every deadline is exactly its pre-022 floor; on a 150ms link
        the keepalive ping, blip debounce, willfire grace and barrier
        waits all stretch together, so "slow" stops reading as
        "dead"."""
        rtt = self.max_rtt_s() if peer is None else self.peer_rtt_s(peer)
        ext = self.rtt_deadline_k * rtt
        if ext > 0:
            self.rtt_adaptive_extended += 1
        return floor_s + ext

    def _send_hello(self, link: BridgeLink) -> None:
        """Announce wire capabilities (ADR 017 version negotiation).
        An old peer counts the unknown kind as inbound_rejected and
        carries on; a peer that never heard OUR hello sends us plain
        pre-017 envelopes, which we parse fine."""
        from .telemetry import WIRE_CAPS
        link.send_control(f"$cluster/hello/{self.node_id}",
                          json.dumps({"v": 1,
                                      "caps": list(WIRE_CAPS)}).encode())

    def on_link_down(self, link: BridgeLink, reason: str) -> None:
        # routes are KEPT: a flapping link must not churn the mesh's
        # tables; a peer that actually restarted re-announces with a
        # fresh epoch, which flushes its old routes on arrival
        if self.sessions is not None:
            self.sessions.on_link_down(link)
        if self.log is not None:
            self.log.warn("cluster link down", peer=link.peer,
                          reason=reason)

    # ------------------------------------------------------------------
    # Forwarding decision (called from the broker fan-out, sync)
    # ------------------------------------------------------------------

    @property
    def fwd_park_active(self) -> bool:
        """ADR 018: QoS>0 forwards ride QoS1 on the link and park for
        retry-after-heal when stranded (any ``cluster_fwd_durability``
        but ``off``)."""
        return self.fwd_durability != "off"

    @property
    def fwd_coupled(self) -> bool:
        """ADR 018: the publisher's QoS ack additionally waits (bounded)
        on the peers' forward PUBACKs — ``always``/``chained``, or
        ``coupled`` when ``cluster_session_sync=always`` already couples
        acks to peers."""
        if self.fwd_durability in ("always", "chained"):
            return True
        return (self.fwd_durability == "coupled"
                and self.sessions is not None
                and self.sessions.sync == "always")

    @property
    def fwd_chained(self) -> bool:
        """ADR 020: relays extend the fwd-ack chain hop-by-hop — a relay
        PUBACKs its upstream only after its own onward forwards are
        acked or journal-parked, so the publisher's released PUBACK
        covers the whole route (a 3-node line, not just direct peers).
        Each hop's wait is bounded by ``fwd_timeout``."""
        return self.fwd_durability == "chained"

    def maybe_forward(self, packet: Packet) -> None:
        """Forward one locally fanned-out publish to every peer whose
        advertised routes match (retained messages flood so any future
        remote subscriber finds them), once per peer, guarded by the
        origin/hop rails. Under ADR-018 fwd durability QoS>0 publishes
        ride QoS1 on the link (parked when stranded) and their PUBACK
        futures are collected on the packet for the ack barrier. The
        relay-chain future a chained ``_handle_fwd`` planted (ADR 020)
        is settled on EVERY exit — including the no-target and
        hop-capped early returns — or the relay's bounded upstream-ack
        wait would always run to its timeout."""
        try:
            self._forward_targets(packet)
        finally:
            self._settle_relay(packet)

    def _forward_targets(self, packet: Packet) -> None:
        topic = packet.topic
        if topic.startswith("$"):
            return
        origin, epoch, msgid, via, hops = self._fwd_identity(packet)
        if packet.fixed.retain:
            targets = set(self.links)       # flood retained state
        else:
            targets = set(self.routes.nodes_for(topic))
        targets.discard(origin)
        targets.discard(via)
        if (self.content_routes and targets
                and not packet.fixed.retain):
            targets = self._content_gate(targets, topic, packet)
        if not targets:
            return
        if hops >= self.max_hops:
            self.hops_dropped += 1
            # per-origin stage attribution (ADR 015): a hop-capped drop
            # is explained cross-node loss — the macroday harness
            # asserts no loss is counted ONLY by the aggregate
            tracer = getattr(self.broker, "tracer", None)
            if tracer is not None:
                tracer.note_error("bridge", "hop_cap")
            return
        park = self.fwd_park_active and packet.fixed.qos > 0
        qos = 1 if park else min(packet.fixed.qos, self.link_qos)
        collect = [] if park and self.fwd_coupled else None
        flags = f"{qos}" + ("r" if packet.fixed.retain else "")
        base = f"$cluster/fwd/{origin}/{epoch}/{msgid}/{hops + 1}/"
        envelope = base + flags + "/" + topic
        traced_env = self._traced_envelope(packet, base, flags, topic)
        for node in targets:
            self._forward_to(node, envelope, traced_env, packet, qos,
                             collect, park)
        if collect:
            packet._fwd_waits = collect

    def _content_gate(self, targets: set[str], topic: str,
                      packet: Packet) -> set[str]:
        """ADR 023 stretch: drop forward targets whose EVERY matching
        advertised filter carries predicate annotations none of which
        pass this payload — the peer's content plane would mask every
        delivery anyway. Fail open on any doubt (un-annotated filter,
        compile error, eval error): correctness over savings."""
        obj = None
        decoded = False
        keep = set()
        for node in targets:
            exprs = self.routes.pred_gate(node, topic)
            if exprs is None:
                keep.add(node)
                continue
            if not decoded:
                obj = decode_payload(packet.payload)
                decoded = True
            if self._any_pred_passes(exprs, obj):
                keep.add(node)
            else:
                self.content_route_skips += 1
        return keep

    def _any_pred_passes(self, exprs, obj) -> bool:
        for e in exprs:
            pred = self._pred_cache.get(e)
            if pred is None:
                try:
                    pred = compile_expr(e)
                except ExprError:
                    return True     # un-compilable annotation: fail open
                if len(self._pred_cache) > 512:
                    self._pred_cache.clear()
                self._pred_cache[e] = pred
            try:
                if pred.eval_reference(obj):
                    return True
            except Exception:
                return True
        return False

    def _fwd_identity(self, packet: Packet) -> tuple:
        """(origin, epoch, msgid, via, hops) for one forward — local
        publishes mint a fresh per-origin msgid, relayed ones carry
        theirs verbatim."""
        via = getattr(packet, "_cluster_via", None)
        hops = getattr(packet, "_cluster_hops", 0)
        origin = getattr(packet, "_cluster_origin", None)
        if origin is None:
            self._next_msg_id += 1
            return (self.node_id, self.routes.epoch, self._next_msg_id,
                    via, hops)
        return (origin, packet._cluster_epoch, packet._cluster_msgid,
                via, hops)

    def _forward_to(self, node: str, envelope: str,
                    traced_env: str | None, packet: Packet, qos: int,
                    collect: list | None, park: bool) -> None:
        """Enqueue one forward on one peer's link; a down link counts
        the skip and (under fwd durability) still PARKS the copy for
        the heal — the publish's durability at that peer is pending,
        so a coupled barrier counts the degrade."""
        link = self.links.get(node)
        if link is not None and link.connected:
            ok = link.forward(self._env_for(node, envelope, traced_env),
                              packet.payload, qos=qos, collect=collect,
                              park=park)
            if not ok and collect is not None:
                # parked without an ack future (dead-read-loop window,
                # budget refusal): this release lacks that peer's
                # durability — count the degrade the barrier can't see
                self.fwd_barrier_degraded += 1
            return
        self.forwards_skipped_down += 1
        tracer = getattr(self.broker, "tracer", None)
        if tracer is not None:
            tracer.note_error("bridge", "link_down")
        if park and link is not None:
            link.forward(envelope, packet.payload, qos=1, park=True)
            if collect is not None:
                self.fwd_barrier_degraded += 1

    def fwd_barrier(self, loop, packet: Packet):
        """The ADR-018 cross-node durability barrier for one publish:
        a future resolved once every collected forward PUBACK has
        landed, or after ``fwd_timeout`` (degraded + counted — a
        partitioned peer costs latency once, never a wedged publisher).
        ``None`` when the publish forwarded nowhere or everything is
        already acked."""
        waits = packet.__dict__.pop("_fwd_waits", None)
        if not waits:
            return None
        pending = self._fwd_pending(waits)
        if not pending:
            return None
        self.fwd_barrier_waits += 1
        fut = loop.create_future()
        state = {"n": len(pending)}

        def _one(f) -> None:
            if f.cancelled() or f.exception() is not None:
                self.fwd_barrier_degraded += 1
            state["n"] -= 1
            if state["n"] == 0 and not fut.done():
                fut.set_result(None)

        def _timeout() -> None:
            if not fut.done():
                self.fwd_barrier_timeouts += 1
                self.fwd_barrier_degraded += 1
                fut.set_result(None)

        for f in pending:
            f.add_done_callback(_one)
        # ADR 022: a barrier waits on PUBACKs from every forwarded
        # peer, so its timeout stretches with the slowest measured RTT
        loop.call_later(self.link_deadline(None, self.fwd_timeout),
                        _timeout)
        return fut

    def _settle_relay(self, packet: Packet) -> None:
        """ADR 020 (chained durability): resolve the relay-chain future
        ``_handle_fwd`` planted on a relayed publish once this node's
        own onward forwards are durable. No onward targets (or dedup'd/
        hop-capped copies) resolve immediately; otherwise the standard
        ``fwd_barrier`` — bounded by ``fwd_timeout``, degrades counted —
        is chained into it, so the upstream PUBACK releases exactly when
        a local publisher's would."""
        fut = packet.__dict__.pop("_relay_chain", None)
        if fut is None or fut.done():
            return
        barrier = self.fwd_barrier(fut.get_loop(), packet)
        if barrier is None:
            fut.set_result(None)
            return

        def _done(_f) -> None:
            if not fut.done():
                fut.set_result(None)

        barrier.add_done_callback(_done)

    def _fwd_pending(self, waits: list) -> list:
        """Split one publish's forward-ack futures: already-failed ones
        (refused at enqueue -> parked for retry-after-heal) count a
        degrade NOW — that release lacks peer durability even if
        nothing is left to wait on — and the still-pending rest come
        back for the barrier."""
        failed = sum(1 for f in waits if f.done()
                     and (f.cancelled() or f.exception() is not None))
        if failed:
            self.fwd_barrier_degraded += failed
        return [f for f in waits if not f.done()]

    def _env_for(self, node: str, envelope: str,
                 traced_env: str | None) -> str:
        """Capability gate: only peers that announced ``fwd-trace``
        get the traced envelope (old binaries keep the pre-017 wire)."""
        if traced_env is not None and self._peer_has_cap(node,
                                                         "fwd-trace"):
            return traced_env
        return envelope

    def _traced_envelope(self, packet: Packet, base: str, flags: str,
                         topic: str) -> str | None:
        """ADR 017: when this publish rides a sampled trace (local or
        adopted), capability-negotiated peers get a flag bit + trace
        segment — id + t0 in OUR clock frame, re-translated per hop —
        so the whole line shares one correlation id. Zero cost
        untraced."""
        tracer = self.broker.tracer
        if not (self.trace_propagation
                and (tracer.sample_n or tracer.adopted_open)):
            return None
        tr = packet.__dict__.get("_trace")
        if tr is None:
            return None
        return base + flags + "t/" + f"{tr.id}.{tr.start_ns}/" + topic

    def _peer_has_cap(self, node: str, cap: str) -> bool:
        st = self.membership.get(node)
        return st is not None and cap in st.caps

    # ------------------------------------------------------------------
    # Inbound $cluster/* dispatch (from broker.process_publish)
    # ------------------------------------------------------------------

    async def handle_inbound(self, client, packet: Packet) -> None:
        sender = client.id[len(BRIDGE_ID_PREFIX):]
        levels = packet.topic.split("/")
        kind = levels[1] if len(levels) > 1 else ""
        if kind == "hb" and len(levels) == 3:
            # counted OUTSIDE _cluster_rx on both ends: heartbeats
            # audit the data stream, they are not part of it
            self._handle_hb(client, sender, levels, packet)
            return
        if kind == "blip" and len(levels) == 3:
            self._handle_blip(sender, levels)
            return
        # per-connection inbound data count (ADR 020 blip detection):
        # compared against the sender's enqueue count carried on its
        # next heartbeat — a deficit is sub-keepalive in-flight loss
        client._cluster_rx = getattr(client, "_cluster_rx", 0) + 1
        if kind == "fwd" and len(levels) >= 8:
            await self._handle_fwd(client, sender, levels, packet)
        elif kind == "routes" and len(levels) >= 3:
            self._handle_routes(sender, levels, packet)
        elif kind == "sync" and len(levels) == 3:
            self._handle_sync(levels[2])
        elif (kind == "sess" and len(levels) >= 4
                and self.sessions is not None):
            if levels[2] != sender:
                self.inbound_rejected += 1  # spoofed session message
            else:
                await self.sessions.handle_inbound(sender, levels, packet)
        else:
            self._handle_observability(kind, sender, levels, packet)

    def _handle_observability(self, kind: str, sender: str,
                              levels: list[str], packet: Packet) -> None:
        """The ADR-017 plane's control kinds (hello/clock/telemetry/
        trace) — dispatched to ClusterTelemetry; anything else (or an
        unknown future kind) counts as rejected, exactly the behavior
        an old binary shows our new kinds."""
        if kind == "hello" and len(levels) == 3:
            self._handle_hello(sender, levels, packet)
        elif kind == "clock" and len(levels) >= 3:
            if levels[2] != sender:
                self.inbound_rejected += 1  # spoofed probe identity
            else:
                self.telemetry.handle_clock(sender, levels, packet)
        elif kind == "telemetry" and len(levels) == 3:
            self.telemetry.handle_snapshot(sender, levels, packet)
        elif kind == "trace" and len(levels) == 3:
            self.telemetry.handle_trace(sender, levels, packet)
        else:
            self.inbound_rejected += 1

    def _handle_hb(self, client, sender: str, levels: list[str],
                   packet: Packet) -> None:
        """ADR 020 (sub-keepalive blip detection, receive side): one
        per-link heartbeat — monotonic per-connection seq plus the
        sender's cumulative data-item enqueue count. A seq gap (a
        heartbeat itself was blackholed) or an item deficit (data
        enqueued before this heartbeat never arrived on the FIFO
        stream) means the path dropped bytes WITHOUT flapping the link:
        notify the sender over our own outbound link so it resyncs.
        The count re-baselines to the sender's after a detection — only
        NEW loss re-triggers, so a healed blip costs one notice."""
        if levels[2] != sender:
            self.inbound_rejected += 1      # spoofed identity
            return
        try:
            d = json.loads(packet.payload)
            seq, n_sent = int(d["seq"]), int(d["n"])
        except Exception:
            self.inbound_rejected += 1
            return
        rx = getattr(client, "_cluster_rx", 0)
        last_seq = getattr(client, "_hb_seq", 0)
        client._hb_seq = seq
        if seq > last_seq + 1 or rx < n_sent:
            self.blips_detected += 1
            client._cluster_rx = n_sent     # re-baseline
            link = self.links.get(sender)
            if link is not None and link.connected:
                link.send_control(f"$cluster/blip/{self.node_id}", b"",
                                  counted=False)
            if self.log is not None:
                self.log.warn("cluster blip detected", peer=sender,
                              hb_gap=seq - last_seq - 1,
                              item_deficit=max(n_sent - rx, 0))

    def _handle_blip(self, sender: str, levels: list[str]) -> None:
        """ADR 020 (blip detection, send side): the peer saw a gap on
        OUR link to it — some of what we enqueued vanished in flight
        while the connection stayed up, the loss class a keepalive-
        driven flap can never catch. Debounced per link (one resync per
        keepalive window): fail the pending forward PUBACK futures so
        their park-on-failure callbacks journal the copies, re-snapshot
        the routes, resync sessions, and drain the parked forwards —
        the receiver's per-(origin, epoch) dedup keeps it at-most-once."""
        if levels[2] != sender:
            self.inbound_rejected += 1
            return
        link = self.links.get(sender)
        if link is None:
            return
        now = time.monotonic()
        # ADR 022: the debounce window stretches with the measured link
        # RTT — on a 150ms WAN link a resync's own round trips overlap
        # the next keepalive window, and re-triggering mid-resync reads
        # healthy slowness as repeated loss
        if now - link.last_blip_resync < self.link_deadline(
                sender, link.keepalive):
            return      # debounce: one resync per keepalive window
        link.last_blip_resync = now
        self.blip_resyncs += 1
        client = link.client
        if client is not None:
            from ..mqtt_client import MQTTError
            # ONLY the forward PUBACK futures: a blanket sweep would
            # also fail an in-flight PINGRESP future and the keepalive
            # loop's ping await would tear the link down — the exact
            # flap the resync exists to avoid
            for key in [k for k, f in client._acks.items()
                        if k[0] == PT.PUBACK and not f.done()]:
                fut = client._acks.pop(key)
                fut.set_exception(MQTTError("blip resync"))
        link.needs_snapshot = True
        self._refresh_advertisements()
        if self.sessions is not None:
            self.sessions.on_link_up(link)
        if self.fwd_park_active:
            # the failed acks re-park through done-callbacks the
            # event loop runs via call_soon — defer the drain one
            # loop pass so it sees the re-parked copies, not an
            # empty buffer
            asyncio.get_running_loop().call_soon(link.drain_parked)
        if self.log is not None:
            self.log.warn("cluster blip resync", peer=sender)

    def _handle_hello(self, sender: str, levels: list[str],
                      packet: Packet) -> None:
        """ADR-017 capability announcement: record what wire the peer
        can parse (pre-017 peers never send one and get pre-017
        envelopes forever)."""
        if levels[2] != sender:
            self.inbound_rejected += 1      # spoofed identity
            return
        st = self.membership.get(sender)
        if st is None:
            return
        try:
            caps = json.loads(packet.payload).get("caps") or []
            st.caps = frozenset(str(c) for c in caps)
        except Exception:
            self.inbound_rejected += 1

    async def _handle_fwd(self, client, sender: str, levels: list[str],
                          packet: Packet) -> None:
        try:
            origin, epoch = levels[2], int(levels[3])
            msgid, hops, flags = int(levels[4]), int(levels[5]), levels[6]
            # ADR 018: with fwd durability on, the sender upgrades QoS>0
            # forwards to a QoS1 link leg — honor that here even when
            # link_qos is 0, or the local fan-out silently downgrades
            # the durable copy; still capped at 1 (a peer can never
            # smuggle QoS2 wire through the bridge)
            qos_cap = max(self.link_qos, 1) if self.fwd_park_active \
                else self.link_qos
            qos = min(int(flags[0]), qos_cap)
            retain = "r" in flags
        except (ValueError, IndexError):
            self.inbound_rejected += 1
            return
        trace_ctx = None
        ti = 7
        if "t" in flags:
            trace_ctx = self._parse_fwd_trace(levels)
            if trace_ctx is None:
                self.inbound_rejected += 1
                return
            ti = 8
        topic = "/".join(levels[ti:])
        if topic.startswith("$") or not valid_topic_name(topic):
            # a bridge peer must never smuggle $-state overwrites or
            # wildcard "topics" into the local fan-out/retain store
            self.inbound_rejected += 1
            return
        if origin == self.node_id:
            self.loops_dropped += 1     # our own publish came back
            return
        if not self._admit_fwd(origin, epoch, msgid):
            return
        if not self._routes_ready.is_set() and self.fwd_park_active:
            await self._await_route_sync()
        out = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos,
                                       retain=retain),
                     topic=topic, payload=packet.payload,
                     origin=f"$cluster/{origin}", created=time.time())
        out._cluster_origin = origin
        out._cluster_epoch = epoch
        out._cluster_via = sender
        out._cluster_hops = hops
        out._cluster_msgid = msgid
        if retain:
            self.broker.retain_message(client, out)
        self.forwards_delivered += 1
        relay_fut = None
        if self.fwd_chained and packet.fixed.qos > 0:
            # ADR 020: the upstream sent this leg QoS1 and its barrier
            # counts OUR PUBACK — plant the chain future maybe_forward
            # settles once the onward forwards are acked/parked, and
            # hold the upstream ack (bounded) on it below. Dedup'd
            # duplicates returned above already acked immediately, so
            # a cyclic mesh cannot chain waits into a loop.
            relay_fut = asyncio.get_running_loop().create_future()
            out._relay_chain = relay_fut
        tr = self._adopt_trace(sender, origin, trace_ctx, out, hops)
        try:
            # re-enters the normal local fan-out (order-preserving
            # publish pipeline when a matcher is attached) AND
            # maybe_forward for the onward hop
            await self.broker.publish_to_subscribers(out)
        except BaseException:
            # a raising fan-out/enqueue must still settle the adopted
            # trace or tracer.adopted_open leaks and the stamping
            # gates stay open forever (finish is idempotent, so this
            # is safe even if the pipeline consumer got the packet)
            if tr is not None:
                self.broker.tracer.finish(tr)
            raise
        self._finish_adopted(tr)
        if relay_fut is not None:
            await self._await_relay_chain(relay_fut)

    async def _await_route_sync(self) -> None:
        """ADR 020: hold an inbound forward until this node's FIRST
        route convergence — every configured peer advertised once —
        so a relay restarted mid-heal doesn't apply an upstream's
        parked-forward drain against an empty route table (fan out to
        nobody, ack upstream, PUBACKed message gone). Bounded like the
        relay chain itself; a peer that never comes up degrades the
        gate once, permanently, counted — never a wedge."""
        self.route_sync_waits += 1
        try:
            # ADR 022: convergence needs a round trip per peer — the
            # gate stretches with the slowest measured link RTT
            await asyncio.wait_for(self._routes_ready.wait(),
                                   self.link_deadline(
                                       None, self.fwd_timeout * 2))
        except asyncio.TimeoutError:
            self.route_sync_timeouts += 1
            self._routes_ready.set()

    def _note_route_sync(self, node: str) -> None:
        if self._routes_ready.is_set():
            return
        self._route_synced.add(node)
        if self._route_synced >= set(self.links):
            self._routes_ready.set()

    async def _await_relay_chain(self, relay_fut) -> None:
        """ADR 020: hold the upstream PUBACK for this relayed forward
        until the onward chain settles — bounded by ``fwd_timeout`` on
        top of the barrier's own timeout (pipeline mode fans out from
        the consumer task, so the barrier may not even EXIST yet when
        the inbound handler gets here). A timeout releases the ack
        degraded + counted: the upstream's publisher sees bounded
        latency, never a wedge, and the parked/journaled copies keep
        the retry-after-heal promise."""
        self.relay_chain_waits += 1
        try:
            # ADR 022: the onward hop's PUBACK rides the slowest shaped
            # link — stretch by the mesh's max measured RTT
            await asyncio.wait_for(asyncio.shield(relay_fut),
                                   self.link_deadline(
                                       None, self.fwd_timeout * 2))
        except asyncio.TimeoutError:
            self.relay_chain_timeouts += 1
            self.fwd_barrier_degraded += 1

    def _admit_fwd(self, origin: str, epoch: int, msgid: int) -> bool:
        """Epoch-scoped per-origin dedup (ADR 013): a fresh incarnation
        replaces the window wholesale (its message ids restarted, so
        the old window no longer means "already delivered"); stale
        incarnations and redundant mesh paths are dropped + counted."""
        window = self._seen.get(origin)
        if window is None or epoch > window.epoch:
            window = self._seen[origin] = DedupWindow(epoch=epoch)
        elif epoch < window.epoch:
            self.loops_dropped += 1     # stale incarnation replay
            return False
        if not window.admit(msgid):
            self.loops_dropped += 1     # redundant path in the mesh
            return False
        return True

    def _parse_fwd_trace(self, levels: list[str]) -> tuple | None:
        """ADR-017 trace segment "<trace_id>.<t0_ns>" before the
        topic; the flag bit is capability-negotiated, so it only
        arrives from peers that meant it — malformed is rejected (None
        here), never misread as topic levels."""
        try:
            tid_s, t0_s = levels[7].split(".", 1)
            return (int(tid_s), int(t0_s), self.broker.tracer.clock())
        except (ValueError, IndexError):
            return None

    def _adopt_trace(self, sender: str, origin: str, ctx: tuple | None,
                     out: Packet, hops: int):
        """Open the receiving-node child span chain of a cross-node
        trace (ADR 017): origin's id, start backdated to the origin t0
        translated through the per-peer skew estimate, rooted at a
        ``bridge_in`` span. Also stamps the ``mq-trace`` user property
        so v5 subscriber deliveries (and their log records) carry
        ``<origin>:<id>`` — the cross-node grep key. A None ctx (the
        untraced common case) is a no-op."""
        if ctx is None:
            return None
        tracer = self.broker.tracer
        tid, t0, t_in = ctx
        t0_local = t0 - self.telemetry.skew_ns(sender)
        tr = tracer.adopt(origin, tid, out.topic, out.fixed.qos, hops,
                          min(t0_local, t_in))
        tr.span("bridge_in", t_in, tracer.clock())
        out._trace = tr
        out.properties.user_properties.append(
            ("mq-trace", f"{origin}:{tid}"))
        if self.log is not None:
            # the RECEIVING node's delivered-publish record: one grep
            # of trace=<origin>:<id> correlates every node's logs
            self.log.debug("forward delivered", topic=out.topic,
                           origin=origin, hops=hops,
                           trace=f"{origin}:{tid}")
        return tr

    def _finish_adopted(self, tr) -> None:
        """Synchronous fan-out path: the adopted trace is terminal
        once publish_to_subscribers returned; in pipeline mode the
        consumer's _pub_deliver finishes it after the ordered fan-out
        actually ran (finish is idempotent either way)."""
        if tr is not None and (self.broker.matcher is None
                               or self.broker._pub_consumer is None):
            self.broker.tracer.finish(tr)

    def _handle_routes(self, sender: str, levels: list[str],
                       packet: Packet) -> None:
        node = levels[2]
        if node != sender:
            self.inbound_rejected += 1  # spoofed advertisement
            return
        is_delta = len(levels) >= 4 and levels[3] == "delta"
        try:
            faults.fire(faults.CLUSTER_ROUTE_APPLY)
            if is_delta:
                self._apply_delta(node, packet.payload)
            else:
                self._apply_snapshot(node, packet.payload)
        except (faults.InjectedFault, RouteWireError):
            # a failed SNAPSHOT apply must desync too: the sender has
            # already marked this link caught-up, so without a resync
            # request no delta would ever repair the hole
            self.route_apply_failures += 1
            self._desync(node)

    def _apply_snapshot(self, node: str, payload: bytes) -> None:
        wnode, epoch, seq, filters, preds = \
            decode_snapshot_preds(payload)
        if wnode != node:
            self.inbound_rejected += 1
            return
        if not self.content_routes:
            preds = {}      # ADR 023 off: never gate on annotations
        if self.routes.apply_snapshot(node, epoch, seq, filters,
                                      preds=preds):
            self.snapshots_applied += 1
            self._note_route_sync(node)
            self.membership.note_alive(node)
            st = self.membership.get(node)
            if st is not None:
                st.epoch = epoch
            self._retain_observable(node, payload)
            self._schedule_refresh()    # transitive re-advertisement
            self._schedule_rehome()     # moved subs may strand parks

    def _apply_delta(self, node: str, payload: bytes) -> None:
        wnode, epoch, seq, add, rem = decode_delta(payload)
        if wnode != node:
            self.inbound_rejected += 1
            return
        if self.routes.apply_delta(node, epoch, seq, add, rem):
            self.deltas_applied += 1
            self._note_route_sync(node)
            self.membership.note_alive(node)
            self._schedule_refresh()
            self._schedule_rehome()
        else:
            self._desync(node)

    def _desync(self, node: str) -> None:
        """A delta gap/epoch mismatch: flush what we hold for the node
        (stale routes must not forward) and ask it for a fresh
        snapshot over OUR link to it."""
        self.route_desyncs += 1
        self.routes.flush_node(node)
        self._schedule_refresh()
        link = self.links.get(node)
        if link is not None and link.connected:
            if link.send_control(f"$cluster/sync/{self.node_id}", b""):
                self.syncs_sent += 1

    def _handle_sync(self, requester: str) -> None:
        link = self.links.get(requester)
        if link is not None and link.connected:
            self._send_snapshot(link)

    def _retain_observable(self, node: str, payload: bytes) -> None:
        """Keep the latest applied snapshot retained in the local trie
        so operators can inspect cluster state by subscribing to
        ``$cluster/routes/#`` on any node."""
        self.broker.topics.retain(Packet(
            fixed=FixedHeader(type=PT.PUBLISH, retain=True),
            topic=f"$cluster/routes/{node}", payload=payload,
            origin=f"$cluster/{node}", created=time.time()))

    # ------------------------------------------------------------------
    # Parked-forward rehoming (ADR 022, closes the ADR-021 blackhole)
    # ------------------------------------------------------------------
    #
    # ADR 018 parks a stranded QoS1 forward against the link it was
    # ROUTED to — and ADR 021 documented the hole: if that owner dies
    # for good and an epoch-fenced takeover moves the subscription to
    # a surviving node, the parked copies sit pinned to a link that
    # will never come up, so "PUBACKed => delivered after heal" broke
    # across owner death. The takeover is visible to us as a ROUTE
    # CHANGE (the winner re-advertises the subscription), so every
    # applied snapshot/delta schedules one debounced rehome pass:
    # parked forwards on a DOWN link whose inner topic now routes
    # elsewhere are re-forwarded (or re-parked) against a live routed
    # link. The receiver's per-(origin, epoch) msgid dedup keeps the
    # move at-most-once even if the old owner later heals and the
    # journal had both copies.

    def _schedule_rehome(self) -> None:
        if self._rehome_pending or not self.fwd_park_active:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return      # unit tests applying routes outside a loop
        self._rehome_pending = True
        loop.call_soon(self._rehome_parked)

    def _rehome_parked(self) -> None:
        self._rehome_pending = False
        now = time.monotonic()
        still = []
        for intent in self._pending_rehomes:
            dead, winner, filters, deadline = intent
            if now < deadline and not self._try_rehome(dead, winner,
                                                       filters):
                still.append(intent)
        self._pending_rehomes = still
        for link in list(self.links.values()):
            if not link.connected and link.parked:
                self._rehome_from(link)

    def _rehome_from(self, link) -> None:
        """Move the dead link's strays: every parked forward whose
        inner topic no longer routes to that peer goes to the first
        connected link that IS routed (never the envelope origin — it
        already holds the message). Still-routed or unroutable copies
        stay parked; the old owner may yet heal."""
        kept: deque = deque()
        for topic, payload, key in link.parked:
            parsed = self._fwd_inner_topic(topic)
            target = None
            if parsed is not None:
                origin, inner = parsed
                targets = self.routes.nodes_for(inner)
                if link.peer not in targets:
                    target = self._rehome_target(targets,
                                                 {origin, link.peer})
            if target is None:
                kept.append((topic, payload, key))
                continue
            link._parked_keys.discard(key)
            link._journal_delete(key)
            # the target computes its own peer-prefixed journal key
            target.forward(topic, payload, qos=1, park=True)
            self.fwd_parked_rehomed += 1
        link.parked = kept

    def rehome_for_takeover(self, dead: str, winner: str,
                            filters: list[str]) -> None:
        """The precise rehome: an epoch-fenced takeover moved a session
        off ``dead`` (whose link is down) to ``winner`` — every parked
        forward on the dead link whose inner topic matches one of the
        session's filters is re-sent against the winner's link (same
        envelope, so the receiver's per-(origin, epoch) msgid dedup
        keeps the move at-most-once), or re-injected into the local
        fan-out when the winner is THIS node. Non-matching copies stay
        parked — the dead owner may yet heal and its other subscribers
        still deserve them.

        The move is GATED on the winner advertising a matching route:
        a claim lands before the winner's install (its state pull is
        still in flight), and a copy shipped that early would be
        admitted into the winner's dedup window, fanned out to nobody,
        and lost forever. Until the route shows up the intent parks in
        ``_pending_rehomes`` and retries on every applied route change
        (bounded — an intent the winner never backs expires)."""
        if not self.fwd_park_active or not filters:
            return
        link = self.links.get(dead)
        if link is None or link.connected or not link.parked:
            return
        if not self._try_rehome(dead, winner, list(filters)):
            self._pending_rehomes.append(
                [dead, winner, list(filters),
                 time.monotonic() + REHOME_INTENT_TTL_S])

    def _try_rehome(self, dead: str, winner: str,
                    filters: list[str]) -> bool:
        """One rehome attempt; True = nothing left to wait for (done,
        or the parked set no longer holds a matching copy)."""
        link = self.links.get(dead)
        if link is None or link.connected or not link.parked:
            return True
        local = winner == self.node_id
        target = None
        if not local:
            target = self.links.get(winner)
            if target is None or not target.connected:
                return False
        flevels = [f.split("/") for f in filters]
        kept: deque = deque()
        waiting = False
        moved = 0
        for topic, payload, key in link.parked:
            parsed = self._fwd_inner_topic(topic)
            if parsed is None or not any(
                    filter_matches_topic(fl, parsed[1].split("/"),
                                         False) for fl in flevels):
                kept.append((topic, payload, key))
                continue
            if not local and winner not in self.routes.nodes_for(
                    parsed[1]):
                # the winner has not advertised the subscription yet
                kept.append((topic, payload, key))
                waiting = True
                continue
            link._parked_keys.discard(key)
            link._journal_delete(key)
            if target is not None:
                target.forward(topic, payload, qos=1, park=True)
            else:
                # winner is us: local fan-out reaches the freshly
                # installed subscription (QoS1 at-least-once — a local
                # subscriber that already saw the original publish may
                # see one duplicate; the alternative is PUBACKed loss)
                self._reinject_fwd(topic, payload)
            self.fwd_parked_rehomed += 1
            moved += 1
        link.parked = kept
        if moved and self.log is not None:
            self.log.info("parked forwards rehomed", dead=dead,
                          winner=winner, moved=moved,
                          parked_left=len(kept))
        return not waiting

    def _reinject_fwd(self, envelope: str, payload: bytes) -> None:
        """Replay one parked forward into OUR local fan-out, keeping
        its cluster identity (origin/epoch/msgid) so any onward
        forwarding stays dedup-protected at the receivers."""
        levels = envelope.split("/")
        try:
            origin, epoch, msgid = levels[2], int(levels[3]), \
                int(levels[4])
            hops, flags = int(levels[5]), levels[6]
            qos = min(int(flags[0]), max(self.link_qos, 1))
        except (ValueError, IndexError):
            return
        ti = 8 if "t" in flags else 7
        topic = "/".join(levels[ti:])
        out = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos),
                     topic=topic, payload=payload,
                     origin=f"$cluster/{origin}", created=time.time())
        out._cluster_origin = origin
        out._cluster_epoch = epoch
        out._cluster_via = self.node_id
        out._cluster_hops = hops
        out._cluster_msgid = msgid
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        loop.create_task(self.broker.publish_to_subscribers(out))

    def _rehome_target(self, targets, exclude: set):
        for node in sorted(targets):
            if node in exclude:
                continue
            lk = self.links.get(node)
            if lk is not None and lk.connected:
                return lk
        return None

    @staticmethod
    def _fwd_inner_topic(envelope: str) -> tuple[str, str] | None:
        """``$cluster/fwd/<origin>/<epoch>/<msgid>/<hops>/<flags>/
        [trace/]<topic>`` -> (origin, topic); None for anything that
        isn't a well-formed forward envelope."""
        levels = envelope.split("/")
        if len(levels) < 8 or levels[0] != "$cluster" \
                or levels[1] != "fwd":
            return None
        ti = 8 if "t" in levels[6] else 7
        if len(levels) <= ti:
            return None
        return levels[2], "/".join(levels[ti:])

    # ------------------------------------------------------------------
    # Aggregates for metrics / $SYS
    # ------------------------------------------------------------------

    @property
    def shape_deferrals(self) -> int:
        """ADR 022: outbound items the WAN shape held in a deferral
        queue before the writer released them."""
        return sum(lk.shape_deferrals for lk in self.links.values())

    @property
    def forwards_sent(self) -> int:
        return sum(lk.forwards_sent for lk in self.links.values())

    @property
    def forwards_refused(self) -> int:
        return sum(lk.forwards_refused for lk in self.links.values())

    @property
    def forwards_parked(self) -> int:
        return sum(lk.forwards_parked for lk in self.links.values())

    @property
    def fwd_parked_now(self) -> int:
        return sum(len(lk.parked) for lk in self.links.values())

    @property
    def fwd_parked_dropped(self) -> int:
        return sum(lk.parked_dropped for lk in self.links.values())

    @property
    def fwd_parked_resent(self) -> int:
        return sum(lk.parked_resent for lk in self.links.values())

    @property
    def partition_drops_out(self) -> int:
        return sum(lk.partition_drops for lk in self.links.values())

    @property
    def link_flaps(self) -> int:
        return sum(st.flaps for st in self.membership.peers.values())

    @property
    def connect_attempts(self) -> int:
        return sum(lk.connect_attempts for lk in self.links.values())

    @property
    def links_up(self) -> int:
        return sum(1 for lk in self.links.values() if lk.connected)
