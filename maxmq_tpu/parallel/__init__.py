"""Mesh-sharded (cluster-mode) matching: subscriptions partitioned into
per-device NFA shards over a ('data', 'subs') mesh; matched row ids are
reassembled across shards over the ICI."""

from .sharded import ShardedNFAEngine, make_mesh

__all__ = ["ShardedNFAEngine", "make_mesh"]
