"""Mesh-sharded NFA matcher: the cluster mode of the framework.

The reference's cluster design is a Route Table of topic-filter -> broker
IDs with inter-broker PUBLISH forwarding (it exists only as a design doc:
/root/reference/docs/system-design.md:201-231). TPU-native, the whole idea
collapses into sharded evaluation + one gather: partition the
*subscriptions* across the device mesh, compile one (small) NFA per shard,
let every device walk its own NFA over its slice of the publish batch, and
reassemble the per-shard matched row ids. The "route lookup + forward"
becomes moving a few int32 row ids over the ICI.

Mesh axes:
  * ``data`` — data parallelism over the publish batch (each device matches
    a slice of the topics).
  * ``subs`` — the scale axis: subscriptions are partitioned round-robin
    into one NFA per mesh column, so 1M+ subscriptions never need one
    device's HBM. Per-shard tables are padded to identical shapes and
    stacked on a leading axis sharded over 'subs'.

Outputs are per-shard row ids (out_spec P('subs', 'data', None)): the global
result [sp, B, max_rows] stays sharded on device and the gather rides the
ICI lazily when the host fetches it. Row ids are local to their shard; the
host decodes via the matching shard's row_entries table (SubscriberSet
union is shard-order independent).
"""

from __future__ import annotations

import threading
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..matching.engine import NFAEngine, match_batch_body
from ..matching.nfa import NFATables, TableFull, compile_subscriptions
from ..matching.trie import SubscriberSet, TopicIndex


def make_mesh(shape: tuple[int, int] = None, devices=None) -> Mesh:
    """Build a ('data', 'subs') mesh over the available devices.

    Default shape: put everything on 'subs' (the scale axis) until there
    are >=8 devices, then split 2 x N/2.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (2, n // 2) if n >= 8 and n % 2 == 0 else (1, n)
    mesh_devices = np.asarray(devices[: shape[0] * shape[1]]).reshape(shape)
    return Mesh(mesh_devices, axis_names=("data", "subs"))


def compile_shards(subs, n_shards: int, version: int) -> list[NFATables]:
    """Partition a subscription list round-robin and compile one NFA per
    shard, all with a common edge-table size (grown together until every
    shard's edges fit the probe bound)."""
    buckets = [subs[i::n_shards] for i in range(n_shards)]
    vocab: dict[str, int] = {}   # one intern pool => shard-uniform token ids
    probe = [compile_subscriptions(b, version, vocab=vocab) for b in buckets]
    size = max([8] + [t.table_size for t in probe])
    if size == probe[0].table_size and all(
            t.table_size == size for t in probe):
        return probe
    while True:
        try:
            return [compile_subscriptions(b, version, table_size=size,
                                          vocab=vocab) for b in buckets]
        except TableFull:
            size *= 2


def _sharded_match(tables_dev, toks, lengths, dollar, *, width, table_mask,
                   max_rows):
    """Runs INSIDE shard_map: this device's NFA shard (leading axis of
    length 1, squeezed) over the local batch slice."""
    local = tuple(t[0] for t in tables_dev)
    rows, overflow = match_batch_body(
        *local, toks, lengths, dollar,
        width=width, table_mask=table_mask, max_rows=max_rows,
        mesh_axes=("data", "subs"))
    return rows[None], overflow[None]   # re-add the 'subs' axis


class ShardedNFAEngine:
    """NFA matcher sharded over a ('data', 'subs') mesh.

    Equivalent single-device engine: matching.engine.NFAEngine. This class
    trades per-shard decode for an HBM footprint of subscriptions/``subs``
    per device, and batch-throughput scaling of ``data``.
    """

    def __init__(self, index: TopicIndex, mesh: Mesh | None = None,
                 width: int = 32, max_levels: int = 16,
                 max_rows: int = 128) -> None:
        self.index = index
        self.mesh = mesh if mesh is not None else make_mesh()
        self.width = width
        self.max_levels = max_levels
        self.max_rows = max_rows
        self.dp = self.mesh.shape["data"]
        self.sp = self.mesh.shape["subs"]
        # (version, shards, dev_tables, fn): swapped as ONE attribute so a
        # concurrent match_raw always pairs vocab, tables and compiled fn
        self._state = None
        self._refresh_lock = threading.Lock()
        self.matches = 0
        self.fallbacks = 0
        self.refresh(force=True)

    # ------------------------------------------------------------------

    def refresh(self, force: bool = False) -> bool:
        """Re-partition + recompile + re-shard if the index changed."""
        with self._refresh_lock:
            state = self._state
            if (not force and state is not None
                    and state[0] == self.index.version):
                return False
            version = self.index.version
            shards = compile_shards(self.index.all_subscriptions(), self.sp,
                                    version)

            # pad node-indexed arrays to a common node count and stack
            n_nodes = max(t.n_nodes for t in shards)
            node_arrays = ("plus_child", "node_mask", "hash_mask")

            def stack(name):
                outs = []
                for t in shards:
                    a = getattr(t, name)
                    if name in node_arrays and len(a) < n_nodes:
                        a = np.pad(a, (0, n_nodes - len(a)),
                                   constant_values=-1)
                    outs.append(a)
                return np.stack(outs)

            mesh = self.mesh
            by_shard = NamedSharding(mesh, P("subs"))
            dev = tuple(
                jax.device_put(stack(name), by_shard)
                for name in ("hash_node", "hash_tok", "hash_val",
                             "plus_child", "node_mask", "hash_mask"))
            fn = self.build_fn(shards[0].table_size - 1)
            self._state = (version, shards, dev, fn)
            return True

    def build_fn(self, table_mask: int):
        """jit(shard_map) of the match step over the mesh."""
        mesh = self.mesh
        table_specs = tuple(P("subs") for _ in range(6))
        fn = jax.shard_map(
            partial(_sharded_match, width=self.width, table_mask=table_mask,
                    max_rows=self.max_rows),
            mesh=mesh,
            in_specs=(table_specs, P("data"), P("data"), P("data")),
            out_specs=(P("subs", "data", None), P("subs", "data")),
        )
        return jax.jit(fn)

    # ------------------------------------------------------------------

    def match_raw(self, topics: list[str]):
        """Sharded device match. Pads the batch to a multiple of the data
        axis. Returns (rows int32[sp, B, max_rows], overflow bool[sp, B],
        shards) as numpy, batch-trimmed."""
        self.refresh()
        _version, shards, dev, fn = self._state
        batch = len(topics)
        padded = -(-batch // self.dp) * self.dp
        # shards[0].tokenize: identical token ids across shards (toks are
        # replicated over 'subs') — guaranteed by compile_shards assigning
        # ids from a shared intern pass
        toks, lengths, dollar = shards[0].tokenize(
            topics + [""] * (padded - batch), self.max_levels)
        rows, overflow = fn(
            dev, jnp.asarray(toks), jnp.asarray(lengths),
            jnp.asarray(dollar))
        return (np.asarray(rows)[:, :batch], np.asarray(overflow)[:, :batch],
                shards)

    def subscribers_batch(self, topics: list[str]) -> list[SubscriberSet]:
        rows, overflow, shards = self.match_raw(topics)
        out = []
        for i, topic in enumerate(topics):
            self.matches += 1
            if overflow[:, i].any():
                self.fallbacks += 1
                out.append(self.index.subscribers(topic))
                continue
            result = SubscriberSet()
            for s, tables in enumerate(shards):
                NFAEngine.decode(rows[s, i], tables, into=result)
            out.append(result)
        return out

    def subscribers(self, topic: str) -> SubscriberSet:
        return self.subscribers_batch([topic])[0]

    async def subscribers_async(self, topic: str) -> SubscriberSet:
        """Event-loop-friendly match (worker thread, like NFAEngine's)."""
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.subscribers, topic)
