"""Mesh-sharded NFA matcher: the cluster mode of the framework.

The reference's cluster design is a Route Table of topic-filter -> broker
IDs with inter-broker PUBLISH forwarding (it exists only as a design doc:
/root/reference/docs/system-design.md:201-231). TPU-native, the whole idea
collapses into sharded evaluation + one gather: partition the
*subscriptions* across the device mesh, compile one (small) NFA per shard,
let every device walk its own NFA over its slice of the publish batch, and
reassemble the per-shard matched row ids. The "route lookup + forward"
becomes moving a few int32 row ids over the ICI.

Mesh axes:
  * ``data`` — data parallelism over the publish batch (each device matches
    a slice of the topics).
  * ``subs`` — the scale axis: subscriptions are partitioned round-robin
    into one NFA per mesh column, so 1M+ subscriptions never need one
    device's HBM. Per-shard tables are padded to identical shapes and
    stacked on a leading axis sharded over 'subs'.

Outputs are per-shard row ids (out_spec P('subs', 'data', None)): the global
result [sp, B, max_rows] stays sharded on device and the gather rides the
ICI lazily when the host fetches it. Row ids are local to their shard; the
host decodes via the matching shard's row_entries table (SubscriberSet
union is shard-order independent).
"""

from __future__ import annotations

import threading
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                   # newer jax spells it jax.shard_map
    _shard_map = jax.shard_map
except AttributeError:                 # 0.4.x: the experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

from ..matching.engine import NFAEngine, match_batch_body
from ..matching.nfa import NFATables, TableFull, compile_subscriptions
from ..matching.trie import SubscriberSet, TopicIndex, subs_version


def make_mesh(shape: tuple[int, int] = None, devices=None) -> Mesh:
    """Build a ('data', 'subs') mesh over the available devices.

    Default shape: put everything on 'subs' (the scale axis) until there
    are >=8 devices, then split 2 x N/2.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (2, n // 2) if n >= 8 and n % 2 == 0 else (1, n)
    mesh_devices = np.asarray(devices[: shape[0] * shape[1]]).reshape(shape)
    return Mesh(mesh_devices, axis_names=("data", "subs"))


def _pad_and_stack_shards(shards, sp: int) -> tuple:
    """Pad per-shard sig tables to common shapes and stack on 'subs'.

    +1 group column: padding word slots must NOT alias a real group — a
    real group's adjusted signature can (adversarially, the hash seed is
    deterministic) equal the 0xFFFFFFFF poison plane, emitting row ids
    past the shard's row tables. The extra all-zero-coefficient group
    has signature 0 for every topic (never the poison), so padding
    words can never fire."""
    g_real = max(max(len(t.groups), 1) for t in shards)
    g_max = g_real + 1
    g_pad = g_real
    d_max = max(max(t.probe_depth, 1) for t in shards)
    w_max = max(max(int(t.group_words.sum()), 1) for t in shards)

    topo = np.zeros((sp, g_max, d_max), dtype=np.uint32)
    dc = np.zeros((sp, g_max), dtype=np.uint32)
    mind = np.zeros((sp, g_max), dtype=np.int32)
    ish = np.zeros((sp, g_max), dtype=bool)
    wild = np.zeros((sp, g_max), dtype=bool)
    planes = np.full((sp, 32, w_max), 0xFFFFFFFF, dtype=np.uint32)
    grp = np.full((sp, w_max), g_pad, dtype=np.int32)
    for s, t in enumerate(shards):
        g = len(t.groups)
        if g:
            topo[s, :g, :t.topo_coef.shape[1]] = t.topo_coef
            dc[s, :g] = t.depth_coef
            mind[s, :g] = t.min_depth
            ish[s, :g] = t.is_hash
            wild[s, :g] = t.wild_first
        w = int(t.group_words.sum())
        if w:
            planes[s, :, :w] = t.row_sig.reshape(w, 32).T
            grp[s, :w] = np.repeat(
                np.arange(g, dtype=np.int32), t.group_words)
    return (topo, dc, mind, ish, wild, planes, grp), d_max


def _group_by_slice(devices, n_slices) -> list[list]:
    """Group devices by hardware slice_index; a synthetic even split
    when the platform reports one slice but n_slices is forced."""
    groups: dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0) or 0, []).append(d)
    if len(groups) == 1 and n_slices and n_slices > 1:
        per = len(devices) // n_slices
        if per == 0:
            raise ValueError(f"need >= {n_slices} devices for "
                             f"{n_slices} slices, have {len(devices)}")
        groups = {i: devices[i * per:(i + 1) * per]
                  for i in range(n_slices)}
    elif n_slices and n_slices != len(groups):
        raise ValueError(f"n_slices={n_slices} but the platform reports "
                         f"{len(groups)} hardware slice(s)")
    return [groups[k] for k in sorted(groups)]


def make_multislice_mesh(n_slices: int | None = None,
                         shape: tuple[int, int] | None = None,
                         devices=None) -> Mesh:
    """('slice', 'data', 'subs') mesh for multi-slice deployments.

    Devices group by their hardware ``slice_index`` so the 'data'/'subs'
    axes always sit INSIDE a slice (collective-free matching over ICI
    neighbours); the leading 'slice' axis spans the DCN. The sharded
    engines partition subscriptions over ('slice', 'subs') jointly, and
    nothing in the match program communicates across 'slice' — matched
    rows stay slice-local until the host fetch, so the slow inter-slice
    fabric carries only result bytes, never compare traffic (the
    scaling-book recipe: keep collectives on ICI, let DCN carry the
    embarrassingly-parallel axis).

    ``n_slices`` forces a synthetic split when the platform reports a
    single slice (CPU meshes in tests; single-slice dev boxes).
    """
    import warnings

    if devices is None:
        devices = list(jax.devices())
    slices = _group_by_slice(devices, n_slices)
    per = min(len(s) for s in slices)
    if shape is None:
        shape = (1, per)
    dp, sp = shape
    if dp * sp > per:
        raise ValueError(f"per-slice shape {shape} needs {dp * sp} "
                         f"devices; smallest slice has {per}")
    idle = sum(len(s) - dp * sp for s in slices)
    if idle:
        warnings.warn(f"make_multislice_mesh leaves {idle} device(s) "
                      f"idle (unequal slices, or shape {shape} smaller "
                      "than a slice)", stacklevel=2)
    mesh_devices = np.stack([np.asarray(s[: dp * sp]).reshape(dp, sp)
                             for s in slices])
    return Mesh(mesh_devices, axis_names=("slice", "data", "subs"))


def compile_shards(subs, n_shards: int, version: int) -> list[NFATables]:
    """Partition a subscription list round-robin and compile one NFA per
    shard, all with a common edge-table size (grown together until every
    shard's edges fit the probe bound)."""
    buckets = [subs[i::n_shards] for i in range(n_shards)]
    vocab: dict[str, int] = {}   # one intern pool => shard-uniform token ids
    probe = [compile_subscriptions(b, version, vocab=vocab) for b in buckets]
    size = max([8] + [t.table_size for t in probe])
    if size == probe[0].table_size and all(
            t.table_size == size for t in probe):
        return probe
    while True:
        try:
            return [compile_subscriptions(b, version, table_size=size,
                                          vocab=vocab) for b in buckets]
        except TableFull:
            size *= 2


def _sharded_match(tables_dev, toks, lengths, dollar, *, width, table_mask,
                   max_rows):
    """Runs INSIDE shard_map: this device's NFA shard (leading axis of
    length 1, squeezed) over the local batch slice."""
    local = tuple(t[0] for t in tables_dev)
    rows, overflow = match_batch_body(
        *local, toks, lengths, dollar,
        width=width, table_mask=table_mask, max_rows=max_rows,
        mesh_axes=("data", "subs"))
    return rows[None], overflow[None]   # re-add the 'subs' axis


def compile_sig_shards(subs, n_shards: int, version: int,
                       by_client: bool = True):
    """Partition subscriptions BY CLIENT (stable crc32 hash of client id)
    and compile one signature table per shard with a shared token-intern
    pool (uniform token ids across the mesh, so topics are tokenized once
    and replicated over 'subs').

    Client-hash partitioning is the load-bearing choice: every entry of
    one client lives on exactly ONE shard, so per-shard decode results
    are disjoint by construction and the host can CHAIN them per topic
    (ChainedIntents) with no cross-shard merge — the sharded equivalent
    of ADR 007's no-merged-dict rule. IoT corpora carry ~1 subscription
    per client, so balance matches round-robin to within hash noise.
    ``by_client=False`` restores round-robin (the refresh fallback when
    one heavy client's wildcard shapes overflow a bucket's MAX_GROUPS —
    spreading keeps the device path alive at the cost of chaining)."""
    import zlib

    from ..matching.sig import compile_sig_subscriptions

    vocab: dict[str, int] = {}
    if by_client:
        buckets: list[list] = [[] for _ in range(n_shards)]
        for entry in subs:
            cid = entry[1]              # (filter, client_id, sub, group)
            buckets[zlib.crc32(cid.encode()) % n_shards].append(entry)
    else:
        buckets = [subs[i::n_shards] for i in range(n_shards)]
    return [compile_sig_subscriptions(b, version, vocab=vocab)
            for b in buckets]


def _sharded_sig_match(tables_dev, toks, lens_enc, *, sel_blocks, max_rows):
    """Runs INSIDE shard_map: this device's signature-table shard (leading
    axis of length 1, squeezed) over the local batch slice."""
    from ..matching.sig import (fixed_slots_from_words,
                                sig_match_words_gather)

    topo_coef, depth_coef, min_depth, is_hash, wild_first, planes, grp = (
        t[0] for t in tables_dev)
    consts = {"topo_coef": topo_coef, "depth_coef": depth_coef,
              "min_depth": min_depth, "is_hash": is_hash,
              "wild_first": wild_first}
    dollar = lens_enc < 0
    lengths = jnp.abs(lens_enc.astype(jnp.int32))
    too_deep = lengths >= 127
    words = sig_match_words_gather(consts, planes, grp,
                                   toks.astype(jnp.int32), lengths, dollar)
    out = fixed_slots_from_words(words, too_deep, sel_blocks, max_rows,
                                 fmt16=False)
    return out[None]                      # re-add the 'subs' axis


from ..matching.sig import OverlayedEngine


def _shard_pairs(out_s, hr, batch, col, fall):
    """One shard's UNVERIFIED candidate (topic, row) pairs: device slots
    + host-probe rows, with overflowed (trie-served) topics' pairs
    dropped before the C verify."""
    cnt = out_s[:, 0].astype(np.int64)
    cnt = np.where(cnt == 0xF, 0, cnt)          # fall slots replaced later
    mask = col[None, :] < cnt[:, None]
    ti_dev = np.repeat(np.arange(batch), cnt)
    rw_dev = out_s[:, 1:][mask].astype(np.int64)
    offs = getattr(hr, "offsets", None)
    if offs is not None:                        # HostRows CSR
        ti_h = np.repeat(np.arange(batch), np.diff(offs[:batch + 1]))
        rw_h = hr.rows[:offs[batch]].astype(np.int64)
    else:
        ti_h = np.repeat(np.arange(batch), [len(h) for h in hr])
        rw_h = (np.concatenate([np.asarray(h) for h in hr])
                .astype(np.int64) if len(ti_h)
                else np.empty(0, dtype=np.int64))
    ti = np.concatenate([ti_dev, ti_h])
    rw = np.concatenate([rw_dev, rw_h])
    if fall.any():                  # overflowed topics are served by the
        keep = ~fall[ti]            # trie; don't union their pairs
        ti, rw = ti[keep], rw[keep]
    return np.ascontiguousarray(ti), np.ascontiguousarray(rw)


class ChainedIntents:
    """Per-topic cluster-mode delivery result: the per-shard
    DeliveryIntents chained, NOT merged. Valid because subscriptions
    partition by client hash (compile_sig_shards) — one client's entries
    live on exactly one shard, so the chained iteration can never name a
    client twice and no cross-shard per-client merge exists to do.
    Duck-types the ADR-007 consumer surface (__iter__/n/__len__/shared/
    has_client/to_set); shared-group candidate maps MAY span shards (a
    group's members hash apart), so ``shared`` is a lazy outer-merged
    view. Immutable, like every cached match result."""

    __slots__ = ("parts", "_shared", "_set")

    def __init__(self, parts: list) -> None:
        self.parts = parts
        self._shared = None
        self._set = None

    def __iter__(self):
        for p in self.parts:
            yield from p

    @property
    def n(self) -> int:
        return sum(p.n for p in self.parts)

    def __len__(self) -> int:
        return sum(len(p) for p in self.parts)

    @property
    def shared(self) -> dict:
        if self._shared is None:
            merged: dict = {}
            for p in self.parts:
                if len(p) == p.n:        # no shared members on this shard
                    continue
                for key, members in p.shared.items():
                    cur = merged.get(key)
                    if cur is None:
                        merged[key] = members
                    else:                # group spans shards: union view
                        cur = dict(cur)
                        cur.update(members)
                        merged[key] = cur
            self._shared = merged
        return self._shared

    def has_client(self, cid: str) -> bool:
        return any(p.has_client(cid) for p in self.parts)

    def to_set(self) -> SubscriberSet:
        if self._set is None:
            subs: dict = {}
            for cid, sub in self:
                subs[cid] = sub          # disjoint by construction
            self._set = SubscriberSet(subs, dict(self.shared))
        return self._set


class ShardedSigEngine(OverlayedEngine):
    """Signature matcher sharded over a ('data', 'subs') mesh — cluster
    mode of the production `sig` path.

    Subscriptions partition by CLIENT HASH over 'subs'
    (compile_sig_shards — the invariant ChainedIntents' merge-free
    chaining rests on; refresh falls back to round-robin, chaining off,
    if a heavy client overflows a bucket): each device holds one
    shard's group constants + row-signature planes and matches the full
    topic batch slice against them; per-shard fixed match slots come back
    over the ICI and the host unions shard-local decodes (the reference's
    Route-Table-lookup-plus-forward collapsed into one sharded compare +
    gather, docs/system-design.md:201-231).
    """

    def __init__(self, index: TopicIndex, mesh: Mesh | None = None,
                 sel_blocks: int = 8, max_rows: int = 7) -> None:
        if not 1 <= max_rows <= 14:
            # the 4-bit count packing reserves 0xF for overflow
            raise ValueError("max_rows must be in [1, 14]")
        self.index = index
        self.mesh = mesh if mesh is not None else make_mesh()
        self.sel_blocks = sel_blocks
        self.max_rows = max_rows
        self._bind_mesh_axes()
        self._state = None
        self._refresh_lock = threading.Lock()
        self.matches = 0
        self.fallbacks = 0
        self.host_matches = 0     # topics served by the device-free path
        # cluster-mode ADR 007: per-shard native DeliveryIntents chained
        # per topic (client-hash sharding makes chaining merge-free)
        self.emit_intents = False
        self._init_overlay()
        self.refresh(force=True)

    @staticmethod
    def _state_version(state) -> int:
        return state[0]

    def _bind_mesh_axes(self) -> None:
        """Subscriptions partition over ('slice', 'subs') jointly on a
        multi-slice mesh (make_multislice_mesh) and over 'subs' on the
        plain 2-axis mesh; the match program never communicates across
        either axis, so the slice axis may ride the DCN for free."""
        names = self.mesh.axis_names
        self._subs_axes = tuple(a for a in ("slice", "subs") if a in names)
        self.sp = 1
        for a in self._subs_axes:
            self.sp *= self.mesh.shape[a]
        self.dp = self.mesh.shape["data"]

    # ------------------------------------------------------------------

    def refresh(self, force: bool = False) -> bool:
        """Re-partition + recompile + re-shard if the index changed."""
        with self._refresh_lock:
            state = self._state
            if (not force and state is not None
                    and state[0] == subs_version(self.index)):
                return False
            version = subs_version(self.index)
            shards, chain_ok = self._compile_shards(version)
            if shards is None or chain_ok is None:
                # pathological corpus under EITHER partitioning: serve
                # exactly via the CPU trie (as SigEngine.refresh)
                self._state = (version, shards or [], None, None, 0, {},
                               self.dp, False)
                return True

            stacked, d_max = _pad_and_stack_shards(shards, self.sp)
            mesh = self.mesh
            subs_axes = self._subs_axes
            by_shard = NamedSharding(mesh, P(subs_axes))
            dev = tuple(jax.device_put(a, by_shard) for a in stacked)

            fn = jax.jit(_shard_map(
                partial(_sharded_sig_match, sel_blocks=self.sel_blocks,
                        max_rows=self.max_rows),
                mesh=mesh,
                in_specs=(tuple(P(subs_axes) for _ in range(7)),
                          P("data"), P("data")),
                out_specs=P(subs_axes, "data", None),
            ))
            # exact-group coefficients are deterministic by shape, so the
            # union over shards gives ONE esig per topic valid everywhere
            union_exact = {}
            for t in shards:
                union_exact.update(t.host_exact or {})
            # dp and chain_ok ride in the state tuple: a concurrent
            # match must pad with the SAME data-axis factor the compiled
            # fn expects, and chaining must pair atomically with the
            # partitioning that makes it merge-free, even while
            # reshard()/refresh() swap states
            self._state = (version, shards, dev, fn, d_max, union_exact,
                           self.dp, chain_ok)
            return True

    def _compile_shards(self, version: int):
        """Compile per-shard tables: client-hash first (chaining ok);
        round-robin fallback when a heavy client overflows a bucket's
        MAX_GROUPS (spreads shapes across shards, keeping the DEVICE
        path alive at the cost of merge-free chaining); (None, None)
        when even round-robin overflows."""
        from ..matching.sig import MAX_GROUPS

        subs = self.index.all_subscriptions()
        shards = compile_sig_shards(subs, self.sp, version)
        if all(len(t.groups) <= MAX_GROUPS for t in shards):
            return shards, True
        shards = compile_sig_shards(subs, self.sp, version,
                                    by_client=False)
        if all(len(t.groups) <= MAX_GROUPS for t in shards):
            return shards, False
        return None, None

    # ------------------------------------------------------------------

    def prewarm_decode_bases(self, chunk: int = 2048) -> int:
        """Cluster form of SigEngine.prewarm_decode_bases: populate the
        chained-decode anchors for every SHARD's table at a quiescent
        point (called by the boot path and the background refresh via
        getattr). Skipped when the shards compiled via the round-robin
        fallback (chain_ok False, state[7]) — the intents decode never
        runs there, so anchors would be pinned dead weight. Returns
        total chunk calls made."""
        if not self.emit_intents or not self._state:
            return 0
        shards, chain_ok = self._state[1], self._state[7]
        if not shards or not chain_ok:
            return 0
        from ..matching.sig import prewarm_tables
        return sum(prewarm_tables(t, chunk) for t in shards)

    def match_raw(self, topics: list[str]):
        """Sharded device match. Returns (out uint32[sp, B, 1+max_rows],
        hostrows list[sp][B], shards, toks[B, W], lens_enc[B]),
        batch-trimmed; toks/lens_enc feed the per-shard native decode."""
        from ..matching.sig import (host_exact_rows_from_sig,
                                    host_plus_rows, prepare_batch_sig)

        self.refresh_soon()
        (_version, shards, dev, fn, d_max, union_exact, dp,
         _chain_ok) = self._state
        if fn is None:
            raise RuntimeError(
                "device matching disabled for this corpus (> MAX_GROUPS "
                "wildcard shapes in a shard); use subscribers_*, which "
                "fall back to the CPU trie")
        batch = len(topics)
        padded = -(-batch // dp) * dp
        padded_topics = topics + ["\x01pad"] * (padded - batch)
        # shared intern pool => identical tokens for every shard; one host
        # tokenize pass serves every shard's exact + '+'-shape probes
        toks, lens_enc, esig, lengths = prepare_batch_sig(
            shards[0], padded_topics, window=max(d_max, 1),
            host_exact=union_exact)
        out = fn(dev, jnp.asarray(toks), jnp.asarray(lens_enc))
        dollar = lens_enc < 0
        hostrows = []
        for t in shards:
            hr = host_exact_rows_from_sig(t, esig, lengths)
            host_plus_rows(t, toks, lengths, dollar, into=hr)
            hostrows.append(hr)
        return (np.asarray(out)[:, :batch],
                [h[:batch] for h in hostrows], shards,
                toks[:batch], lens_enc[:batch])

    def _trie_all(self, topics: list[str]) -> list[SubscriberSet]:
        self.matches += len(topics)
        self.fallbacks += len(topics)
        return [self.index.subscribers(t) for t in topics]

    def subscribers_batch(self, topics: list[str]) -> list[SubscriberSet]:
        self.refresh_soon()
        if self._state[3] is None:      # pathological corpus: CPU trie
            return self._trie_all(topics)
        try:
            out, hostrows, shards, toks, lens_enc = self.match_raw(topics)
        except RuntimeError:            # state swapped to disabled mid-call
            return self._trie_all(topics)
        overlay = self.overlay_for(shards[0].version)
        if overlay == "resync":
            return self._trie_all(topics)
        if self.emit_intents and overlay is None and self._state[7]:
            chained = self._decode_intents(topics, out, hostrows, shards,
                                           toks, lens_enc)
            if chained is not None:
                return chained
        return self._decode_sets(topics, out, hostrows, shards, overlay)

    def _decode_sets(self, topics, out, hostrows, shards, overlay):
        """Per-topic python union across shards (the set form; also the
        overlay-window path, which needs merge_delta's mutation)."""
        from ..matching.sig import SigEngine

        removed = overlay.removed if overlay else None
        results = []
        for i, topic in enumerate(topics):
            self.matches += 1
            cnt = out[:, i, 0]
            if (cnt == 0xF).any():
                self.fallbacks += 1
                results.append(self.index.subscribers(topic))
                continue
            result = SubscriberSet()
            for s, tables in enumerate(shards):
                SigEngine.decode_rows(topic, out[s, i, 1:1 + int(cnt[s])],
                                      tables, into=result, removed=removed)
                SigEngine.decode_rows(topic, hostrows[s][i], tables,
                                      into=result, removed=removed)
            results.append(SigEngine.merge_delta(topic, result, overlay))
        return results

    def _decode_intents(self, topics, out, hostrows, shards, toks,
                        lens_enc):
        """Cluster-mode ADR 007: one native decode_batch_intents pass PER
        SHARD (verify + union + row-set caching in C against that
        shard's table), then chain the per-shard results per topic —
        client-hash sharding guarantees disjointness. None when any
        shard lacks the native extension (python set path serves)."""
        from ..matching.sig import _compact_dtype, _native_decode

        nds = [_native_decode(t) for t in shards]
        if any(nd is None for nd in nds):
            return None
        batch = len(topics)
        self.matches += batch
        fall = (out[:, :, 0] == 0xF).any(axis=0)
        max_rows = out.shape[2] - 1
        col = np.arange(max_rows)
        per_shard: list = []
        toks = np.ascontiguousarray(toks)
        lens_enc = np.ascontiguousarray(lens_enc)
        for s, (tables, nd) in enumerate(zip(shards, nds)):
            mod, cap = nd
            ti, rw = _shard_pairs(out[s], hostrows[s], batch, col, fall)
            _dt, pad = _compact_dtype(tables)
            per_shard.append(mod.decode_batch_intents(
                cap, toks, toks.dtype.itemsize, int(pad), lens_enc,
                batch, ti, rw))
        results: list = []
        fall_list = fall.tolist()
        for i, topic in enumerate(topics):
            if fall_list[i]:
                self.fallbacks += 1
                results.append(self.index.subscribers(topic))
            else:
                results.append(ChainedIntents([ps[i] for ps in per_shard]))
        return results

    def subscribers_host_batch(self, topics: list[str]
                               ) -> list[SubscriberSet]:
        """Cluster-mode device-free match: one tokenize pass (shared
        intern pool), per-shard exact/'+'/'#' host probes, then the
        same per-shard native decode + merge-free chaining the device
        path uses — no mesh dispatch at all. Serves the batcher's
        low-occupancy bypass when a sharded engine backs the broker,
        exactly like SigEngine.subscribers_host_batch single-node."""
        from ..matching.sig import (_native_hash_probe, _scatter_hits,
                                    host_exact_rows_from_sig,
                                    host_hash_rows, host_plus_rows,
                                    prepare_batch_sig)

        self.refresh_soon()
        state = self._state
        (_version, shards, _dev, fn, d_max, union_exact, _dp,
         _chain_ok) = state
        if fn is None:                  # pathological corpus: CPU trie
            return self._trie_all(topics)
        batch = len(topics)
        toks, lens_enc, esig, lengths = prepare_batch_sig(
            shards[0], topics, window=max(d_max, 1),
            host_exact=union_exact)
        dollar = lens_enc < 0
        over = lengths < 0    # prepare_batch_sig reports overflow as -1
        toks_c = np.ascontiguousarray(toks)
        hostrows = []
        for t in shards:
            hr = host_exact_rows_from_sig(t, esig, lengths)
            host_plus_rows(t, toks, lengths, dollar, into=hr)
            # '#'-probe: the cached C ge-depth probe when built (small
            # batches are this path's whole point), numpy twin otherwise
            hp = _native_hash_probe(t)
            if hp is not None:
                ti_h, rw_h = hp.run(toks_c, lens_enc)
                if len(ti_h):
                    _scatter_hits(hr, [ti_h], [rw_h.astype(np.int64)])
            else:
                host_hash_rows(t, toks, lengths, dollar, into=hr)
            hostrows.append(hr)
        # synthesized zero-count device matrix: every candidate rides
        # the host-rows slot; overflow topics get the 0xF marker so
        # the shared decode paths serve them from the trie
        out = np.zeros((len(shards), batch, 1 + self.max_rows),
                       dtype=np.uint32)
        out[:, over, 0] = 0xF
        overlay = self.overlay_for(shards[0].version)
        if overlay == "resync":
            return self._trie_all(topics)
        # fallback-served topics (overflow now, resync above) are
        # counted under matches/fallbacks, not host matches
        self.host_matches += batch - int(over.sum())
        if self.emit_intents and overlay is None and state[7]:
            chained = self._decode_intents(topics, out, hostrows,
                                           shards, toks, lens_enc)
            if chained is not None:
                return chained
        return self._decode_sets(topics, out, hostrows, shards, overlay)

    def subscribers(self, topic: str) -> SubscriberSet:
        return self.subscribers_batch([topic])[0]

    async def subscribers_async(self, topic: str) -> SubscriberSet:
        """Event-loop-friendly match (worker thread, like NFAEngine's)."""
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.subscribers, topic)

    def reshard(self, mesh: Mesh) -> None:
        """Elastic recovery: re-partition + recompile over a NEW mesh
        (e.g. after losing devices). Matching stays exact throughout —
        callers racing the swap use whichever complete state they hold,
        and the state tuple pairs shards with their compiled fn
        atomically (the reference's cluster design has no live story for
        this; its Route Table rebuild is the moral equivalent,
        docs/system-design.md:201-231)."""
        with self._refresh_lock:
            self.mesh = mesh
            self._bind_mesh_axes()
        self.refresh(force=True)


class ShardedNFAEngine:
    """NFA matcher sharded over a ('data', 'subs') mesh.

    Equivalent single-device engine: matching.engine.NFAEngine. This class
    trades per-shard decode for an HBM footprint of subscriptions/``subs``
    per device, and batch-throughput scaling of ``data``.
    """

    def __init__(self, index: TopicIndex, mesh: Mesh | None = None,
                 width: int = 32, max_levels: int = 16,
                 max_rows: int = 128) -> None:
        self.index = index
        self.mesh = mesh if mesh is not None else make_mesh()
        self.width = width
        self.max_levels = max_levels
        self.max_rows = max_rows
        self.dp = self.mesh.shape["data"]
        self.sp = self.mesh.shape["subs"]
        # (version, shards, dev_tables, fn): swapped as ONE attribute so a
        # concurrent match_raw always pairs vocab, tables and compiled fn
        self._state = None
        self._refresh_lock = threading.Lock()
        self.matches = 0
        self.fallbacks = 0
        self.refresh(force=True)

    # ------------------------------------------------------------------

    def refresh(self, force: bool = False) -> bool:
        """Re-partition + recompile + re-shard if the index changed."""
        with self._refresh_lock:
            state = self._state
            if (not force and state is not None
                    and state[0] == subs_version(self.index)):
                return False
            version = subs_version(self.index)
            shards = compile_shards(self.index.all_subscriptions(), self.sp,
                                    version)

            # pad node-indexed arrays to a common node count and stack
            n_nodes = max(t.n_nodes for t in shards)
            node_arrays = ("plus_child", "node_mask", "hash_mask")

            def stack(name):
                outs = []
                for t in shards:
                    a = getattr(t, name)
                    if name in node_arrays and len(a) < n_nodes:
                        a = np.pad(a, (0, n_nodes - len(a)),
                                   constant_values=-1)
                    outs.append(a)
                return np.stack(outs)

            mesh = self.mesh
            by_shard = NamedSharding(mesh, P("subs"))
            dev = tuple(
                jax.device_put(stack(name), by_shard)
                for name in ("hash_node", "hash_tok", "hash_val",
                             "plus_child", "node_mask", "hash_mask"))
            fn = self.build_fn(shards[0].table_size - 1)
            self._state = (version, shards, dev, fn)
            return True

    def build_fn(self, table_mask: int):
        """jit(shard_map) of the match step over the mesh."""
        mesh = self.mesh
        table_specs = tuple(P("subs") for _ in range(6))
        fn = _shard_map(
            partial(_sharded_match, width=self.width, table_mask=table_mask,
                    max_rows=self.max_rows),
            mesh=mesh,
            in_specs=(table_specs, P("data"), P("data"), P("data")),
            out_specs=(P("subs", "data", None), P("subs", "data")),
        )
        return jax.jit(fn)

    def _compile_shards(self, version: int):
        """Compile per-shard tables: client-hash first (chaining ok);
        round-robin fallback when a heavy client overflows a bucket's
        MAX_GROUPS (spreads shapes across shards, keeping the DEVICE
        path alive at the cost of merge-free chaining); (None, None)
        when even round-robin overflows."""
        from ..matching.sig import MAX_GROUPS

        subs = self.index.all_subscriptions()
        shards = compile_sig_shards(subs, self.sp, version)
        if all(len(t.groups) <= MAX_GROUPS for t in shards):
            return shards, True
        shards = compile_sig_shards(subs, self.sp, version,
                                    by_client=False)
        if all(len(t.groups) <= MAX_GROUPS for t in shards):
            return shards, False
        return None, None

    # ------------------------------------------------------------------

    def match_raw(self, topics: list[str]):
        """Sharded device match. Pads the batch to a multiple of the data
        axis. Returns (rows int32[sp, B, max_rows], overflow bool[sp, B],
        shards) as numpy, batch-trimmed."""
        self.refresh()
        _version, shards, dev, fn = self._state
        batch = len(topics)
        padded = -(-batch // self.dp) * self.dp
        # shards[0].tokenize: identical token ids across shards (toks are
        # replicated over 'subs') — guaranteed by compile_shards assigning
        # ids from a shared intern pass
        toks, lengths, dollar = shards[0].tokenize(
            topics + [""] * (padded - batch), self.max_levels)
        rows, overflow = fn(
            dev, jnp.asarray(toks), jnp.asarray(lengths),
            jnp.asarray(dollar))
        return (np.asarray(rows)[:, :batch], np.asarray(overflow)[:, :batch],
                shards)

    def subscribers_batch(self, topics: list[str]) -> list[SubscriberSet]:
        rows, overflow, shards = self.match_raw(topics)
        out = []
        for i, topic in enumerate(topics):
            self.matches += 1
            if overflow[:, i].any():
                self.fallbacks += 1
                out.append(self.index.subscribers(topic))
                continue
            result = SubscriberSet()
            for s, tables in enumerate(shards):
                NFAEngine.decode(rows[s, i], tables, into=result)
            out.append(result)
        return out

    def subscribers(self, topic: str) -> SubscriberSet:
        return self.subscribers_batch([topic])[0]

    async def subscribers_async(self, topic: str) -> SubscriberSet:
        """Event-loop-friendly match (worker thread, like NFAEngine's)."""
        import asyncio

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self.subscribers, topic)
