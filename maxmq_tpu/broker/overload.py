"""Broker overload-protection ladder state (ADR 012).

ADR 011 made the *matcher* degrade predictably; this module is the same
discipline for the host/network path: byte-accounted outbound queues,
a slow-consumer stall policy, CONNECT admission control, and global
load-shed watermarks. One :class:`OverloadState` per broker aggregates
the queued-byte total across every client's outbound queue and owns the
shed/recover hysteresis; :class:`TokenBucket` gates CONNECT storms per
listener. All counters are plain ints mutated on the asyncio loop
thread and read tear-free from the metrics scrape thread under the GIL
(the same contract as ``sys_info.SysInfo``).
"""

from __future__ import annotations

import time

# labelled per-client drop metric cardinality bound: only the top-N
# offenders are ever exported ($SYS and /metrics both); see ADR 012
TOP_OFFENDERS = 8


class TokenBucket:
    """Rate gate for CONNECT admission: ``rate`` tokens/second with a
    ``burst`` ceiling; an empty bucket refuses the socket instead of
    letting a CONNECT storm queue handshake work unboundedly."""

    __slots__ = ("rate", "burst", "tokens", "_last")

    def __init__(self, rate: float, burst: int = 0) -> None:
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(1.0, self.rate)
        self.tokens = self.burst
        self._last = time.monotonic()

    def allow(self, now: float | None = None) -> bool:
        if self.rate <= 0:
            return True
        if now is None:
            now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class OverloadState:
    """Global byte accounting + watermark hysteresis + ladder counters.

    ``queued_bytes`` sums the wire bytes sitting in every client's
    outbound queue (maintained by ``client.OutboundQueue``). Crossing
    ``broker_byte_budget * overload_high_water`` enters the shedding
    regime (QoS0 fan-out dropped, retained delivery deferred); dropping
    back below ``broker_byte_budget * overload_low_water`` recovers.
    A ``broker_byte_budget`` of 0 disables the watermarks entirely.
    """

    def __init__(self, capabilities) -> None:
        self.caps = capabilities
        self.queued_bytes = 0
        self.shedding = False
        self.sheds = 0              # entries into the shedding regime
        self.recoveries = 0         # exits back below the low-water mark
        self.shed_messages = 0      # QoS0 deliveries dropped while shedding
        self.budget_drops = 0       # deliveries dropped by byte budgets
        self.qos_drops = 0          # QoS>0 sends rolled back (quota+inflight)
        self.deferred_retained = 0  # retained deliveries parked by shedding
        self.connects_refused = 0   # token-bucket socket refusals
        self.half_open_refused = 0  # half-open-handshake cap refusals
        self.stalled_disconnects = 0
        self.disk_full_sheds = 0    # QoS0-irrelevant storage rewrites
                                    # shed by the ENOSPC rung (ADR 024)
        # -- zero-copy fan-out ledger (ADR 019) ------------------------
        # one publish should cost one encode: template_sends counts
        # deliveries assembled from a shared template (wire0 cache hits
        # included), slow_encodes the per-subscriber Packet encodes
        # that remain (hook overrides, oversize fallbacks, resends).
        # shared_bytes/copied_bytes split every delivered wire byte by
        # whether fan-out copied it per subscriber — the bench's
        # bytes-copied-per-publish ledger reads these.
        self.template_builds = 0    # shared templates encoded
        self.template_sends = 0     # deliveries from shared wire
        self.slow_encodes = 0       # per-subscriber full encodes left
        self.shared_bytes = 0       # delivered bytes reused, not copied
        self.copied_bytes = 0       # delivered bytes copied/subscriber
        self.writev_batches = 0     # transport.writelines burst flushes
        self.writev_buffers = 0     # buffers handed to writelines

    # -- byte accounting (called by every OutboundQueue put/get) -------

    def note_put(self, size: int) -> None:
        self.queued_bytes += size
        caps = self.caps
        if (not self.shedding and caps.broker_byte_budget
                and self.queued_bytes
                >= caps.broker_byte_budget * caps.overload_high_water):
            self.shedding = True
            self.sheds += 1

    def note_get(self, size: int) -> None:
        self.queued_bytes -= size
        if self.shedding and self.below_low_water():
            self.shedding = False
            self.recoveries += 1

    def below_low_water(self) -> bool:
        caps = self.caps
        return (not caps.broker_byte_budget
                or self.queued_bytes
                <= caps.broker_byte_budget * caps.overload_low_water)


def top_offenders(clients, n: int = TOP_OFFENDERS) -> list[dict]:
    """The worst slow consumers by dropped deliveries, bounded to ``n``
    entries — the cardinality cap for the labelled per-client metric
    and the ``$SYS/broker/clients/top_dropped`` payload.

    Ranked by the drops a client's OWN backpressure caused (queue/byte
    budget, stalls) — global watermark sheds and global-budget refusals
    land on whatever recipient happens to be addressed and would
    otherwise bury the one slow consumer that triggered them under the
    healthy majority. Per-client shed/global counts stay visible in
    ``drops_by_reason`` and the row's ``dropped_total``."""
    rows = []
    for c in clients:
        owned = (c.dropped_msgs - c.drops_by_reason.get("shed", 0)
                 - c.drops_by_reason.get("global_budget", 0))
        if owned > 0:
            rows.append((owned, c.dropped_bytes, c.dropped_msgs, c.id))
    rows.sort(reverse=True)
    return [{"client": cid, "dropped": owned, "bytes": b,
             "dropped_total": total}
            for owned, b, total, cid in rows[:n]]
