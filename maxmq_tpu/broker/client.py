"""Per-connection client session state and transport loops.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/clients.go (Client,
ClientState, read/write loops, packet-id allocation, inflight resend).
Re-designed around asyncio: one reader task + one writer task per client,
outbound delivery through a bounded asyncio queue.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass

from ..matching.trie import TopicAliases
from ..protocol.codec import PacketType as PT
from ..protocol.packets import Packet, ProtocolError, Subscription, Will, parse_stream
from .inflight import Inflight


@dataclass
class ClientProperties:
    protocol_version: int = 4
    username: bytes = b""
    clean_start: bool = False
    will: Will | None = None
    will_delay: int = 0
    session_expiry: int = 0
    session_expiry_set: bool = False
    receive_maximum: int = 0        # client's stated receive maximum
    topic_alias_maximum: int = 0    # client's stated inbound alias maximum
    maximum_packet_size: int = 0
    request_problem_info: int = 1


class PacketIDExhausted(Exception):
    pass


class Client:
    """One MQTT session (possibly outliving several network connections)."""

    def __init__(self, server, reader: asyncio.StreamReader | None,
                 writer: asyncio.StreamWriter | None, listener_id: str = "",
                 inline: bool = False) -> None:
        self.server = server
        self.reader = reader
        self.writer = writer
        self.listener = listener_id
        self.inline = inline
        self.id = ""
        self.remote = ""
        if writer is not None:
            peer = writer.get_extra_info("peername")
            if peer:
                self.remote = f"{peer[0]}:{peer[1]}" if len(peer) >= 2 else str(peer)

        self.properties = ClientProperties()
        self.subscriptions: dict[str, Subscription] = {}
        self.inflight = Inflight()
        # QoS2 publishes we have PUBRECed but not yet PUBRELed (dedup set)
        self.pubrec_inbound: set[int] = set()
        # outbound QoS packets parked on an exhausted send quota, FIFO;
        # released as acks return quota (see Broker._release_held)
        self.held_pids: deque[int] = deque()
        self.aliases: TopicAliases | None = None
        self.keepalive = 0
        self.requested_keepalive = 0
        self.last_received = time.monotonic()
        self.connected_at = 0.0
        self.disconnected_at = 0.0
        self.taken_over = False
        self.assigned_id = False
        self.stop_cause: ProtocolError | None = None
        self._stopped = asyncio.Event()
        self._packet_id_cursor = 0

        maxq = server.capabilities.maximum_client_writes_pending
        # bytes items are pre-encoded wire (QoS0 fan-out fast path);
        # None is the writer-shutdown sentinel
        self.outbound: asyncio.Queue[Packet | bytes | None] = \
            asyncio.Queue(maxsize=maxq)
        self._writer_task: asyncio.Task | None = None
        self._reader_task: asyncio.Task | None = None

    # ------------------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._stopped.is_set()

    def parse_connect(self, packet: Packet) -> None:
        """Absorb CONNECT fields into session properties."""
        p = self.properties
        p.protocol_version = packet.protocol_version
        p.clean_start = packet.clean_start
        p.username = packet.username
        self.id = packet.client_id
        self.requested_keepalive = packet.keepalive
        self.keepalive = packet.keepalive
        caps_ka = self.server.capabilities.maximum_keepalive
        if caps_ka and (self.keepalive == 0 or self.keepalive > caps_ka):
            # clamp to the operator limit; v5 clients learn the new value
            # via ServerKeepAlive in CONNACK [MQTT-3.1.2-21]
            self.keepalive = caps_ka
        if packet.protocol_version >= 5:
            self._absorb_v5_connect_props(packet.properties)
        caps = self.server.capabilities
        self.inflight = Inflight(
            receive_maximum=caps.receive_maximum,
            send_maximum=p.receive_maximum or caps.receive_maximum)
        self.aliases = TopicAliases(caps.topic_alias_maximum)
        if packet.will is not None:
            w = packet.will
            p.will = w
            p.will_delay = w.properties.will_delay or 0

    def _absorb_v5_connect_props(self, pr) -> None:
        p = self.properties
        p.session_expiry = pr.session_expiry or 0
        p.session_expiry_set = pr.session_expiry is not None
        p.receive_maximum = pr.receive_maximum or 0
        p.topic_alias_maximum = pr.topic_alias_max or 0
        p.maximum_packet_size = pr.maximum_packet_size or 0
        if pr.request_problem_info is not None:
            p.request_problem_info = pr.request_problem_info

    def next_packet_id(self) -> int:
        """Allocate an unused outbound packet id; raises when all 65535 are
        inflight."""
        for _ in range(65535):
            self._packet_id_cursor = (self._packet_id_cursor % 65535) + 1
            if self.inflight.get(self._packet_id_cursor) is None:
                return self._packet_id_cursor
        raise PacketIDExhausted()

    # ------------------------------------------------------------------
    # Transport
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.writer is not None:
            self._writer_task = asyncio.get_running_loop().create_task(
                self._write_loop(), name=f"mq-write-{self.id or id(self)}")

    async def read_loop(self, on_packet, initial: bytearray | None = None
                        ) -> None:
        """Frame the inbound byte stream and dispatch packets until EOF,
        error, or stop. ``on_packet`` is the server's receive entry point.
        ``initial`` seeds the buffer with bytes read past the CONNECT
        packet (a client may pipeline SUBSCRIBE/PUBLISH in the same
        segment)."""
        assert self.reader is not None
        buf = initial if initial is not None else bytearray()
        maxsize = self.server.capabilities.maximum_packet_size
        while not self.closed:
            for fh, body in parse_stream(buf, maxsize):
                self.server.info.packets_received += 1
                packet = Packet.decode(fh, body,
                                       self.properties.protocol_version)
                await on_packet(self, packet)
                if self.closed:
                    return
            try:
                chunk = await self.reader.read(
                    self.server.capabilities.buffer_size)
            except (ConnectionError, asyncio.CancelledError, OSError):
                return
            if not chunk:
                return
            self.server.info.bytes_received += len(chunk)
            self.last_received = time.monotonic()
            buf.extend(chunk)

    async def _write_loop(self) -> None:
        assert self.writer is not None
        get_nowait = self.outbound.get_nowait
        try:
            while True:
                packet = await self.outbound.get()
                # greedy drain: one task wake-up flushes everything queued
                # (one await per BURST, not per packet)
                while packet is not None:
                    if type(packet) is bytes:  # pre-encoded fast path
                        self.writer.write(packet)
                        info = self.server.info
                        info.bytes_sent += len(packet)
                        info.packets_sent += 1
                        if packet[0] >> 4 == PT.PUBLISH:
                            info.messages_sent += 1
                    else:
                        self._write_packet(packet)
                    try:
                        packet = get_nowait()
                    except asyncio.QueueEmpty:
                        break
                else:
                    break                      # drained a None: stop
            await self._drain()
        except (ConnectionError, asyncio.CancelledError, OSError):
            pass

    def _write_packet(self, packet: Packet) -> None:
        packet = self.server.hooks.modify("on_packet_encode", packet, self)
        # oversize outbound packets first shed their optional problem-
        # info properties [MQTT-3.2.2-19/20]; still-oversize ones drop
        # [MQTT-3.1.2-25]
        wire = packet.encode_under(self.properties.maximum_packet_size)
        if wire is None:
            self.server.info.messages_dropped += 1
            return
        assert self.writer is not None
        self.writer.write(wire)
        self.server.info.bytes_sent += len(wire)
        self.server.info.packets_sent += 1
        if packet.type == PT.PUBLISH:
            self.server.info.messages_sent += 1
        self.server.hooks.notify("on_packet_sent", self, packet, len(wire))

    async def _drain(self) -> None:
        if self.writer is not None:
            try:
                await self.writer.drain()
            except (ConnectionError, OSError):
                pass

    def send(self, packet: Packet) -> bool:
        """Enqueue a packet for the writer task; False when the queue is full
        (caller decides whether that drops a message)."""
        if self.closed or self.writer is None:
            return False
        try:
            self.outbound.put_nowait(packet)
            return True
        except asyncio.QueueFull:
            return False

    def send_wire(self, wire: bytes) -> bool:
        """Enqueue pre-encoded bytes (the broker's QoS0 fan-out fast path:
        one encode shared by every subscriber on the same fixed flags)."""
        if self.closed or self.writer is None:
            return False
        try:
            self.outbound.put_nowait(wire)
            return True
        except asyncio.QueueFull:
            return False

    def send_now(self, packet: Packet) -> None:
        """Write synchronously, bypassing the queue (CONNACK, shutdown)."""
        if self.writer is not None:
            self._write_packet(packet)

    async def stop(self, cause: ProtocolError | None = None) -> None:
        """Terminate the network connection (the session may persist)."""
        if self._stopped.is_set():
            return
        self.stop_cause = self.stop_cause or cause
        self._stopped.set()
        self.disconnected_at = time.time()
        if self._writer_task is not None:
            try:
                self.outbound.put_nowait(None)
            except asyncio.QueueFull:
                self._writer_task.cancel()
            try:
                await asyncio.wait_for(self._writer_task, timeout=1.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._writer_task.cancel()
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
        if self._reader_task is not None and self._reader_task is not asyncio.current_task():
            self._reader_task.cancel()

    # ------------------------------------------------------------------

    def resend_inflight(self, force_dup: bool = True) -> int:
        """Queue all unacked messages again (session resume [MQTT-4.4.0-1]).
        Returns the number of packets queued."""
        n = 0
        for p in self.inflight.all():
            q = p.copy()
            if q.type == PT.PUBLISH and force_dup:
                q.fixed.dup = True
            if self.send(q):
                self.server.hooks.notify("on_qos_publish", self, q,
                                         time.time(), 1)
                n += 1
        return n

    def expired(self, now: float, maximum_expiry: int) -> bool:
        """True when a disconnected session has outlived its expiry window."""
        if self.disconnected_at == 0:
            return False
        if self.properties.protocol_version >= 5:
            expiry = self.properties.session_expiry
            if self.properties.session_expiry_set:
                expiry = min(expiry, maximum_expiry) if maximum_expiry else expiry
            else:
                expiry = 0 if self.properties.clean_start else maximum_expiry
        else:
            expiry = 0 if self.properties.clean_start else maximum_expiry
        return now > self.disconnected_at + expiry


class ClientRegistry:
    """Session registry keyed by client id."""

    def __init__(self) -> None:
        self._clients: dict[str, Client] = {}

    def get(self, client_id: str) -> Client | None:
        return self._clients.get(client_id)

    def add(self, client: Client) -> None:
        self._clients[client.id] = client

    def delete(self, client_id: str) -> None:
        self._clients.pop(client_id, None)

    def __len__(self) -> int:
        return len(self._clients)

    def all(self) -> list[Client]:
        return list(self._clients.values())

    def connected(self) -> list[Client]:
        return [c for c in self._clients.values() if not c.closed]
