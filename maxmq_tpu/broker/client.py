"""Per-connection client session state and transport loops.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/clients.go (Client,
ClientState, read/write loops, packet-id allocation, inflight resend).
Re-designed around asyncio: one reader task + one writer task per client,
outbound delivery through a bounded asyncio queue.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass

from .. import faults
from ..matching.trie import TopicAliases
from ..protocol.codec import PacketType as PT
from ..protocol.packets import Packet, ProtocolError, Subscription, Will, parse_stream
from .inflight import Inflight


@dataclass
class ClientProperties:
    protocol_version: int = 4
    username: bytes = b""
    clean_start: bool = False
    will: Will | None = None
    will_delay: int = 0
    session_expiry: int = 0
    session_expiry_set: bool = False
    receive_maximum: int = 0        # client's stated receive maximum
    topic_alias_maximum: int = 0    # client's stated inbound alias maximum
    maximum_packet_size: int = 0
    request_problem_info: int = 1


class PacketIDExhausted(Exception):
    pass


def _estimate_wire(packet: Packet) -> int:
    """Cheap wire-size estimate for byte accounting: exact encoding is
    deferred to the writer task, so the budget ledger uses topic+payload
    plus a flat header/property allowance. The estimate is stored with
    the queued item, so enqueue/dequeue accounting is always symmetric.
    Since ADR 019 converted fan-out to exact-sized wire entries this
    covers only the residual Packet paths (hook-override deliveries,
    resends, retained sends, acks) — the variable-length v5 properties
    are summed in so the watermarks fire on real bytes, not a flat
    allowance an adversarial publisher can hide a kilobyte of user
    properties under."""
    if packet.type == PT.PUBLISH:
        est = 32 + len(packet.topic) + len(packet.payload or b"")
        if packet.protocol_version >= 5:
            pr = packet.properties
            if pr.content_type:
                est += 3 + len(pr.content_type)
            if pr.response_topic:
                est += 3 + len(pr.response_topic)
            if pr.correlation_data:
                est += 3 + len(pr.correlation_data)
            for k, v in pr.user_properties:
                est += 5 + len(k) + len(v)
        return est
    return 32


def _droppable_qos0(item) -> bool:
    """True for queued items the slow-consumer policy may shed: QoS0
    PUBLISH deliveries only — never acks, control packets, QoS>0
    publishes (those park on session rules), or the shutdown sentinel.
    Items are ``bytes`` (pre-encoded wire), ``tuple`` (ADR 019 shared-
    template buffer sequences, first buffer = frame head), a Packet,
    or None."""
    t = type(item)
    if t is bytes:
        return (item[0] >> 4) == PT.PUBLISH and (item[0] & 0x06) == 0
    if t is tuple:
        head = item[0]
        return (head[0] >> 4) == PT.PUBLISH and (head[0] & 0x06) == 0
    return (item is not None and item.type == PT.PUBLISH
            and item.fixed.qos == 0)


class FlushScheduler:
    """Per-loop-iteration getter-wake coalescing (ADR 019). A 1→N
    fan-out enqueues its N deliveries synchronously; completing each
    parked getter future inline schedules N task wake-ups before the
    fan-out loop finishes, and a client hit K times in one iteration
    is scheduled K times. Deferring the completions to one
    ``loop.call_soon`` callback wakes each writer exactly once per
    iteration — after its FULL backlog is queued, so the greedy burst
    sees everything on its first dequeue."""

    __slots__ = ("_pending", "_scheduled", "flushes", "deferred",
                 "coalesced")

    def __init__(self) -> None:
        self._pending: list = []
        self._scheduled = False
        self.flushes = 0        # call_soon flush passes run
        self.deferred = 0       # wakes parked for a flush pass
        self.coalesced = 0      # duplicate wakes absorbed by one park

    def defer(self, q: "OutboundQueue") -> bool:
        """Park one queue's getter wake; False when no loop is running
        (inline/test contexts), letting the caller wake directly."""
        if q._wake_deferred:
            self.coalesced += 1
            return True
        if not self._scheduled:
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                return False
            loop.call_soon(self._flush)
            self._scheduled = True
        q._wake_deferred = True
        self._pending.append(q)
        self.deferred += 1
        return True

    def _flush(self) -> None:
        self._scheduled = False
        pending, self._pending = self._pending, []
        self.flushes += 1
        for q in pending:
            q._wake_deferred = False
            g = q._getter
            if g is not None and not g.done():
                g.set_result(None)


class OutboundQueue:
    """Bounded single-consumer outbound queue with wire-byte accounting
    (ADR 012). Each entry carries the byte size charged at enqueue, so
    the per-client ledger (``self.bytes``) and the broker-global ledger
    (``overload.queued_bytes``) stay exact without re-deriving sizes at
    dequeue. The sole consumer is the client's writer task."""

    def __init__(self, maxsize: int, overload=None,
                 scheduler: FlushScheduler | None = None) -> None:
        self._q: deque = deque()
        self._maxsize = maxsize
        self._getter: asyncio.Future | None = None
        self._overload = overload
        # ADR 019: getter wakes route through the broker's per-loop-
        # iteration flush scheduler when one is attached; direct wake
        # otherwise (inline clients, queues built outside a broker)
        self._scheduler = scheduler
        self._wake_deferred = False
        self.bytes = 0
        # cumulative entry counters (ADR 015): a drain-span watcher
        # registered at enqueue seq S is settled by the first flush
        # whose removal count reaches S — not by whatever flush happens
        # to complete next (which may predate S's delivery entirely)
        self.enqueued = 0
        self.removed = 0

    def qsize(self) -> int:
        return len(self._q)

    def put_nowait(self, item, size: int = 0) -> None:
        if self._maxsize and len(self._q) >= self._maxsize:
            raise asyncio.QueueFull
        self._q.append((item, size))
        self.bytes += size
        self.enqueued += 1
        if self._overload is not None:
            self._overload.note_put(size)
        g = self._getter
        if g is not None and not g.done():
            s = self._scheduler
            if s is None or not s.defer(self):
                g.set_result(None)

    def get_nowait(self):
        if not self._q:
            raise asyncio.QueueEmpty
        item, size = self._q.popleft()
        self._account_out(size)
        self.removed += 1
        return item

    async def get(self):
        while not self._q:
            self._getter = asyncio.get_running_loop().create_future()
            try:
                await self._getter
            finally:
                self._getter = None
        return self.get_nowait()

    def _account_out(self, size: int) -> None:
        self.bytes -= size
        if self._overload is not None:
            self._overload.note_get(size)

    def drop_oldest_qos0(self, need: int) -> tuple[list, int]:
        """Shed the oldest droppable (QoS0 PUBLISH) entries until at
        least ``need`` bytes are freed or none remain; other entries
        keep their order. Returns (dropped items, bytes freed) — the
        items so the caller can fire drop hooks for Packet entries."""
        freed = 0
        dropped: list = []
        kept: deque = deque()
        while self._q and freed < need:
            item, size = self._q.popleft()
            if _droppable_qos0(item):
                freed += size
                dropped.append(item)
                self._account_out(size)
                self.removed += 1
            else:
                kept.append((item, size))
        while kept:
            self._q.appendleft(kept.pop())
        return dropped, freed

    def release_all(self) -> None:
        """Drop everything still queued and settle both byte ledgers
        (client teardown: abandoned bytes must not pin the global
        watermark above the recovery threshold forever)."""
        while self._q:
            _item, size = self._q.popleft()
            self._account_out(size)


class Client:
    """One MQTT session (possibly outliving several network connections)."""

    def __init__(self, server, reader: asyncio.StreamReader | None,
                 writer: asyncio.StreamWriter | None, listener_id: str = "",
                 inline: bool = False) -> None:
        self.server = server
        self.reader = reader
        self.writer = writer
        self.listener = listener_id
        self.inline = inline
        self.id = ""
        self.remote = ""
        if writer is not None:
            peer = writer.get_extra_info("peername")
            if peer:
                self.remote = f"{peer[0]}:{peer[1]}" if len(peer) >= 2 else str(peer)

        self.properties = ClientProperties()
        self.subscriptions: dict[str, Subscription] = {}
        self.inflight = Inflight()
        # QoS2 publishes we have PUBRECed but not yet PUBRELed (dedup set)
        self.pubrec_inbound: set[int] = set()
        # outbound QoS packets parked on an exhausted send quota, FIFO;
        # released as acks return quota (see Broker._release_held)
        self.held_pids: deque[int] = deque()
        # inbound QoS acks awaiting the storage durability barrier
        # (ADR 014), FIFO: [MQTT-4.6.0-2] PUBACK order must match
        # PUBLISH arrival order even when a later publish's barrier
        # clears first (see Broker._ack_publish_durable)
        self.pending_durable_acks: deque = deque()
        self.aliases: TopicAliases | None = None
        self.keepalive = 0
        self.requested_keepalive = 0
        self.last_received = time.monotonic()
        self.connected_at = 0.0
        self.disconnected_at = 0.0
        self.taken_over = False
        self.assigned_id = False
        self.stop_cause: ProtocolError | None = None
        self._stopped = asyncio.Event()
        self._packet_id_cursor = 0

        maxq = server.capabilities.maximum_client_writes_pending
        # bytes items are pre-encoded wire (QoS0 fan-out fast path);
        # tuple items are ADR-019 shared-template buffer sequences;
        # None is the writer-shutdown sentinel. Byte-accounted against
        # the per-client and broker budgets (ADR 012).
        self.outbound = OutboundQueue(
            maxq, overload=getattr(server, "overload", None),
            scheduler=getattr(server, "flush_sched", None))
        self._writer_task: asyncio.Task | None = None
        self._reader_task: asyncio.Task | None = None
        # slow-consumer ledger (ADR 012): writer progress timestamp for
        # the stall detector, the first fatal writer error, and
        # per-client drop accounting surfaced via $SYS + /metrics
        self.write_progress = time.monotonic()
        self.write_error: str | None = None
        self.dropped_msgs = 0
        self.dropped_bytes = 0
        self.drops_by_reason: dict[str, int] = {}
        # ADR 015 drain watchers: (trace, enqueue_ns, enqueue_seq)
        # triples the server registers for sampled deliveries; the
        # writer loop settles each after the first flush that covers
        # its seq (one branch per burst when empty)
        self._drain_traces: list = []
        # ADR 017 QoS2 release-leg stopwatches: pid -> PUBREC-sent ns
        # for SAMPLED inbound QoS2 publishes; popped at PUBREL
        self._qos2_release_t0: dict[int, int] = {}

    # ------------------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._stopped.is_set()

    def parse_connect(self, packet: Packet) -> None:
        """Absorb CONNECT fields into session properties."""
        p = self.properties
        p.protocol_version = packet.protocol_version
        p.clean_start = packet.clean_start
        p.username = packet.username
        self.id = packet.client_id
        self.requested_keepalive = packet.keepalive
        self.keepalive = packet.keepalive
        caps_ka = self.server.capabilities.maximum_keepalive
        if caps_ka and (self.keepalive == 0 or self.keepalive > caps_ka):
            # clamp to the operator limit; v5 clients learn the new value
            # via ServerKeepAlive in CONNACK [MQTT-3.1.2-21]
            self.keepalive = caps_ka
        if packet.protocol_version >= 5:
            self._absorb_v5_connect_props(packet.properties)
        caps = self.server.capabilities
        self.inflight = Inflight(
            receive_maximum=caps.receive_maximum,
            send_maximum=p.receive_maximum or caps.receive_maximum)
        self.aliases = TopicAliases(caps.topic_alias_maximum)
        if packet.will is not None:
            w = packet.will
            p.will = w
            p.will_delay = w.properties.will_delay or 0

    def _absorb_v5_connect_props(self, pr) -> None:
        p = self.properties
        p.session_expiry = pr.session_expiry or 0
        p.session_expiry_set = pr.session_expiry is not None
        p.receive_maximum = pr.receive_maximum or 0
        p.topic_alias_maximum = pr.topic_alias_max or 0
        p.maximum_packet_size = pr.maximum_packet_size or 0
        if pr.request_problem_info is not None:
            p.request_problem_info = pr.request_problem_info

    def next_packet_id(self) -> int:
        """Allocate an unused outbound packet id; raises when all 65535 are
        inflight."""
        for _ in range(65535):
            self._packet_id_cursor = (self._packet_id_cursor % 65535) + 1
            if self.inflight.get(self._packet_id_cursor) is None:
                return self._packet_id_cursor
        raise PacketIDExhausted()

    # ------------------------------------------------------------------
    # Transport
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.writer is not None:
            budget = self.server.capabilities.client_byte_budget
            transport = getattr(self.writer, "transport", None)
            if budget and transport is not None:
                # cap the transport's own buffering so a slow consumer
                # blocks the writer's drain() (and so shows up in the
                # byte-accounted queue + stall detector) instead of
                # hiding inside an unbounded transport buffer
                try:
                    transport.set_write_buffer_limits(
                        high=min(budget, 65536))
                except (AttributeError, RuntimeError):
                    pass
            self.write_progress = time.monotonic()
            self._writer_task = asyncio.get_running_loop().create_task(
                self._write_loop(), name=f"mq-write-{self.id or id(self)}")

    async def read_loop(self, on_packet, initial: bytearray | None = None
                        ) -> None:
        """Frame the inbound byte stream and dispatch packets until EOF,
        error, or stop. ``on_packet`` is the server's receive entry point.
        ``initial`` seeds the buffer with bytes read past the CONNECT
        packet (a client may pipeline SUBSCRIBE/PUBLISH in the same
        segment)."""
        assert self.reader is not None
        buf = initial if initial is not None else bytearray()
        maxsize = self.server.capabilities.maximum_packet_size
        tracer = self.server.tracer
        while not self.closed:
            for fh, body in parse_stream(buf, maxsize):
                self.server.info.packets_received += 1
                if tracer.sample_n and fh.type == PT.PUBLISH:
                    # ADR 015: time the decode; process_publish folds
                    # it into the trace when this publish is sampled
                    t0 = tracer.clock()
                    packet = Packet.decode(
                        fh, body, self.properties.protocol_version)
                    packet._decode_ns = tracer.clock() - t0
                else:
                    packet = Packet.decode(
                        fh, body, self.properties.protocol_version)
                await on_packet(self, packet)
                if self.closed:
                    return
            try:
                chunk = await self.reader.read(
                    self.server.capabilities.buffer_size)
            except (ConnectionError, asyncio.CancelledError, OSError):
                return
            if not chunk:
                return
            self.server.info.bytes_received += len(chunk)
            self.last_received = time.monotonic()
            buf.extend(chunk)

    def _write_fault_delay(self) -> float:
        """0.0 unless a client.write fault applies to this client —
        then the seconds the writer must stall (hang mode). Kept sync
        and gated on any_armed() so the idle-registry production cost
        is one predicate call per written packet; raise-mode faults
        propagate to the write loop as a recorded writer death."""
        if not faults.REGISTRY.any_armed():
            return 0.0
        hit = faults.fire_detail(faults.CLIENT_WRITE, key=self.id)
        return hit[1] if hit is not None and hit[0] == "hang" else 0.0

    # greedy-burst byte cap: past this, the writer drains before
    # dequeuing more, so a wedged consumer keeps its backlog in the
    # ACCOUNTED queue (visible to stall detector + watermarks) instead
    # of de-accounted inside the transport buffer (ADR 012)
    BURST_BYTES = 65536

    def _flush_bufs(self, bufs: list) -> None:
        """Hand one burst's collected wire buffers to the transport in
        a single writev-style call (ADR 019): shared template segments
        are joined once at the socket layer per burst, not copied once
        per subscriber at fan-out. Writer facades without writelines
        (WS / embedder stream shims expose only write) get the burst
        as one joined write — same bytes, one frame."""
        writelines = getattr(self.writer, "writelines", None)
        if writelines is not None:
            writelines(bufs)
        else:
            self.writer.write(b"".join(bufs))
        overload = getattr(self.server, "overload", None)
        if overload is not None:
            overload.writev_batches += 1
            overload.writev_buffers += len(bufs)
        bufs.clear()

    async def _write_loop(self) -> None:
        assert self.writer is not None
        get_nowait = self.outbound.get_nowait
        info = self.server.info
        # wire buffers collected across the burst, flushed through ONE
        # transport.writelines per burst (or before any Packet item,
        # which must encode+write in order)
        bufs: list = []
        try:
            while True:
                packet = await self.outbound.get()
                burst = 0
                # greedy drain: one task wake-up flushes everything queued
                # (one await per BURST, not per packet), bounded in bytes
                while packet is not None:
                    stall = self._write_fault_delay()
                    if stall:
                        # deterministic slow consumer: stall THIS writer
                        # without blocking the loop (tests/bench arm
                        # client.write#<id>; see faults.fire_detail)
                        await asyncio.sleep(stall)
                    t = type(packet)
                    if t is bytes:             # pre-encoded fast path
                        bufs.append(packet)
                        n = len(packet)
                        info.bytes_sent += n
                        info.packets_sent += 1
                        burst += n
                        if packet[0] >> 4 == PT.PUBLISH:
                            info.messages_sent += 1
                    elif t is tuple:           # ADR 019 buffer sequence
                        n = 0
                        for b in packet:
                            n += len(b)
                        bufs.extend(packet)
                        info.bytes_sent += n
                        info.packets_sent += 1
                        burst += n
                        if packet[0][0] >> 4 == PT.PUBLISH:
                            info.messages_sent += 1
                    else:
                        if bufs:               # keep the wire in order
                            self._flush_bufs(bufs)
                        self._write_packet(packet)
                        burst += _estimate_wire(packet)
                    if burst >= self.BURST_BYTES:
                        break
                    try:
                        packet = get_nowait()
                    except asyncio.QueueEmpty:
                        break
                else:
                    break                      # drained a None: stop
                if bufs:
                    self._flush_bufs(bufs)
                await self._flush_burst()
            if bufs:
                self._flush_bufs(bufs)
            await self._drain()
        except asyncio.CancelledError:
            pass
        except (ConnectionError, OSError, faults.InjectedFault) as exc:
            # a dead writer must be visible to the stall detector and
            # stop_cause — not an apparently-healthy idle one
            self.write_error = self.write_error or repr(exc)

    async def _flush_burst(self) -> None:
        """One burst's transport flush. The removed-counter snapshot
        happens BEFORE awaiting: deliveries enqueued while drain() is
        in flight were not carried by this flush, so their ADR-015
        watchers must wait for a later one. drain() is the flow
        control: past the transport high-water mark it blocks until
        the consumer catches up, backpressuring into the
        byte-accounted queue where the stall detector and budgets can
        see it (ADR 012)."""
        self.write_progress = time.monotonic()
        flushed = self.outbound.removed
        await self.writer.drain()
        self.write_progress = time.monotonic()
        if self._drain_traces:
            self._settle_drain_traces(flushed)

    def _write_packet(self, packet: Packet) -> None:
        packet = self.server.hooks.modify("on_packet_encode", packet, self)
        # oversize outbound packets first shed their optional problem-
        # info properties [MQTT-3.2.2-19/20]; still-oversize ones drop
        # [MQTT-3.1.2-25]
        wire = packet.encode_under(self.properties.maximum_packet_size)
        if wire is None:
            self.server.info.messages_dropped += 1
            return
        assert self.writer is not None
        self.writer.write(wire)
        self.server.info.bytes_sent += len(wire)
        self.server.info.packets_sent += 1
        if packet.type == PT.PUBLISH:
            self.server.info.messages_sent += 1
            overload = getattr(self.server, "overload", None)
            if overload is not None:
                # ADR 019 ledger: a Packet entry reaching the writer is
                # a per-subscriber encode the template path didn't cover
                overload.slow_encodes += 1
                overload.copied_bytes += len(wire)
        self.server.hooks.notify("on_packet_sent", self, packet, len(wire))

    async def _drain(self) -> None:
        if self.writer is not None:
            try:
                await self.writer.drain()
            except (ConnectionError, OSError) as exc:
                # swallowed (shutdown path), but recorded: the stall
                # detector and stop_cause must see the dead writer
                self.write_error = self.write_error or repr(exc)

    def _settle_drain_traces(self, flushed: int) -> None:
        """Close the ADR-015 drain watchers whose delivery the flush
        that just completed actually carried — those registered at an
        enqueue seq the writer has dequeued (seq <= ``flushed``).
        Watchers for deliveries still sitting in the outbound queue
        (burst byte-cap leftovers, enqueues racing an in-flight drain)
        keep accruing real latency until their own flush."""
        tracer = self.server.tracer
        now = tracer.clock()
        keep = []
        for tr, t0, seq in self._drain_traces:
            if seq <= flushed:
                tracer.drain_span(tr, self.id, t0, now)
            else:
                keep.append((tr, t0, seq))
        self._drain_traces = keep

    def note_drop(self, reason: str, n: int = 1, size: int = 0) -> None:
        """Per-client drop/stall accounting (ADR 012): what $SYS
        top-offender reporting and the labelled metric read. Also feeds
        the ADR-015 per-stage error counter, so write-path drops show
        up next to the drain-stage latency they explain."""
        self.dropped_msgs += n
        self.dropped_bytes += size
        self.drops_by_reason[reason] = \
            self.drops_by_reason.get(reason, 0) + n
        tracer = getattr(self.server, "tracer", None)
        if tracer is not None:
            tracer.note_error("drain", reason, n)

    def _refuse_publish(self, size: int) -> str | None:
        """Byte-budget admission for one queued PUBLISH delivery: free
        room by shedding this client's oldest queued QoS0 publishes
        first (oldest-first slow-consumer policy), then check the
        global broker budget. Returns the refusal reason for the NEW
        delivery, or None when admitted. The distinction matters for
        attribution: "byte_budget" is THIS client's backpressure,
        "global_budget" is broker-wide pressure some other consumer
        caused — top_offenders only ranks the former."""
        caps = self.server.capabilities
        overload = self.server.overload
        budget = caps.client_byte_budget
        if budget and self.outbound.bytes + size > budget:
            items, freed = self.outbound.drop_oldest_qos0(
                self.outbound.bytes + size - budget)
            if items:
                self.note_drop("byte_budget", len(items), freed)
                overload.budget_drops += len(items)
                self.server.info.messages_dropped += len(items)
                hooks = self.server.hooks
                if hooks.overrides("on_publish_dropped"):
                    for item in items:
                        # pre-encoded wire/buffer-sequence sheds have no
                        # Packet to hand the hook; the counters above
                        # remain authoritative
                        if type(item) not in (bytes, tuple):
                            hooks.notify("on_publish_dropped",
                                         self, item)
            if self.outbound.bytes + size > budget:
                return "byte_budget"
        if (caps.broker_byte_budget
                and overload.queued_bytes + size > caps.broker_byte_budget):
            return "global_budget"
        return None

    def send(self, packet: Packet, *, count_drops: bool = True) -> bool:
        """Enqueue a packet for the writer task; False when the queue or
        byte budget refused it (caller decides whether that drops a
        message). Control packets are exempt from the byte budget —
        they are small, and dropping acks would wedge the protocol.
        ``count_drops=False`` suppresses refusal accounting for callers
        whose refused message is NOT lost (inflight resend: it stays
        parked and lands on a later resume)."""
        if self.closed or self.writer is None:
            return False
        size = _estimate_wire(packet)
        if packet.type == PT.PUBLISH and \
                (reason := self._refuse_publish(size)) is not None:
            if count_drops:
                self.note_drop(reason, 1, size)
                self.server.overload.budget_drops += 1
            return False
        try:
            self.outbound.put_nowait(packet, size)
            return True
        except asyncio.QueueFull:
            if count_drops:
                self.note_drop("queue_full", 1, size)
            return False

    def send_wire(self, wire: bytes) -> bool:
        """Enqueue pre-encoded bytes (the broker's QoS0 fan-out fast path:
        one encode shared by every subscriber on the same fixed flags)."""
        if self.closed or self.writer is None:
            return False
        size = len(wire)
        if (wire[0] >> 4) == PT.PUBLISH and \
                (reason := self._refuse_publish(size)) is not None:
            self.note_drop(reason, 1, size)
            self.server.overload.budget_drops += 1
            return False
        try:
            self.outbound.put_nowait(wire, size)
            return True
        except asyncio.QueueFull:
            self.note_drop("queue_full", 1, size)
            return False

    def send_buffers(self, bufs: tuple, size: int,
                     publish: bool = True) -> bool:
        """Enqueue one ADR-019 buffer-sequence delivery (shared
        template segments + a per-subscriber head) with its EXACT wire
        size — the writer hands the buffers to transport.writelines
        unchanged, so enqueue accounting equals socket bytes. Refusal
        accounting mirrors send_wire: one refusal, one reason, one
        budget_drops increment, on both fast and slow paths."""
        if self.closed or self.writer is None:
            return False
        if publish and (reason := self._refuse_publish(size)) is not None:
            self.note_drop(reason, 1, size)
            self.server.overload.budget_drops += 1
            return False
        try:
            self.outbound.put_nowait(bufs, size)
            return True
        except asyncio.QueueFull:
            self.note_drop("queue_full", 1, size)
            return False

    def send_now(self, packet: Packet) -> None:
        """Write synchronously, bypassing the queue (CONNACK, shutdown)."""
        if self.writer is not None:
            self._write_packet(packet)

    async def stop(self, cause: ProtocolError | None = None) -> None:
        """Terminate the network connection (the session may persist)."""
        if self._stopped.is_set():
            return
        self.stop_cause = self.stop_cause or cause
        self._stopped.set()
        self.disconnected_at = time.time()
        if self._writer_task is not None:
            try:
                self.outbound.put_nowait(None)
            except asyncio.QueueFull:
                self._writer_task.cancel()
            try:
                await asyncio.wait_for(self._writer_task, timeout=1.0)
            except (asyncio.TimeoutError, asyncio.CancelledError):
                self._writer_task.cancel()
        # settle the byte ledgers for anything never written: abandoned
        # bytes must not pin the global watermark in shedding forever
        self.outbound.release_all()
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
        if self._reader_task is not None and self._reader_task is not asyncio.current_task():
            self._reader_task.cancel()

    # ------------------------------------------------------------------

    def resend_inflight(self, force_dup: bool = True) -> int:
        """Queue all unacked messages again (session resume [MQTT-4.4.0-1]).
        Returns the number of packets queued."""
        n = 0
        held = set(self.held_pids)
        for p in self.inflight.all():
            if p.packet_id in held:
                # held-but-unsent (ADR 018): was never on the wire, so
                # it is not a resend — _release_held sends it fresh
                # (no DUP) as send quota opens
                continue
            q = p.copy()
            if q.type == PT.PUBLISH and force_dup:
                q.fixed.dup = True
            # a refused resend is parked, not dropped (it stays in
            # inflight for the next resume): keep it off the drop books
            if self.send(q, count_drops=False):
                self.server.hooks.notify("on_qos_publish", self, q,
                                         time.time(), 1)
                n += 1
        return n

    def expired(self, now: float, maximum_expiry: int) -> bool:
        """True when a disconnected session has outlived its expiry window."""
        if self.disconnected_at == 0:
            return False
        if self.properties.protocol_version >= 5:
            expiry = self.properties.session_expiry
            if self.properties.session_expiry_set:
                expiry = min(expiry, maximum_expiry) if maximum_expiry else expiry
            else:
                expiry = 0 if self.properties.clean_start else maximum_expiry
        else:
            expiry = 0 if self.properties.clean_start else maximum_expiry
        return now > self.disconnected_at + expiry


class ClientRegistry:
    """Session registry keyed by client id."""

    def __init__(self) -> None:
        self._clients: dict[str, Client] = {}

    def get(self, client_id: str) -> Client | None:
        return self._clients.get(client_id)

    def add(self, client: Client) -> None:
        self._clients[client.id] = client

    def delete(self, client_id: str) -> None:
        self._clients.pop(client_id, None)

    def __len__(self) -> int:
        return len(self._clients)

    def all(self) -> list[Client]:
        return list(self._clients.values())

    def connected(self) -> list[Client]:
        return [c for c in self._clients.values() if not c.closed]
