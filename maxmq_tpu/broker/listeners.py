"""Network listeners: TCP (optionally TLS), Unix socket, in-memory mock, and
a WebSocket adapter (RFC 6455 server handshake + binary frames).

Parity surface: vendor/github.com/mochi-co/mqtt/v2/listeners/ in the
reference (Listener interface + registry, tcp/unix/ws/mock).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import ssl as ssl_module
import struct


class Listener:
    """A bound endpoint that accepts connections and hands (reader, writer)
    pairs to the broker's establish callback."""

    def __init__(self, id_: str, address: str) -> None:
        self.id = id_
        self.address = address
        self._server: asyncio.AbstractServer | None = None
        self._establish = None
        # per-listener CONNECT admission gate (ADR 012): the broker
        # installs a TokenBucket here when connect_rate is configured;
        # an exhausted bucket refuses the socket before handshake work
        self.gate = None

    @property
    def protocol(self) -> str:
        raise NotImplementedError

    async def serve(self, establish) -> None:
        """Bind and start accepting; ``establish(listener_id, reader, writer)``
        is awaited per connection."""
        raise NotImplementedError

    def stop_accepting(self) -> None:
        """Stop accepting new connections (non-blocking)."""
        if self._server is not None:
            self._server.close()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            # wait_closed() blocks until every handler coroutine finishes;
            # the broker disconnects clients first, so bound the wait.
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=2.0)
            except asyncio.TimeoutError:
                pass
            self._server = None


class TCPListener(Listener):
    def __init__(self, id_: str, address: str,
                 tls: ssl_module.SSLContext | None = None,
                 reuse_port: bool = False) -> None:
        super().__init__(id_, address)
        self.tls = tls
        # SO_REUSEPORT: the delivery-worker pool binds N processes to
        # one port and lets the kernel shard accepts (ADR 005)
        self.reuse_port = reuse_port

    @property
    def protocol(self) -> str:
        return "tls" if self.tls else "tcp"

    async def serve(self, establish) -> None:
        host, _, port = self.address.rpartition(":")
        self._establish = establish

        async def handler(reader, writer):
            await establish(self.id, reader, writer)

        self._server = await asyncio.start_server(
            handler, host or "0.0.0.0", int(port), ssl=self.tls,
            reuse_port=self.reuse_port or None)


class UnixListener(Listener):
    @property
    def protocol(self) -> str:
        return "unix"

    async def serve(self, establish) -> None:
        async def handler(reader, writer):
            await establish(self.id, reader, writer)

        self._server = await asyncio.start_unix_server(handler, path=self.address)


class SocketListener(Listener):
    """Serve an externally created, already-bound socket — the analog
    of the reference's bring-your-own net.Listener (listeners/net.go):
    callers doing their own bind dance (fd passing, systemd socket
    activation, exotic socket options) hand the socket over and the
    broker just accepts on it."""

    def __init__(self, id_: str, sock) -> None:
        try:
            addr = sock.getsockname()
            address = (addr if isinstance(addr, str)
                       else f"{addr[0]}:{addr[1]}")
        except OSError:
            address = "?"
        super().__init__(id_, address)
        self.sock = sock

    @property
    def protocol(self) -> str:
        return "sock"

    async def serve(self, establish) -> None:
        async def handler(reader, writer):
            await establish(self.id, reader, writer)

        self._server = await asyncio.start_server(handler, sock=self.sock)


class MockListener(Listener):
    """In-process listener for tests: ``connect()`` returns the client-side
    (reader, writer) of a paired in-memory stream."""

    def __init__(self, id_: str = "mock", address: str = "mock://") -> None:
        super().__init__(id_, address)
        self.serving = asyncio.Event()

    @property
    def protocol(self) -> str:
        return "mock"

    async def serve(self, establish) -> None:
        self._establish = establish
        self.serving.set()

    async def connect(self):
        assert self._establish is not None, "listener not serving"
        c2s_r = asyncio.StreamReader()
        s2c_r = asyncio.StreamReader()
        server_writer = _QueueWriter(s2c_r)
        client_writer = _QueueWriter(c2s_r)
        asyncio.get_running_loop().create_task(
            self._establish(self.id, c2s_r, server_writer))
        return s2c_r, client_writer

    async def close(self) -> None:
        self.serving.clear()


class _QueueWriter:
    """Duck-typed StreamWriter feeding a paired StreamReader directly."""

    def __init__(self, peer_reader: asyncio.StreamReader) -> None:
        self._peer = peer_reader
        self._closed = False

    def write(self, data: bytes) -> None:
        if not self._closed:
            self._peer.feed_data(data)

    async def drain(self) -> None:
        pass

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._peer.feed_eof()

    async def wait_closed(self) -> None:
        pass

    def is_closing(self) -> bool:
        return self._closed

    def get_extra_info(self, name, default=None):
        return default


# ---------------------------------------------------------------------------
# WebSocket (MQTT-over-WS, binary frames, subprotocol "mqtt")
# ---------------------------------------------------------------------------

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class WSListener(Listener):
    """MQTT over WebSocket: performs the RFC 6455 server handshake, then
    bridges binary frames to the broker as a plain byte stream."""

    def __init__(self, id_: str, address: str,
                 tls: ssl_module.SSLContext | None = None,
                 reuse_port: bool = False) -> None:
        super().__init__(id_, address)
        self.tls = tls
        self.reuse_port = reuse_port   # worker-pool accept sharding

    @property
    def protocol(self) -> str:
        return "ws"

    async def serve(self, establish) -> None:
        host, _, port = self.address.rpartition(":")

        async def handler(reader, writer):
            try:
                key = await self._handshake(reader, writer)
            except (ValueError, ConnectionError, asyncio.IncompleteReadError):
                writer.close()
                return
            if key is None:
                writer.close()
                return
            bridged_reader = asyncio.StreamReader()
            ws_writer = _WSWriter(writer)
            pump = asyncio.get_running_loop().create_task(
                self._pump_frames(reader, bridged_reader, ws_writer))
            try:
                await establish(self.id, bridged_reader, ws_writer)
            finally:
                pump.cancel()

        self._server = await asyncio.start_server(
            handler, host or "0.0.0.0", int(port), ssl=self.tls,
            reuse_port=self.reuse_port or None)

    async def _handshake(self, reader, writer) -> str | None:
        request = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 10)
        headers: dict[str, str] = {}
        lines = request.decode("latin-1").split("\r\n")
        for line in lines[1:]:
            name, sep, value = line.partition(":")
            if sep:
                headers[name.strip().lower()] = value.strip()
        key = headers.get("sec-websocket-key")
        if not key or "websocket" not in headers.get("upgrade", "").lower():
            return None
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_MAGIC).encode()).digest()).decode()
        resp = ("HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {accept}\r\n")
        if "mqtt" in headers.get("sec-websocket-protocol", ""):
            resp += "Sec-WebSocket-Protocol: mqtt\r\n"
        writer.write((resp + "\r\n").encode())
        await writer.drain()
        return key

    async def _pump_frames(self, reader, bridged: asyncio.StreamReader,
                           ws_writer: "_WSWriter") -> None:
        """Decode masked client frames into the bridged byte stream."""
        try:
            while True:
                hdr = await reader.readexactly(2)
                opcode = hdr[0] & 0x0F
                masked = bool(hdr[1] & 0x80)
                length = hdr[1] & 0x7F
                if length == 126:
                    length = struct.unpack(">H", await reader.readexactly(2))[0]
                elif length == 127:
                    length = struct.unpack(">Q", await reader.readexactly(8))[0]
                mask = await reader.readexactly(4) if masked else b"\x00" * 4
                payload = bytearray(await reader.readexactly(length))
                if masked:
                    for i in range(length):
                        payload[i] ^= mask[i % 4]
                if opcode == 0x8:  # close
                    ws_writer.send_close()
                    bridged.feed_eof()
                    return
                if opcode == 0x9:  # ping -> pong
                    ws_writer.send_pong(bytes(payload))
                    continue
                if opcode in (0x0, 0x1, 0x2):
                    bridged.feed_data(bytes(payload))
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            bridged.feed_eof()


class _WSWriter:
    """StreamWriter facade that wraps outbound bytes in binary WS frames."""

    def __init__(self, raw: asyncio.StreamWriter) -> None:
        self._raw = raw

    @staticmethod
    def _frame(opcode: int, payload: bytes) -> bytes:
        head = bytearray([0x80 | opcode])
        n = len(payload)
        if n < 126:
            head.append(n)
        elif n < 65536:
            head.append(126)
            head.extend(struct.pack(">H", n))
        else:
            head.append(127)
            head.extend(struct.pack(">Q", n))
        return bytes(head) + payload

    def write(self, data: bytes) -> None:
        self._raw.write(self._frame(0x2, data))

    def send_pong(self, payload: bytes) -> None:
        try:
            self._raw.write(self._frame(0xA, payload))
        except Exception:
            pass

    def send_close(self) -> None:
        try:
            self._raw.write(self._frame(0x8, b""))
        except Exception:
            pass

    async def drain(self) -> None:
        await self._raw.drain()

    def close(self) -> None:
        try:
            self._raw.write(self._frame(0x8, b""))
        except Exception:
            pass
        self._raw.close()

    async def wait_closed(self) -> None:
        try:
            await self._raw.wait_closed()
        except Exception:
            pass

    def is_closing(self) -> bool:
        return self._raw.is_closing()

    def get_extra_info(self, name, default=None):
        return self._raw.get_extra_info(name, default)


class HTTPStatsListener(Listener):
    """HTTP endpoint serving the broker's ``$SYS`` counters as JSON.

    Parity surface: vendor/.../v2/listeners/http_sysinfo.go:22-120 in the
    reference. ``info_fn`` returns the live SysInfo; every GET returns one
    JSON object snapshot.
    """

    def __init__(self, id_: str, address: str, info_fn) -> None:
        super().__init__(id_, address)
        self.info_fn = info_fn

    @property
    def protocol(self) -> str:
        return "http"

    async def serve(self, establish) -> None:
        host, _, port = self.address.rpartition(":")

        async def handler(reader, writer):
            import dataclasses
            import json
            try:
                # consume the request head; the response is the same for
                # every path, like the reference's single-route mux
                await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), timeout=5.0)
            except Exception:
                writer.close()
                return
            info = self.info_fn()
            d = dataclasses.asdict(info)
            d.pop("extra", None)
            body = json.dumps(d).encode()
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Type: application/json\r\n"
                         b"Content-Length: " + str(len(body)).encode() +
                         b"\r\nConnection: close\r\n\r\n" + body)
            try:
                await writer.drain()
            except Exception:
                pass
            writer.close()

        self._server = await asyncio.start_server(
            handler, host or "0.0.0.0", int(port))


class Listeners:
    """Registry of listeners; serve-all / close-all.

    Parity: listeners.go:40-133 in the reference.
    """

    def __init__(self) -> None:
        self._listeners: dict[str, Listener] = {}

    def add(self, listener: Listener) -> Listener:
        if listener.id in self._listeners:
            raise ValueError(f"listener id {listener.id!r} already exists")
        self._listeners[listener.id] = listener
        return listener

    def get(self, id_: str) -> Listener | None:
        return self._listeners.get(id_)

    def all(self) -> list[Listener]:
        return list(self._listeners.values())

    def __len__(self) -> int:
        return len(self._listeners)

    async def serve_all(self, establish) -> None:
        for listener in self._listeners.values():
            await listener.serve(establish)

    def stop_accepting_all(self) -> None:
        for listener in self._listeners.values():
            listener.stop_accepting()

    async def close_all(self) -> None:
        for listener in self._listeners.values():
            await listener.close()
        self._listeners.clear()
