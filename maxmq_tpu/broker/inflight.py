"""Per-client inflight (unacknowledged QoS 1/2) message tracking and MQTT v5
send/receive quota counters.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/inflight.go.
"""

from __future__ import annotations

from ..protocol.packets import Packet


class Inflight:
    """Unacked packets keyed by packet id, plus v5 flow-control quotas.

    ``receive_quota``: how many more QoS>0 publishes we accept from the
    client; ``send_quota``: how many more we may have outstanding to it.
    """

    def __init__(self, receive_maximum: int = 0, send_maximum: int = 0) -> None:
        self._messages: dict[int, Packet] = {}
        # packet ids whose record is known to be in the persistence
        # pipeline/store (written by the storage hook, or restored from
        # it at boot) — lets resend-on-resume skip byte-identical
        # journal rewrites (ADR 014)
        self._stored: set[int] = set()
        self.maximum_receive = receive_maximum
        self.receive_quota = receive_maximum
        self.maximum_send = send_maximum
        self.send_quota = send_maximum

    def __len__(self) -> int:
        return len(self._messages)

    def set(self, packet: Packet) -> bool:
        """Store/replace; True when the packet id was not present before.
        A (re)set invalidates the stored marker: the persisted form no
        longer matches until the storage hook rewrites it."""
        is_new = packet.packet_id not in self._messages
        self._messages[packet.packet_id] = packet
        self._stored.discard(packet.packet_id)
        return is_new

    def get(self, packet_id: int) -> Packet | None:
        return self._messages.get(packet_id)

    def delete(self, packet_id: int) -> bool:
        self._stored.discard(packet_id)
        return self._messages.pop(packet_id, None) is not None

    # -- persistence markers (ADR 014) --------------------------------------

    def note_stored(self, packet_id: int) -> None:
        if packet_id in self._messages:
            self._stored.add(packet_id)

    def stored(self, packet_id: int) -> bool:
        return packet_id in self._stored

    def all(self) -> list[Packet]:
        """Inflight packets ordered by creation time (for resend-on-resume)."""
        return sorted(self._messages.values(), key=lambda p: (p.created, p.packet_id))

    def digest(self) -> tuple[int, int]:
        """(count, xor-of-packet-ids): the order-free inflight-window
        digest replicated with session updates (ADR 016). A takeover
        compares the installed window against the owner's digest —
        cheap enough to ride every update, strong enough to catch a
        dropped or duplicated replication op."""
        x = 0
        for pid in self._messages:
            x ^= pid
        return len(self._messages), x

    def clone(self) -> "Inflight":
        other = Inflight(self.maximum_receive, self.maximum_send)
        other._messages = {k: v.copy() for k, v in self._messages.items()}
        other._stored = set(self._stored)
        return other

    # -- quotas (clamped to maxima) -----------------------------------------

    def take_receive_quota(self) -> bool:
        if self.maximum_receive == 0:
            return True
        if self.receive_quota <= 0:
            return False
        self.receive_quota -= 1
        return True

    def return_receive_quota(self) -> None:
        if self.maximum_receive and self.receive_quota < self.maximum_receive:
            self.receive_quota += 1

    def take_send_quota(self) -> bool:
        if self.maximum_send == 0:
            return True
        if self.send_quota <= 0:
            return False
        self.send_quota -= 1
        return True

    def return_send_quota(self) -> None:
        if self.maximum_send and self.send_quota < self.maximum_send:
            self.send_quota += 1
