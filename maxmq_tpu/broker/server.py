"""The broker engine: connection establishment, per-packet dispatch, QoS 1/2
state machines, publish fan-out, retained/will/session lifecycles, $SYS.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/server.go in the reference
(Server, Capabilities, EstablishConnection, processPublish,
publishToSubscribers, publishToClient, event loop). Re-designed around
asyncio: the per-connection read loop serializes that client's packets; the
topic matcher is pluggable so the TPU NFA engine can replace the CPU trie.
"""

from __future__ import annotations

import asyncio
import heapq
import threading
import time
from dataclasses import dataclass, field
from zlib import crc32

from .. import faults
from ..filtering.expr import ExprError, decode_payload
from ..filtering.plane import (ContentPlane, ContentQuota,
                               USER_PROP_KEY as FILTER_PROP_KEY)
from ..hooks.base import Hook, Hooks, RejectPacket
from ..trace import MAX_DRAIN_SPANS, PipelineTracer
from ..matching.topics import valid_filter, valid_topic_name
from ..matching.trie import (SubscriberSet, TopicIndex,
                             VersionedTopicCache)
from ..protocol import codes, wire
from ..protocol.codec import (FixedHeader, MalformedPacketError,
                              PacketType as PT, write_varint)
from ..protocol.packets import Packet, ProtocolError, Subscription
from .client import (Client, ClientRegistry, FlushScheduler,
                     PacketIDExhausted)
from .listeners import Listener, Listeners
from .overload import OverloadState, TokenBucket, top_offenders
from .sys_info import SysInfo

__version__ = "0.1.0"


def _current_rss_bytes() -> int:
    """Current resident set size. /proc on linux; best-effort elsewhere
    (a failed probe reports 0 — the $SYS tick must never die over it)."""
    try:
        with open("/proc/self/status", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys as _sys
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if _sys.platform == "darwin" else rss * 1024
    except Exception:
        return 0


@dataclass
class Capabilities:
    """Feature flags/limits advertised to v5 clients and enforced for all.

    Parity: v2/server.go:35-70 (Capabilities + defaults).
    """

    maximum_session_expiry_interval: int = 0xFFFFFFFF
    maximum_message_expiry_interval: int = 60 * 60 * 24
    receive_maximum: int = 1024
    maximum_qos: int = 2
    retain_available: bool = True
    maximum_packet_size: int = 0  # 0 = unlimited
    topic_alias_maximum: int = 65535
    wildcard_sub_available: bool = True
    sub_id_available: bool = True
    shared_sub_available: bool = True
    minimum_protocol_version: int = 3
    maximum_clients: int = 0  # 0 = unlimited
    maximum_keepalive: int = 0  # 0 = unlimited; else clamp + v5 ServerKeepAlive
    maximum_client_writes_pending: int = 1024 * 8
    maximum_inflight: int = 1024 * 8
    buffer_size: int = 65536          # per-connection read-chunk bytes
    shutdown_timeout: float = 15.0    # graceful-close deadline, seconds

    def __post_init__(self) -> None:
        # read(0) returns b'' and reads as EOF, killing every
        # connection at the first loop turn — clamp on the field so
        # direct Capabilities(...) construction is as safe as config
        self.buffer_size = max(self.buffer_size, 1024)
    sys_topic_interval: float = 30.0  # seconds; 0 disables
    keepalive_grace: float = 1.5      # deadline = keepalive * grace

    # -- overload-protection ladder (ADR 012); 0 disables each rung ----
    client_byte_budget: int = 0       # per-client queued outbound bytes
    broker_byte_budget: int = 0       # global queued outbound bytes
    connect_rate: float = 0.0         # CONNECT admissions/sec per listener
    connect_burst: int = 0            # bucket depth; 0 = max(1, rate)
    connect_half_open_max: int = 0    # handshakes awaiting CONNECT
    stall_deadline_ms: int = 0        # writer no-progress disconnect
    overload_high_water: float = 0.8  # shed above budget * high_water
    overload_low_water: float = 0.5   # recover below budget * low_water

    # -- publish-path tracing (ADR 015); sample_n = 0 disables ---------
    trace_sample_n: int = 0           # trace every Nth publish
    trace_slow_ms: float = 0.0        # flight-record only e2e >= this
    trace_ring: int = 64              # flight-recorder entries kept

    # -- zero-copy fan-out (ADR 019) -----------------------------------
    native_encode: bool = True        # C frame-head assembly when the
                                      # maxmq_decode extension is built;
                                      # False pins the Python builder
    flush_coalesce: bool = True       # coalesce writer wakes to one
                                      # flush per loop iteration

    # -- MQTT+ content plane (ADR 023) ---------------------------------
    content_filtering: bool = True    # parse ?$expr/?$agg SUBSCRIBE
                                      # options; False leaves '?' a
                                      # plain topic character
    filter_backend: str = "numpy"     # numpy | jnp | auto
    filter_max_subscriptions: int = 10000  # content subs per broker
    filter_max_expr_len: int = 512    # $expr source-length bound
    filter_max_fields: int = 64       # distinct decoded fields bound
    filter_batch_max: int = 256       # pipeline publishes per eval flush
    filter_window_min_s: float = 0.5  # accepted $win range
    filter_window_max_s: float = 3600.0


@dataclass
class BrokerOptions:
    capabilities: Capabilities = field(default_factory=Capabilities)
    logger: object | None = None
    inline_client: bool = True


class Broker:
    """A single-process MQTT broker instance."""

    def __init__(self, options: BrokerOptions | None = None) -> None:
        self.options = options or BrokerOptions()
        self.capabilities = self.options.capabilities
        self.log = self.options.logger
        self.clients = ClientRegistry()
        self.topics = TopicIndex()
        self.listeners = Listeners()
        self.hooks = Hooks()
        self.info = SysInfo(version=__version__, started=int(time.time()))
        self.matcher = None  # optional TPU/NFA matcher engine (set via attach)
        self._housekeeper: asyncio.Task | None = None
        self._sys_task: asyncio.Task | None = None
        self._will_delays: dict[str, tuple[float, Packet]] = {}
        # client-id -> Client parked in the ADR-016 takeover await of
        # _attach_client (after _inherit_session, before clients.add):
        # a concurrent CONNECT for the same id must fence it off there
        self._mid_connect: dict[str, Client] = {}
        self._retained_expiry: list[tuple[float, str]] = []
        # topic -> latest due time: the heap uses lazy deletion, and a
        # retained topic REPUBLISHED often (1Hz sensor state) would
        # otherwise grow the heap by one stale entry per publish for a
        # full expiry interval (~86K entries/day/topic) — found by
        # tools/soak.py
        self._retained_due: dict[str, float] = {}
        # publish topics repeat heavily, and a trie walk costs ~20us;
        # entries self-invalidate on any subscription change
        self._match_cache = VersionedTopicCache()
        # MQTT+ content plane (ADR 023): payload-predicate masks +
        # windowed aggregates. Constructed whenever the capability is
        # on; with no content subscriptions registered .active is
        # False and every publish-path hook reduces to one check
        self.content = (ContentPlane(self)
                        if self.capabilities.content_filtering else None)
        # matcher-mode publish pipeline: (match future, origin, packet)
        # consumed in arrival order, so per-publisher delivery order holds
        # [MQTT-4.6.0] while many publishes ride the device concurrently
        self._pub_queue: asyncio.Queue | None = None
        self._pub_consumer: asyncio.Task | None = None
        # publishes whose match future failed and were served from the
        # broker's own trie (the rung BELOW the ADR-011 supervisor —
        # nonzero here means a failure got past the supervised matcher)
        self.matcher_degrades = 0
        # overload-protection ladder (ADR 012): global byte ledger +
        # watermark state, half-open handshake count, and retained
        # deliveries parked while shedding (drained on recovery)
        self.overload = OverloadState(self.capabilities)
        self._half_open = 0
        # zero-copy fan-out (ADR 019): per-loop-iteration writer-wake
        # coalescing — one flush pass wakes every writer a fan-out
        # touched, after its full backlog is queued. None disables
        # (direct wakes), for latency-sensitive single-subscriber
        # deployments that prefer the pre-019 behavior.
        self.flush_sched = (FlushScheduler()
                            if self.capabilities.flush_coalesce else None)
        # (client_id, filter) -> (sub, existing): keyed so a client
        # re-SUBSCRIBing during the shed window gets ONE delivery on
        # recovery and the ledger is bounded by the subscription count
        self._deferred_retained: dict[tuple[str, str],
                                      tuple[Subscription, bool]] = {}
        # cluster federation manager (ADR 013); attached via
        # attach_cluster, started/stopped with the broker lifecycle
        self.cluster = None
        # crash-consistent storage pipeline (ADR 014): the storage
        # hook/journal discovered at serve(); under storage_sync=always
        # QoS acks release through the journal's durability barrier
        self._storage_hook = None
        self._journal = None
        self.boot_epoch = 0             # persisted monotonic boot counter
        self.storage_barrier_waits = 0  # acks that waited on a barrier
        # publish-path tracer (ADR 015): always constructed — the
        # stage-error counters are fed even with sampling off; span
        # stamping is gated on tracer.sample_n at every site
        self.tracer = PipelineTracer(
            sample_n=self.capabilities.trace_sample_n,
            slow_ms=self.capabilities.trace_slow_ms,
            ring=self.capabilities.trace_ring)
        self._sys_trace_topics: set[str] = set()  # retained while sampling
        self._running = False
        self.loop: asyncio.AbstractEventLoop | None = None

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def _spawn(self, coro, what: str) -> asyncio.Task:
        """Fire-and-forget task with failure logging: a lost will fan-out
        or a failed forced disconnect must not vanish silently."""
        task = self.loop.create_task(coro)

        def _done(t: asyncio.Task) -> None:
            if t.cancelled():
                return
            exc = t.exception()
            if exc is None:
                return
            if self.log is not None:
                self.log.with_prefix("broker").error(
                    "background task failed", task=what, error=repr(exc))
            else:
                import logging
                logging.getLogger("maxmq").error(
                    "background task %s failed: %r", what, exc)

        task.add_done_callback(_done)
        return task

    def add_hook(self, hook: Hook, config=None) -> Hook:
        return self.hooks.add(hook, config)

    def add_listener(self, listener: Listener) -> Listener:
        return self.listeners.add(listener)

    def attach_matcher(self, matcher) -> None:
        """Install a pluggable matcher engine (e.g. the TPU NFA). It must
        expose ``subscribers(topic) -> SubscriberSet``."""
        self.matcher = matcher

    def attach_cluster(self, manager) -> None:
        """Install the federation manager (ADR 013): bridge links start
        with serve(), publishes consult its route table in the fan-out,
        and inbound ``$cluster/*`` traffic is diverted to it."""
        self.cluster = manager

    async def serve(self) -> None:
        self.loop = asyncio.get_running_loop()
        self._running = True
        # ADR 014: find the persistence hook (and its write-behind
        # journal, if it rides one) before restore — the durability
        # barrier and boot-epoch bump both hang off it
        self._storage_hook = next(
            (h for h in self.hooks if hasattr(h, "bump_boot_epoch")), None)
        self._journal = getattr(self._storage_hook, "journal", None)
        if self._journal is not None:
            # ADR 015: the writer thread feeds the journal_commit stage
            # histogram + commit-failure stage errors through the tracer
            self._journal.tracer = self.tracer
        await self._restore_from_storage()
        await self._compile_matcher_tables()
        if self.capabilities.connect_rate > 0:
            # per-listener CONNECT token bucket (ADR 012): armed before
            # accepting so the very first storm is already gated
            for listener in self.listeners.all():
                if listener.gate is None:
                    listener.gate = TokenBucket(
                        self.capabilities.connect_rate,
                        self.capabilities.connect_burst)
        await self.listeners.serve_all(self._establish)
        self._housekeeper = self.loop.create_task(self._housekeeping_loop())
        if self.capabilities.sys_topic_interval > 0:
            self._sys_task = self.loop.create_task(self._sys_topic_loop())
        if self.cluster is not None:
            # after listeners: peers dialing back must find us accepting
            await self.cluster.start()
        self.hooks.notify("on_started")

    async def _compile_matcher_tables(self) -> None:
        """Compile the matcher's initial tables at the boot quiescent
        point — after storage restore, before listeners accept traffic.
        A restore that loaded a large subscription set would otherwise
        defer the first table compile (and its gc.freeze, ADR 009) to
        the first publish, freezing mid-traffic transients along with
        the tables. Off the event loop: the compile can take seconds at
        1M subscriptions, and nothing is being served yet.

        Prewarm rides the same executor call: a synchronous refresh()
        alone never populates the chained-decode anchors (only
        _bg_refresh does), so a broker restored with a large
        subscription set would pay the anchor-population ramp across
        its first few hundred thousand publishes (ADVICE r5 #1)."""
        if self.matcher is None or self.topics.subscription_count == 0:
            return
        engine = getattr(self.matcher, "engine", self.matcher)
        refresh = getattr(engine, "refresh", None)
        if refresh is None:
            return

        def compile_and_prewarm():
            refresh()
            prewarm = getattr(engine, "prewarm_decode_bases", None)
            if prewarm is None:
                return
            try:
                prewarm()
            except Exception as exc:
                # prewarm is a warm-up optimization: the compiled
                # tables above are live either way, so a prewarm
                # failure must not be reported as a compile failure
                if self.log is not None:
                    self.log.warn("boot-time decode prewarm failed",
                                  error=repr(exc)[:200])
        try:
            await self.loop.run_in_executor(None, compile_and_prewarm)
        except Exception as exc:
            # lazy refresh on first batch remains the fallback
            if self.log is not None:
                self.log.warn("boot-time matcher compile failed",
                              error=repr(exc)[:200])

    async def close(self) -> None:
        if not self._running:
            return
        self._running = False
        for task in (self._housekeeper, self._sys_task):
            if task is not None:
                task.cancel()
        if self.cluster is not None:
            # bridges first: a dying broker must stop forwarding before
            # its local fan-out stops
            await self.cluster.close()
        self.listeners.stop_accepting_all()
        stops = []
        for client in self.clients.connected():
            self.disconnect_client(client, codes.ErrServerShuttingDown)
            stops.append(asyncio.ensure_future(
                client.stop(ProtocolError(codes.ErrServerShuttingDown))))
        if stops:
            # one shared graceful deadline for ALL clients; stragglers
            # are cancelled, not waited on sequentially
            _done, pending = await asyncio.wait(
                stops, timeout=self.capabilities.shutdown_timeout)
            for p in pending:
                p.cancel()
        if self._pub_consumer is not None:
            # intake is stopped (listeners + read loops), so the queue
            # can only shrink: give the backlog a bounded drain (inline
            # clients may still take delivery; closed ones no-op), then
            # stop the consumer and reset so a re-serve()d broker
            # lazily recreates both
            try:
                await asyncio.wait_for(
                    self._pub_queue.join(),
                    timeout=self.capabilities.shutdown_timeout)
            except (asyncio.TimeoutError, TimeoutError):
                pass
            self._pub_consumer.cancel()
            self._pub_consumer = None
            self._pub_queue = None
        await self.listeners.close_all()
        self.hooks.notify("on_stopped")
        self.hooks.stop_all()

    # ------------------------------------------------------------------
    # Connection establishment
    # ------------------------------------------------------------------

    async def _establish(self, listener_id: str, reader, writer) -> None:
        if not await self._admit_connection(listener_id):
            try:
                writer.close()
            except Exception:
                pass
            return
        client = Client(self, reader, writer, listener_id)
        client._half_open = True
        self._half_open += 1
        try:
            await self._attach_client(client)
        except (ProtocolError, MalformedPacketError, ConnectionError, OSError):
            pass
        finally:
            self._settle_half_open(client)
            await client.stop()

    async def _admit_connection(self, listener_id: str) -> bool:
        """Admission control (ADR 012): deterministic accept fault site,
        per-listener CONNECT token bucket, half-open handshake cap. A
        False refuses the socket before any handshake work is queued."""
        try:
            hit = faults.fire_detail(faults.LISTENER_ACCEPT)
        except faults.InjectedFault:
            self.overload.connects_refused += 1
            return False
        if hit is not None and hit[0] == "hang":
            await asyncio.sleep(hit[1])
        listener = self.listeners.get(listener_id)
        gate = getattr(listener, "gate", None)
        if gate is not None and not gate.allow():
            self.overload.connects_refused += 1
            return False
        caps = self.capabilities
        if (caps.connect_half_open_max
                and self._half_open >= caps.connect_half_open_max):
            self.overload.half_open_refused += 1
            return False
        return True

    def _settle_half_open(self, client: Client) -> None:
        if getattr(client, "_half_open", False):
            client._half_open = False
            self._half_open -= 1

    async def _attach_client(self, client: Client) -> None:
        packet, leftover = await self._read_connect(client)
        client.parse_connect(packet)
        self._validate_connect(client, packet)

        self.hooks.notify("on_connect", client, packet)
        if not self.hooks.any_allow("on_connect_authenticate", client, packet):
            self._send_connack(client, codes.ErrBadUsernameOrPassword, False)
            raise ProtocolError(codes.ErrBadUsernameOrPassword)

        if packet.will is not None:
            client.properties.will = self.hooks.modify(
                "on_will", packet.will, client)

        self.hooks.notify("on_session_establish", client, packet)
        session_present = self._inherit_session(client)
        sessions = self._cluster_sessions()
        if sessions is not None:
            # ADR 016: epoch-fenced cross-node takeover BEFORE CONNACK —
            # a session owned by a peer is claimed, transferred (or
            # rebuilt from the replicated ledger) and installed here, so
            # the client sees session-present=1 on any node. Bounded:
            # every remote leg degrades instead of wedging the CONNECT.
            # The await opens a same-id race _inherit_session cannot
            # see (this client is not in the registry yet): a parked
            # predecessor is fenced off like a registered one, and if a
            # successor supersedes US while parked, this CONNECT loses.
            prev = self._mid_connect.get(client.id)
            if prev is not None and prev is not client:
                prev.taken_over = True
                if not prev.closed:
                    self.disconnect_client(prev, codes.ErrSessionTakenOver)
                    self._spawn(
                        prev.stop(ProtocolError(codes.ErrSessionTakenOver)),
                        "takeover-stop")
            self._mid_connect[client.id] = client
            try:
                session_present = await sessions.on_local_connect(
                    client, session_present)
            finally:
                if self._mid_connect.get(client.id) is client:
                    del self._mid_connect[client.id]
            if client.taken_over:
                raise ProtocolError(codes.ErrSessionTakenOver)
        self._will_delays.pop(client.id, None)  # reconnect cancels delayed will
        self.clients.add(client)
        client.connected_at = time.time()
        self.info.clients_connected += 1
        self.info.clients_maximum = max(self.info.clients_maximum,
                                        self.info.clients_connected)
        self.info.clients_total += 1
        client.start()
        self._send_connack(client, codes.Success, session_present)
        self._settle_half_open(client)     # handshake completed
        if session_present:
            client.resend_inflight()
            # quota-parked (held) messages resumed with the session:
            # nothing acked yet, so kick the drain once (ADR 018)
            self._release_held(client)
        self.hooks.notify("on_session_established", client, packet)

        err: ProtocolError | None = None
        try:
            await client.read_loop(self._receive_packet, initial=leftover)
        except ProtocolError as e:
            err = e
        except MalformedPacketError:
            err = ProtocolError(codes.ErrMalformedPacket)
        finally:
            await self._detach_client(client, err)

    async def _read_connect(self, client: Client
                            ) -> tuple[Packet, bytearray]:
        """The first inbound packet must be CONNECT [MQTT-3.1.0-1].
        Returns (packet, leftover bytes read past it) — a client may
        pipeline further packets in the same TCP segment."""
        from ..protocol.packets import parse_stream

        assert client.reader is not None
        buf = bytearray()
        deadline = time.monotonic() + 5.0
        while True:
            for fh, body in parse_stream(
                    buf, self.capabilities.maximum_packet_size):
                self.info.packets_received += 1
                if fh.type != PT.CONNECT:
                    raise ProtocolError(codes.ErrProtocolViolation,
                                        "first packet was not CONNECT")
                return Packet.decode(fh, body), buf
            timeout = deadline - time.monotonic()
            if timeout <= 0:
                raise ProtocolError(codes.ErrKeepAliveTimeout)
            try:
                chunk = await asyncio.wait_for(
                    client.reader.read(self.capabilities.buffer_size),
                    timeout)
            except asyncio.TimeoutError:
                raise ProtocolError(codes.ErrKeepAliveTimeout) from None
            if not chunk:
                raise ConnectionError("eof before CONNECT")
            self.info.bytes_received += len(chunk)
            buf.extend(chunk)

    def _validate_connect(self, client: Client, packet: Packet) -> None:
        caps = self.capabilities
        if packet.protocol_version < caps.minimum_protocol_version:
            self._send_connack(client, codes.ErrUnsupportedProtocolVersion, False)
            raise ProtocolError(codes.ErrUnsupportedProtocolVersion)
        if caps.maximum_clients and len(self.clients) >= caps.maximum_clients:
            self._send_connack(client, codes.ErrServerBusy, False)
            raise ProtocolError(codes.ErrServerBusy)
        if not packet.client_id:
            if not packet.clean_start and packet.protocol_version < 5:
                # [MQTT-3.1.3-8]: zero-byte id requires clean session pre-v5
                self._send_connack(client, codes.ErrClientIdentifierNotValid,
                                   False)
                raise ProtocolError(codes.ErrClientIdentifierNotValid)
            client.id = f"auto-{int(time.time() * 1000):x}-{id(client):x}"
            client.assigned_id = True
        else:
            client.assigned_id = False

    def _inherit_session(self, client: Client) -> bool:
        """Session takeover/resume. Returns session-present for CONNACK.

        Parity: v2/server.go:451-495 (inheritClientSession).
        """
        existing = self.clients.get(client.id)
        if existing is None or existing is client:
            return False
        existing.taken_over = True
        if not existing.closed:
            self.disconnect_client(existing, codes.ErrSessionTakenOver)
            self._spawn(
                existing.stop(ProtocolError(codes.ErrSessionTakenOver)),
                "takeover-stop")
        if client.properties.clean_start:
            self._purge_session(existing)
            return False
        client.subscriptions = dict(existing.subscriptions)
        client.inflight = existing.inflight.clone()
        client.inflight.maximum_send = (client.properties.receive_maximum
                                        or self.capabilities.receive_maximum)
        client.inflight.send_quota = client.inflight.maximum_send
        client.inflight.maximum_receive = self.capabilities.receive_maximum
        client.inflight.receive_quota = client.inflight.maximum_receive
        client.pubrec_inbound = set(existing.pubrec_inbound)
        # held-but-unsent pids stay parked across the resume (ADR 018):
        # resend skips them, _release_held drains them under quota
        client.held_pids = type(client.held_pids)(existing.held_pids)
        return bool(client.subscriptions) or len(client.inflight) > 0

    def _purge_session(self, client: Client) -> None:
        for filt in list(client.subscriptions):
            if self.topics.unsubscribe(client.id, filt):
                self.info.subscriptions -= 1
                if self.cluster is not None:
                    self.cluster.note_unsubscribe(filt)
        client.subscriptions.clear()
        if self.content is not None:
            self.content.drop_client(client.id)
        self.clients.delete(client.id)
        sessions = self._cluster_sessions()
        if sessions is not None:
            # ADR 016: an expired/discarded session is purged
            # cluster-wide, not resurrected from a peer's replica
            sessions.note_purge(client.id)

    def _cluster_sessions(self):
        """The ADR-016 session-federation manager, when attached."""
        return (getattr(self.cluster, "sessions", None)
                if self.cluster is not None else None)

    def _note_pubrec(self, client: Client, pid: int, add: bool) -> None:
        """ADR 018: stream receiver-side QoS2 dedup (PUBREC-pending)
        changes to the session federation, so a dead-owner failover
        keeps the dedup set instead of redelivering on PUBLISH retry."""
        sessions = self._cluster_sessions()
        if sessions is not None:
            sessions.note_pubrec(client, pid, add)

    def _send_connack(self, client: Client, code: codes.Code,
                      session_present: bool) -> None:
        packet = Packet(fixed=FixedHeader(type=PT.CONNACK),
                        protocol_version=client.properties.protocol_version,
                        session_present=session_present,
                        reason_code=codes.connack_for_version(
                            code, client.properties.protocol_version))
        if client.properties.protocol_version >= 5 and not code.is_error:
            self._fill_connack_props(client, packet.properties)
        client.send_now(packet)

    def _fill_connack_props(self, client: Client, pr) -> None:
        """Advertise the server capability set on a v5 CONNACK
        [MQTT-3.2.2]; None leaves a property off the wire."""
        caps = self.capabilities
        pr.session_expiry = min(
            client.properties.session_expiry,
            caps.maximum_session_expiry_interval) \
            if client.properties.session_expiry_set else None
        pr.receive_maximum = caps.receive_maximum or None
        if caps.maximum_qos < 2:
            pr.maximum_qos = caps.maximum_qos
        if caps.maximum_packet_size:
            pr.maximum_packet_size = caps.maximum_packet_size
        pr.topic_alias_max = caps.topic_alias_maximum or None
        for prop, available in (
                ("retain_available", caps.retain_available),
                ("wildcard_sub_available", caps.wildcard_sub_available),
                ("sub_id_available", caps.sub_id_available),
                ("shared_sub_available", caps.shared_sub_available)):
            setattr(pr, prop, None if available else 0)
        if getattr(client, "assigned_id", False):
            pr.assigned_client_id = client.id
        if (caps.maximum_keepalive
                and client.keepalive != client.requested_keepalive):
            pr.server_keep_alive = client.keepalive

    async def _detach_client(self, client: Client, err: ProtocolError | None) -> None:
        """Connection teardown: will handling, registry bookkeeping, expiry."""
        if err is not None and err.code.is_error and client.writer is not None:
            self.disconnect_client(client, err.code)
        await client.stop(err)
        self.info.clients_connected -= 1
        self.info.clients_disconnected += 1

        if client.taken_over:
            current = self.clients.get(client.id)
            if current is not client:
                # session continues elsewhere; suppress will per delay rules
                self.hooks.notify("on_disconnect", client, err, False)
                return
        # A clean client DISCONNECT cleared the will in _process_disconnect;
        # anything still present fires (abnormal close, or v5 reason 0x04).
        if client.properties.will is not None:
            self._queue_will(client)
        if client.properties.protocol_version >= 5:
            expire = (client.properties.session_expiry == 0
                      if client.properties.session_expiry_set
                      else client.properties.clean_start)
        else:
            expire = client.properties.clean_start
        self.hooks.notify("on_disconnect", client, err, expire)
        if expire:
            self._purge_session(client)

    # ------------------------------------------------------------------
    # Packet dispatch
    # ------------------------------------------------------------------

    async def _receive_packet(self, client: Client, packet: Packet) -> None:
        packet = self.hooks.modify("on_packet_read", packet, client)
        err = None
        try:
            await self._process_packet(client, packet)
        except ProtocolError as e:
            err = e
            raise
        finally:
            self.hooks.notify("on_packet_processed", client, packet, err)

    async def _process_packet(self, client: Client, packet: Packet) -> None:
        t = packet.type
        if t == PT.PUBLISH:
            await self.process_publish(client, packet)
            return
        handler = self._DISPATCH.get(t)
        if handler is None:
            raise ProtocolError(codes.ErrProtocolViolation,
                                f"unexpected packet type {t}")
        handler(self, client, packet)

    def _process_pingreq(self, client: Client, packet: Packet) -> None:
        client.send(Packet(fixed=FixedHeader(type=PT.PINGRESP),
                           protocol_version=client.properties.protocol_version))

    def _process_auth(self, client: Client, packet: Packet) -> None:
        if not packet.reason_code_valid():
            raise ProtocolError(codes.ErrProtocolViolation,
                                "invalid AUTH reason code"
                                )  # [MQTT-3.15.2-1]
        self.hooks.modify("on_auth_packet", packet, client)

    def _process_second_connect(self, client: Client,
                                packet: Packet) -> None:
        raise ProtocolError(codes.ErrProtocolViolation,
                            "second CONNECT on live connection")

    def _process_disconnect(self, client: Client, packet: Packet) -> None:
        if (packet.protocol_version >= 5
                and packet.properties.session_expiry is not None):
            if (not client.properties.session_expiry_set
                    and packet.properties.session_expiry > 0):
                # [MQTT-3.1.2-23]: can't resurrect expiry after connecting with 0
                raise ProtocolError(codes.ErrProtocolViolation,
                                    "session expiry raised at disconnect")
            client.properties.session_expiry = packet.properties.session_expiry
            client.properties.session_expiry_set = True
        if packet.reason_code == codes.DisconnectWithWill.value:
            pass  # keep the will: abnormal-close path will fire it
        else:
            client.properties.will = None  # normal disconnect discards will
        raise ProtocolError(codes.Success)  # terminates read loop cleanly

    # ------------------------------------------------------------------
    # PUBLISH inbound
    # ------------------------------------------------------------------

    async def process_publish(self, client: Client, packet: Packet) -> None:
        """Parity: v2/server.go:674-754 (processPublish)."""
        if self.tracer.sample_n:        # ADR 015: one branch when off
            self._trace_begin(client, packet)
        packet.validate_publish()
        packet.protocol_version = client.properties.protocol_version
        packet.origin = client.id
        packet.created = time.time()

        self._resolve_inbound_alias(client, packet)
        if packet.topic.startswith("$") and not client.inline:
            # clients may not publish into reserved $ topics — except
            # $cluster/* arriving over an authenticated bridge link,
            # which is the federation wire (ADR 013)
            await self._process_cluster_inbound(client, packet)
            return
        if not self.hooks.any_allow("on_acl_check", client, packet.topic, True):
            # [MQTT-3.3.5-2]: ack but do not deliver (behind any acks
            # still parked on a durability barrier, [MQTT-4.6.0-2])
            self._ack_publish_ordered(client, packet, success=False)
            return
        if not self._check_publish_qos(client, packet):
            return  # QoS2 dedup re-acked without re-delivery

        try:
            packet = self.hooks.modify("on_publish", packet, client)
        except RejectPacket as r:
            self._ack_publish_ordered(client, packet, success=r.ack_success)
            return

        self.info.messages_received += 1
        if packet.fixed.retain:
            self.retain_message(client, packet)
        await self._route_publish(client, packet)

    def _trace_begin(self, client: Client, packet: Packet) -> None:
        """ADR 015: admit this publish into the sampling stride. The
        read loop timed the decode (packet._decode_ns) when tracing was
        on; the trace's start is backdated to the decode start so e2e
        covers wire-bytes -> terminal stage."""
        tracer = self.tracer
        dec = packet.__dict__.pop("_decode_ns", 0)
        now = tracer.clock()
        tr = tracer.sample(packet.topic, packet.fixed.qos, client.id,
                           start_ns=now - dec)
        if tr is None:
            return
        if dec:
            tr.span("decode", now - dec, now)
        tr.t_admit = now
        packet._trace = tr

    def _packet_trace(self, packet: Packet):
        # the gate opens for local sampling OR while an ADOPTED
        # cross-node trace is live (ADR 017) — a receiving node stamps
        # child spans even when its own sampling stride is off
        t = self.tracer
        return (packet.__dict__.get("_trace")
                if t.sample_n or t.adopted_open else None)

    async def _route_publish(self, client: Client, packet: Packet) -> None:
        """Ack + fan out an accepted publish. Durability barrier
        (ADR 014, storage_sync=always): the QoS ack must cover the
        publish's storage writes — and those are enqueued by the
        FAN-OUT (inflight records for QoS subscribers) as well as the
        retain rewrite — so under a barrier the ack moves after fan-out
        and releases on the journal's commit."""
        tr = self._packet_trace(packet)
        if tr is not None:
            tr.span("admission", tr.t_admit, self.tracer.clock())
        durable = self._needs_durable_ack(client, packet)
        if not durable:
            if tr is None:
                self._ack_publish(client, packet, success=True)
            else:
                t0 = self.tracer.clock()
                self._ack_publish(client, packet, success=True)
                tr.span("ack", t0, self.tracer.clock())
        elif packet.fixed.qos == 2:
            # the QoS2 dedup window opens NOW, not when the barrier
            # resolves: a client that times out and retransmits the
            # same id mid-barrier must be deduped, not redelivered
            # (_ack_publish re-adds on send — a set, idempotent)
            if packet.packet_id not in client.pubrec_inbound:
                client.pubrec_inbound.add(packet.packet_id)
                self._note_pubrec(client, packet.packet_id, True)
        if self.matcher is None:
            if tr is None:
                subscribers = self._match_cached(packet.topic)
            else:
                t0 = self.tracer.clock()
                subscribers = self._match_cached(packet.topic)
                tr.span("match_device", t0, self.tracer.clock())
            if durable:
                # shared with the pipeline consumer: fan-out failures
                # are logged, and the ack STILL releases durably
                self._pub_deliver(subscribers, client, packet, True)
            else:
                self._fan_out(subscribers, packet)
                self.hooks.notify("on_published", client, packet)
                if tr is not None:
                    self.tracer.finish(tr)
        else:
            # pipelined: dispatch the match NOW, fan out in arrival order
            # from the consumer task. The read loop returns immediately,
            # so a single connection can keep thousands of publishes in
            # flight — that in-flight depth is what lets the MicroBatcher
            # form device-sized batches instead of per-connection pairs.
            await self._enqueue_publish(client, packet, durable_ack=durable)

    async def _process_cluster_inbound(self, client: Client,
                                       packet: Packet) -> None:
        """``$cluster/*`` publishes from a recognized bridge peer are
        the federation wire: hand them to the ClusterManager, then ack
        on the normal QoS path (the link QoS is the delivery guarantee
        between nodes). Everything else in the ``$`` namespace from a
        network client stays dropped.

        The ack moves AFTER the apply (ADR 018): a QoS1 sess/fwd
        message is PUBACKed only once its op is applied and enqueued to
        the journal — the sender's replication/fwd barrier then means
        "the peer holds it", not "the peer's socket read it", closing
        the MQTT-ack-vs-apply window ADR 016 left open. The inbound
        half of the directed ``cluster.partition`` site sits before
        everything: a dropped message is in-flight loss (no ack, no
        apply), exactly what a blackholed path does."""
        mgr = self.cluster
        if (mgr is None or not packet.topic.startswith("$cluster/")
                or not mgr.is_bridge_client(client)):
            return
        sender = mgr.bridge_peer(client)
        try:
            hit = faults.fire_detail(
                faults.CLUSTER_PARTITION,
                key=faults.partition_key(sender, mgr.node_id))
        except faults.InjectedFault:
            hit = ("drop", 0.0)
        if hit is not None:
            if hit[0] == "hang":
                await asyncio.sleep(hit[1])
            else:
                mgr.partition_drops_in += 1
                return      # lost in flight: no ack, no apply
        # ADR 022: the WAN shape's receive-side loss draw — same
        # in-flight semantics as a partition drop (no ack, no apply),
        # so the sender's blip audit / parked retry machinery sees it
        # as real path loss rather than a link flap. Delay/jitter/rate
        # were already applied on the SENDER's writer; applying only
        # loss here keeps a one-process harness (one fault registry
        # serving both link ends) from shaping the same hop twice.
        shp = faults.REGISTRY.get_shape(
            faults.partition_key(sender, mgr.node_id))
        if shp is not None and shp.lose():
            mgr.shape_drops_in += 1
            faults.REGISTRY.count_fired(
                f"{faults.CLUSTER_SHAPE}#{sender}->{mgr.node_id}")
            return      # shaped loss: no ack, no apply
        if not self._check_publish_qos(client, packet):
            return  # repeated QoS2 id: already re-acked
        self.info.messages_received += 1
        await mgr.handle_inbound(client, packet)
        self._ack_publish(client, packet, success=True)

    @staticmethod
    def _resolve_inbound_alias(client: Client, packet: Packet) -> None:
        """Inbound v5 topic-alias resolution [MQTT-3.3.2-7..12]."""
        if client.properties.protocol_version < 5 or client.aliases is None:
            return
        alias = packet.properties.topic_alias
        if alias is None:
            return
        resolved = client.aliases.resolve_inbound(packet.topic, alias)
        if resolved is None:
            raise ProtocolError(codes.ErrTopicAliasInvalid)
        packet.topic = resolved
        packet.properties.topic_alias = None

    def _select_subscribers(self, subscribers: SubscriberSet,
                            packet: Packet) -> SubscriberSet:
        """Run the on_select_subscribers modify chain without exposing
        the (possibly cached) matched set to mutation.

        Accepts a materialized SubscriberSet or a DeliveryIntents
        (ADR 007) and materializes the cheapest safe form per tier:

        * ``select_subscribers_shared_only`` on every overrider (the
          worker-pool $share ownership filter): the hook only drops
          keys from the OUTER shared dict — shared-free publishes pass
          the set through untouched, shared ones re-wrap that one dict.
        * default: fresh dicts (hooks may add/drop/replace entries
          anywhere) over ALIASED Subscription records — records are
          immutable by contract (hooks/base.py, ADR 009; the churn
          suite's graft check enforces it), so selection-style hooks
          pay O(entries) dict copies built in C, never per-record
          copies.
        * ``select_subscribers_mutates_records`` on any overrider: the
          hook rewrites record fields (qos downgrades etc.) and gets a
          full ``deep_copy()`` per publish."""
        overriders = self.hooks._overriders("on_select_subscribers")
        intents_select = getattr(subscribers, "select_set", None)
        if any(getattr(h, "select_subscribers_mutates_records", False)
               for h in overriders):
            base = (subscribers.to_set() if intents_select is not None
                    else subscribers)
            return self.hooks.modify("on_select_subscribers",
                                     base.deep_copy(), packet)
        if all(getattr(h, "select_subscribers_shared_only", False)
               for h in overriders):
            base = (subscribers.to_set() if intents_select is not None
                    else subscribers)
            if not base.shared:
                return base
            sel = type(base)(base.subscriptions, dict(base.shared))
        elif intents_select is not None:
            sel = intents_select()
        else:
            sel = subscribers.select_copy()
        return self.hooks.modify("on_select_subscribers", sel, packet)

    def _check_publish_qos(self, client: Client, packet: Packet) -> bool:
        """Capability limits + QoS2 dedup + receive quota; False means
        the packet was already re-acked (repeated QoS2 id)."""
        if packet.fixed.qos > self.capabilities.maximum_qos:
            raise ProtocolError(codes.ErrQosNotSupported)
        if packet.fixed.retain and not self.capabilities.retain_available:
            raise ProtocolError(codes.ErrRetainNotSupported)
        # QoS2 dedup: a repeated packet id re-acks without re-delivery
        if packet.fixed.qos == 2 and packet.packet_id in client.pubrec_inbound:
            client.send(Packet(fixed=FixedHeader(type=PT.PUBREC),
                               protocol_version=packet.protocol_version,
                               packet_id=packet.packet_id))
            return False
        if packet.fixed.qos > 0 and not client.inflight.take_receive_quota():
            raise ProtocolError(codes.ErrReceiveMaximumExceeded)
        return True

    def _match_cached(self, topic: str) -> SubscriberSet:
        # safe even with on_select_subscribers hooks installed:
        # _select_subscribers hands hooks fresh dicts (records aliased
        # but immutable per the ADR 009 contract; a declared
        # select_subscribers_mutates_records hook gets a deep copy)
        version = self.topics.sub_version
        hit = self._match_cache.get(topic, version)
        if hit is not None:
            return hit
        result = self.topics.subscribers(topic)
        self._match_cache.put(topic, version, result)
        return result

    def _ack_publish(self, client: Client, packet: Packet, success: bool) -> None:
        qos = packet.fixed.qos
        if qos == 0 or client.inline:
            if qos > 0:
                client.inflight.return_receive_quota()
            return
        reason = 0 if success else codes.ErrNotAuthorized.value
        if qos == 1:
            client.inflight.return_receive_quota()
            self._send_ack(client, PT.PUBACK, packet, reason)
        elif qos == 2:
            if success:
                if packet.packet_id not in client.pubrec_inbound:
                    client.pubrec_inbound.add(packet.packet_id)
                    self._note_pubrec(client, packet.packet_id, True)
                tracer = self.tracer
                if ((tracer.sample_n or tracer.adopted_open)
                        and packet.__dict__.get("_trace") is not None):
                    # ADR 017 (closing the ADR-015 NOT-traced item):
                    # arm the release-leg stopwatch — PUBREC out ->
                    # PUBREL in, observed histogram-only (it waits on
                    # the publisher's network round trip). Bounded by
                    # the sampling stride; the dict dies with the
                    # client and _process_pubrel pops it either way.
                    client._qos2_release_t0[packet.packet_id] = \
                        tracer.clock()
            else:
                client.inflight.return_receive_quota()
            self._send_ack(client, PT.PUBREC, packet, reason)

    def _ack_publish_durable(self, client: Client, packet: Packet) -> None:
        """Release the success ack through the journal's durability
        barrier (ADR 014, ``storage_sync=always``): PUBACK/PUBREC go
        out only once every storage write this publish enqueued —
        retained rewrite + per-subscriber inflight records — has been
        group-committed. The event loop never waits: the barrier is a
        future resolved from the writer thread. A degraded journal
        (breaker open) returns no barrier — a dead disk must not wedge
        every QoS1 publisher.

        Acks drain through a per-client FIFO: a later publish whose
        barrier clears first (or that needed none) must not overtake an
        earlier ack still waiting [MQTT-4.6.0-2]."""
        jr = self._journal
        fut = jr.barrier(self.loop) if jr is not None else None
        if fut is not None:
            # counted here, not at the combined-future wait below: the
            # replication-only case must not inflate the ADR-014 storage
            # metric (sessions keep their own sync_barrier_waits)
            self.storage_barrier_waits += 1
        sessions = self._cluster_sessions()
        if sessions is not None and sessions.ack_coupled:
            # ADR 016: under cluster_session_sync=always the ack also
            # waits for peers to acknowledge the inflight replication
            # covering this publish — that is what a kill-failover to a
            # peer can redeliver. Both barriers are bounded/degradable.
            fut = self._combine_barriers(fut,
                                         sessions.sync_barrier(self.loop))
        if self.cluster is not None and getattr(self.cluster,
                                               "fwd_coupled", False):
            # ADR 018: cross-node publish durability — the ack also
            # waits (bounded) for every peer this publish forwarded to
            # to PUBACK the forward; the peer acks only after its own
            # apply+journal enqueue, so a released PUBACK means the
            # remote subscriber's node holds the message. Timed-out or
            # stranded forwards are parked for retry-after-heal
            # (degraded + counted, never a wedged publisher).
            fut = self._combine_barriers(
                fut, self.cluster.fwd_barrier(self.loop, packet))
        tr = self._packet_trace(packet)
        if tr is not None:
            tr.t_barrier = self.tracer.clock()
        if fut is None and not client.pending_durable_acks:
            self._ack_traced(client, packet, True, tr)
            return
        client.pending_durable_acks.append((fut, packet, True))
        if fut is None:
            self._drain_durable_acks(client)
        else:
            fut.add_done_callback(
                lambda _f: self._drain_durable_acks(client))

    def _needs_durable_ack(self, client: Client, packet: Packet) -> bool:
        """True when this publish's QoS ack must release through a
        barrier: the ADR-014 journal fsync (storage_sync=always) and/or
        the ADR-016 peer-replication ack (cluster_session_sync=always)."""
        if packet.fixed.qos == 0 or client.inline:
            return False
        if self._journal is not None and self._journal.barrier_needed:
            return True
        if (self.cluster is not None
                and getattr(self.cluster, "fwd_coupled", False)
                and self.cluster.links):
            return True     # ADR 018: the fwd leg may owe a barrier
        sessions = self._cluster_sessions()
        return sessions is not None and sessions.ack_coupled

    def _combine_barriers(self, a, b):
        """AND of two optional barrier futures (journal durability +
        session replication, ADR 014/016): resolves once both have."""
        if a is None or b is None:
            return a if b is None else b
        both = self.loop.create_future()

        def _one(_f) -> None:
            if a.done() and b.done() and not both.done():
                both.set_result(None)

        a.add_done_callback(_one)
        b.add_done_callback(_one)
        return both

    def _ack_traced(self, client: Client, packet: Packet, success: bool,
                    tr) -> None:
        """Release one (possibly traced) publish ack: the barrier span
        closes when the ack is unparked, the ack span covers its wire
        build/enqueue, and the trace finishes here — the publisher's
        terminal stage."""
        if tr is None:
            self._ack_publish(client, packet, success=success)
            return
        tracer = self.tracer
        now = tracer.clock()
        if tr.t_barrier:
            tr.span("barrier", tr.t_barrier, now)
        self._ack_publish(client, packet, success=success)
        tr.span("ack", now, tracer.clock())
        tracer.finish(tr)

    def _ack_publish_ordered(self, client: Client, packet: Packet,
                             success: bool) -> None:
        """A barrier-free ack (ACL refusal, rejected publish) that must
        still honor per-client ack order: if earlier acks are parked on
        a barrier, queue behind them instead of overtaking."""
        if not client.pending_durable_acks:
            self._ack_publish(client, packet, success)
            return
        client.pending_durable_acks.append((None, packet, success))

    def _drain_durable_acks(self, client: Client) -> None:
        q = client.pending_durable_acks
        while q and (q[0][0] is None or q[0][0].done()):
            _fut, packet, success = q.popleft()
            self._ack_traced(client, packet, success,
                             self._packet_trace(packet))

    def _send_ack(self, client: Client, ptype: int, packet: Packet,
                  reason: int) -> None:
        """QoS acks run once per QoS>0 publish: a success ack is a fixed
        4-byte wire (v5 elides the zero reason code + empty properties,
        [MQTT-3.4.2.1]), built directly unless a hook watches the encode
        or sent events."""
        pid = packet.packet_id
        if reason == 0 and not self.hooks.overrides("on_packet_encode") \
                and not self.hooks.overrides("on_packet_sent"):
            # PUBACK/PUBREC/PUBCOMP only (flags 0). Broker-side PUBREL
            # cannot take this path: it needs an inflight Packet copy
            # for resend (_process_pubrec).
            client.send_wire(bytes((ptype << 4, 2, pid >> 8, pid & 0xFF)))
            return
        client.send(Packet(fixed=FixedHeader(type=ptype),
                           protocol_version=packet.protocol_version,
                           packet_id=pid, reason_code=reason))

    def retain_message(self, client: Client, packet: Packet) -> None:
        stored = self.topics.retain(packet.copy())
        self.info.retained += stored
        self._note_retained_expiry(packet)
        self.hooks.notify("on_retain_message", client, packet, stored)

    # ------------------------------------------------------------------
    # PUBLISH fan-out — the hot loop the TPU matcher accelerates
    # ------------------------------------------------------------------

    # bound on publishes awaiting fan-out; a full queue backpressures the
    # offending connection's read loop instead of growing without limit
    PUB_PIPELINE_BOUND = 8192

    async def _enqueue_publish(self, client: Client, packet: Packet,
                               durable_ack: bool = False) -> None:
        """Matcher-mode publish path: start the match immediately (the
        batcher coalesces concurrent ones into device batches) and queue
        the (future, packet) pair for the in-order fan-out consumer.
        ``durable_ack`` carries the ADR-014 barrier obligation: the
        consumer acks after fan-out, through the journal barrier."""
        if self._pub_consumer is None:
            if not self._running:
                # late publish after close() tore the pipeline down (the
                # queue is already drained, so order can't be violated):
                # serve it synchronously off the CPU trie
                self._fan_out(self.topics.subscribers(packet.topic), packet)
                self.hooks.notify("on_published", client, packet)
                if durable_ack:
                    self._ack_publish_durable(client, packet)
                return
            self._pub_queue = asyncio.Queue(maxsize=self.PUB_PIPELINE_BOUND)
            self._pub_consumer = self.loop.create_task(
                self._pub_pipeline_loop(), name="publish-pipeline")
        fut = self._dispatch_match(packet.topic)
        tr = self._packet_trace(packet)
        if tr is not None:
            tr.t_match = self.tracer.clock()
        await self._pub_queue.put((fut, client, packet, durable_ack))

    def _dispatch_match(self, topic: str) -> asyncio.Future:
        enq = getattr(self.matcher, "enqueue", None)
        if enq is not None:
            return enq(topic)
        return asyncio.ensure_future(self._match_async(topic))

    async def _pub_pipeline_loop(self) -> None:
        """Drain the publish pipeline in arrival order: await each match
        result, fan out, fire on_published. A matcher failure degrades
        that one publish to the CPU trie — delivery never silently drops.

        With content subscriptions registered (ADR 023) the loop drains
        every already-queued publish into one flush — bounded by
        filter_batch_max — so the content plane decodes payloads and
        evaluates every (publish x predicate) pair in one vectorized
        pass; arrival order is preserved end to end. With the plane
        inactive the pre-023 single-item path runs unchanged."""
        while True:
            item = await self._pub_queue.get()
            cp = self.content
            if cp is not None and cp.active:
                batch = [item]
                while len(batch) < cp.batch_max:
                    try:
                        batch.append(self._pub_queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                await self._pub_deliver_batch(batch)
                continue
            fut, client, packet, durable_ack = item
            try:
                subscribers = await self._await_match(fut, packet)
                if self.tracer.sample_n or self.tracer.adopted_open:
                    self._trace_match_spans(fut, packet)
                self._pub_deliver(subscribers, client, packet, durable_ack)
            finally:
                self._pub_queue.task_done()

    async def _await_match(self, fut, packet: Packet):
        """Await one match future with the pipeline's degrade ladder:
        a cancelled future (not a cancelled consumer) or a matcher
        failure serves that one publish from the CPU trie."""
        try:
            return await fut
        except asyncio.CancelledError:
            # CancelledError is a BaseException: catch it
            # explicitly or a batcher-close cancelling a MATCH
            # future kills the consumer. cancelling() (3.11+)
            # distinguishes "we are being cancelled" from "only
            # the future was"; without it, stay conservative.
            me = asyncio.current_task()
            cancelling = getattr(me, "cancelling", None)
            if cancelling is None or cancelling():
                raise
            return self.topics.subscribers(packet.topic)
        except Exception as exc:
            self.matcher_degrades += 1
            self.tracer.note_error("match_device", "matcher_failed")
            tr = self._packet_trace(packet)
            if tr is not None:
                tr.degraded = "pipeline_trie"
            if self.log is not None:
                self.log.with_prefix("broker").error(
                    "matcher failed; trie fallback",
                    topic=packet.topic, error=repr(exc))
            return self.topics.subscribers(packet.topic)

    async def _pub_deliver_batch(self, batch: list) -> None:
        """One content-plane flush (ADR 023): resolve every match in
        arrival order, evaluate the batch's predicate matrix once,
        then deliver in the same order. task_done fires once per item
        even when a resolve raises mid-batch (consumer cancellation)."""
        try:
            resolved = []
            for fut, client, packet, durable_ack in batch:
                subscribers = await self._await_match(fut, packet)
                resolved.append(
                    (fut, subscribers, client, packet, durable_ack))
            cp = self.content
            if cp is not None and cp.active:
                cp.apply([(packet, subscribers)
                          for _f, subscribers, _c, packet, _d in resolved])
            for fut, subscribers, client, packet, durable_ack in resolved:
                if self.tracer.sample_n or self.tracer.adopted_open:
                    self._trace_match_spans(fut, packet)
                self._pub_deliver(subscribers, client, packet, durable_ack)
        finally:
            for _ in batch:
                self._pub_queue.task_done()

    def _trace_match_spans(self, fut, packet: Packet) -> None:
        """ADR 015: decompose the matcher leg of one sampled publish.
        The batcher stamps ``_t_dispatch``/``_t_done`` on the match
        future (the supervisor forwards them), splitting coalescing
        wait from device/trie time; whatever the consumer waited past
        the result — in-order fan-out behind earlier publishes — is
        the pipeline_wait segment."""
        tr = packet.__dict__.get("_trace")
        if tr is None or not tr.t_match:
            return
        tracer = self.tracer
        now = tracer.clock()
        td = getattr(fut, "_t_dispatch", 0)
        tdone = getattr(fut, "_t_done", 0)
        if td:
            tr.span("match_queue", tr.t_match, td)
            tr.span("match_device", td, tdone or now)
        else:
            tr.span("match_device", tr.t_match, tdone or now)
        if tdone and now > tdone:
            tr.span("pipeline_wait", tdone, now)
        rung = getattr(self.matcher, "breaker_state_name", None)
        if rung and rung != "closed":
            tr.degraded = rung      # ADR-011 supervisor rung label

    def _pub_deliver(self, subscribers, client, packet: Packet,
                     durable_ack: bool) -> None:
        """One pipeline delivery: fan out, notify, and (under the
        ADR-014 barrier) release the publisher's ack durably."""
        try:
            self._fan_out(subscribers, packet)
            if client is not None:
                self.hooks.notify("on_published", client, packet)
        except Exception as exc:
            # a raising hook must cost this publish, not the
            # consumer: a dead consumer would wedge every
            # matcher-mode publisher behind a full queue
            self.tracer.note_error("fanout", "hook_error")
            if self.log is not None:
                self.log.with_prefix("broker").error(
                    "publish fan-out failed", topic=packet.topic,
                    error=repr(exc))
        if durable_ack and client is not None:
            # even after a failed fan-out the ack must release (the
            # barrier covers what DID get written) or the publisher
            # wedges behind a PUBACK that never comes
            self._ack_publish_durable(client, packet)
        else:
            tr = self._packet_trace(packet)
            if tr is not None:
                self.tracer.finish(tr)

    async def publish_to_subscribers(self, packet: Packet) -> None:
        """Parity: v2/server.go:766-868. Matching goes through the pluggable
        matcher (TPU NFA) when attached, else the CPU trie; hooks may then
        override via on_select_subscribers, mirroring the reference.

        When the publish pipeline is active, out-of-band producers (wills,
        $SYS, inline/injected publishes) enqueue behind it rather than
        fanning out directly — a will must not overtake its own client's
        still-queued publishes."""
        if self.matcher is not None:
            if self._pub_consumer is not None:
                await self._pub_queue.put(
                    (self._dispatch_match(packet.topic), None, packet,
                     False))
                return
            subscribers = await self._match_async(packet.topic)
        else:
            subscribers = self.topics.subscribers(packet.topic)
        self._fan_out(subscribers, packet)

    def _fan_out(self, subscribers, packet: Packet) -> None:
        """Local fan-out + cluster forwarding (ADR 013). Every publish
        path funnels through here exactly once, so the route-table
        consult happens once per publish regardless of matcher mode —
        and the ADR-015 fanout/bridge spans are stamped once too."""
        cp = self.content
        if (cp is not None and cp.active
                and "_content_skip" not in packet.__dict__):
            # trie-path / will / $SYS / inline publishes reach here
            # without riding the pipeline flush: evaluate them as a
            # single-packet batch (the pipeline path already stamped
            # its packets, which is what the sentinel key records)
            cp.apply(((packet, subscribers),))
        tr = self._packet_trace(packet)
        if tr is None:
            self._fan_out_local(subscribers, packet)
            if self.cluster is not None:
                self.cluster.maybe_forward(packet)
            return
        clock = self.tracer.clock
        t0 = clock()
        self._fan_out_local(subscribers, packet)
        t1 = clock()
        tr.span("fanout", t0, t1)
        if self.cluster is not None:
            self.cluster.maybe_forward(packet)
            tr.span("bridge", t1, clock())

    def _fan_out_local(self, subscribers, packet: Packet) -> None:
        """Sync fan-out half (no awaits): shared-group selection + per-
        subscriber delivery. The trie path calls it directly so a QoS0
        publish costs no extra coroutine hop.

        ``subscribers`` is either a SubscriberSet or a DeliveryIntents
        (ADR 007: the native decode's fan-out-ready form — iterable of
        (cid, sub) with a ``shared`` dict and ``has_client``). Intents
        skip the merged-dict materialization on the hot path; the hook
        override path materializes the cheapest safe SubscriberSet form
        via _select_subscribers' tiers."""
        to_set = getattr(subscribers, "to_set", None)
        if self.hooks.overrides("on_select_subscribers"):
            # shared_only hooks (the worker-pool $share ownership
            # filter) only drop keys from the outer shared dict: on a
            # shared-free intents result they are identity, so the fast
            # path survives — pool deployments must not pay set
            # materialization on every publish
            shared_only = to_set is not None and all(
                getattr(h, "select_subscribers_shared_only", False)
                for h in self.hooks._overriders("on_select_subscribers"))
            if not (shared_only and len(subscribers) == subscribers.n):
                subscribers = self._select_subscribers(subscribers, packet)
                to_set = None
        if to_set is None:
            shared = subscribers.shared
            if shared:
                plain = subscribers.subscriptions
                self._fan_out_shared(shared, plain.__contains__, packet)
            for cid, sub in subscribers.subscriptions.items():
                self._publish_to_client(cid, sub, packet, shared=False)
            return
        # intents fast path: flat entries, no dict in sight
        if len(subscribers) != subscribers.n:   # any shared candidates?
            self._fan_out_shared(subscribers.shared,
                                 subscribers.has_client, packet)
        for cid, sub in subscribers:
            self._publish_to_client(cid, sub, packet, shared=False)

    def _fan_out_shared(self, shared, has_plain, packet: Packet) -> None:
        """$share: pick one member per (group, filter), merging per
        client; a client already receiving a plain delivery is skipped
        [MQTT-4.8.2-4]."""
        selected: dict[str, Subscription] = {}
        sessions = self._cluster_sessions()
        token = None
        if (sessions is not None
                and sessions.manager.routes.shares.balance == "weighted"):
            # ADR 018: fairness-aware cluster $share — every node
            # derives the same per-publish token from the same bytes,
            # so the weighted rotation stays exactly-once cluster-wide
            # (pin mode never reads it: skip the payload hash)
            token = crc32(packet.payload,
                          crc32(packet.topic.encode()))
        for (group, filt), candidates in shared.items():
            if sessions is not None and not sessions.owns_share(
                    group, filt, token):
                # ADR 016/018: cluster-wide $share — another node owns
                # this (group, filter) pick for this publish; its
                # forward copy delivers there, so the group receives
                # the publish exactly once cluster-wide
                continue
            pick = self.topics.select_shared(
                group, filt, candidates,
                alive=lambda cid: (c := self.clients.get(cid)) is not None
                and not c.closed)
            if pick is not None:
                cid, sub = pick
                prev = selected.get(cid)
                if prev is None or sub.qos > prev.qos:
                    selected[cid] = sub
        for cid, sub in selected.items():
            if not has_plain(cid):
                self._publish_to_client(cid, sub, packet, shared=True)

    async def _match_async(self, topic: str) -> SubscriberSet:
        async_fn = getattr(self.matcher, "subscribers_async", None)
        if async_fn is not None:
            return await async_fn(topic)
        result = self.matcher.subscribers(topic)
        if asyncio.iscoroutine(result):
            result = await result
        return result

    def _fast_qos0_eligible(self, client: Client, sub: Subscription,
                            packet: Packet) -> bool:
        """True when the delivered packet carries no per-subscriber state
        (qos 0 out, retain cleared, no v5 subscription ids / aliases) —
        its wire bytes are then IDENTICAL for every such subscriber and
        ONE shared bytes object serves them all. Per-subscriber feature
        flags no longer force the copy+encode slow path: they select
        the patched-template strategy instead (_send_template_qos0 /
        _send_template_qos, ADR 019). Disabled when any hook watches
        the encode/sent events."""
        return (min(packet.fixed.qos, sub.qos,
                    self.capabilities.maximum_qos) == 0
                and not client.closed
                and not (sub.retain_as_published and packet.fixed.retain)
                and not (client.properties.protocol_version >= 5
                         and (sub.identifiers or sub.identifier
                              or client.properties.topic_alias_maximum))
                and not client.properties.maximum_packet_size
                and not self.hooks.overrides("on_packet_encode")
                and not self.hooks.overrides("on_packet_sent"))

    @staticmethod
    def _delivery_form(packet: Packet, version: int) -> Packet:
        """The normalized QoS0 delivery copy (what the fast path encodes
        and what drop hooks observe)."""
        out = packet.copy()
        out.protocol_version = version
        out.fixed.qos = 0
        out.fixed.dup = False
        out.fixed.retain = False
        out.packet_id = 0
        if version >= 5:
            out.properties.subscription_ids = []
            out.properties.topic_alias = None
        else:
            out.properties = type(out.properties)()
        return out

    def _send_fast_qos0(self, client: Client, packet: Packet) -> None:
        """Encode once per (packet, version) and enqueue raw bytes —
        per-subscriber copy + encode is the dominant fan-out cost."""
        version = client.properties.protocol_version
        cache = packet.__dict__.get("_wire0")
        if cache is None:
            cache = {}
            packet.__dict__["_wire0"] = cache
        wire = cache.get(version)
        if wire is None:
            if version < 5 or packet.properties.is_empty():
                # direct wire build — the common no-properties delivery
                # needs no Packet/Properties copies at all
                tb = packet.topic.encode()
                body = bytearray(len(tb).to_bytes(2, "big"))
                body += tb
                if version >= 5:
                    body.append(0)          # empty properties block
                body += packet.payload
                wire_b = bytearray([0x30])  # PUBLISH, qos0/dup0/retain0
                write_varint(wire_b, len(body))
                wire = bytes(wire_b + body)
            else:
                wire = self._delivery_form(packet, version).encode()
            cache[version] = wire
            self.overload.template_builds += 1
        if not client.send_wire(wire):
            self.info.messages_dropped += 1
            if self.hooks.overrides("on_publish_dropped"):
                self.hooks.notify("on_publish_dropped", client,
                                  self._delivery_form(packet, version))
            return
        # ADR 019 ledger: the single shared bytes object is enqueued
        # per subscriber — every delivered byte is reused, none copied
        self.overload.template_sends += 1
        self.overload.shared_bytes += len(wire)
        if self.tracer.sample_n or self.tracer.adopted_open:
            self._trace_drain(client, packet)

    def _trace_drain(self, client: Client, packet: Packet) -> None:
        """ADR 015: register one subscriber's enqueue->flush watcher on
        the ORIGINAL publish's trace (delivery copies don't alias it);
        the client's writer task settles it after its next flush, so
        the span crosses into the writer-task domain."""
        tr = packet.__dict__.get("_trace")
        if tr is not None and tr.n_drain < MAX_DRAIN_SPANS:
            tr.n_drain += 1
            client._drain_traces.append(
                (tr, self.tracer.clock(), client.outbound.enqueued))

    def _template_eligible(self, client: Client) -> bool:
        """ADR 019: per-subscriber frame variation (QoS flags, packet
        id, v5 subscription ids / topic alias / retain-as-published /
        max-packet-size) selects a patch strategy over the shared wire
        template instead of the per-subscriber copy+encode. Encode/sent
        hook overrides force the slow path — those hooks must observe
        each delivery as a real mutable Packet — and so does an
        instance-patched ``send``/``send_buffers`` (the embedder/test
        seam for intercepting shaped deliveries)."""
        d = client.__dict__
        return ("send" not in d and "send_buffers" not in d
                and not self.hooks.overrides("on_packet_encode")
                and not self.hooks.overrides("on_packet_sent"))

    def _template_for(self, packet: Packet, version: int):
        """The (packet, version) shared template, counted on first
        build (the ledger term the fan-out bench divides by)."""
        cache = packet.__dict__.get("_tmpl")
        if cache is None or (5 if version >= 5 else 4) not in cache:
            self.overload.template_builds += 1
        return wire.publish_template(packet, version)

    def _send_template_qos0(self, client: Client, sub: Subscription,
                            packet: Packet) -> bool:
        """One QoS0 delivery whose frame VARIES per subscriber
        (retain-as-published, v5 subscription ids / topic alias, a
        client max-packet-size to honor): patch the shared template
        instead of copy+encode (ADR 019). Returns False to fall back
        to the per-subscriber encode — only when the worst-case frame
        could exceed the client's maximum packet size, decided BEFORE
        any outbound alias is consumed so the fallback's own
        ``assign_outbound`` is the only assignment."""
        version = client.properties.protocol_version
        tmpl = self._template_for(packet, version)
        retain = bool(sub.retain_as_published and packet.fixed.retain)
        ids: list = []
        alias = None
        alias_topic = False
        mid = b""
        if version >= 5:
            ids = sorted(set(sub.identifiers.values())
                         or ({sub.identifier} if sub.identifier
                             else set()))
            mid = wire.sid_alias_seg(ids, None)
            aliases_on = (client.aliases is not None
                          and client.properties.topic_alias_maximum)
            mps = client.properties.maximum_packet_size
            if mps and tmpl.frame_size(
                    len(mid) + (3 if aliases_on else 0), False) > mps:
                return False    # encode_under may shed user properties
            if aliases_on:
                a, first = client.aliases.assign_outbound(packet.topic)
                if a:
                    alias = a
                    alias_topic = not first
                    mid = wire.sid_alias_seg(ids, alias)
        bufs, size = tmpl.patch(0, retain, 0, mid, alias_topic,
                                native=self.capabilities.native_encode)
        if not client.send_buffers(bufs, size):
            self.info.messages_dropped += 1
            if self.hooks.overrides("on_publish_dropped"):
                out = self._delivery_form(packet, version)
                out.fixed.retain = retain
                if version >= 5:
                    out.properties.subscription_ids = ids
                    out.properties.topic_alias = alias
                    if alias_topic:
                        out.topic = ""
                self.hooks.notify("on_publish_dropped", client, out)
            return True
        overload = self.overload
        overload.template_sends += 1
        overload.shared_bytes += tmpl.shared_len
        overload.copied_bytes += size - tmpl.shared_len
        if self.tracer.sample_n or self.tracer.adopted_open:
            self._trace_drain(client, packet)
        return True

    def _send_template_qos(self, client: Client, out: Packet,
                           packet: Packet) -> bool:
        """One QoS>0 first transmission patched from the shared
        template (ADR 019). ``out`` is the inflight-registered shaped
        copy from _build_outbound — the patch derives flags, packet id
        and the spliced v5 segment from it, so session resume, DUP
        resends and the ack state machines keep operating on real
        Packets. Returns False to fall back to _send_outbound (frame
        over the client's max packet size: encode_under may still
        save it by shedding user properties)."""
        version = client.properties.protocol_version
        tmpl = self._template_for(packet, version)
        mid = b""
        alias_topic = False
        if version >= 5:
            pr = out.properties
            mid = wire.sid_alias_seg(pr.subscription_ids,
                                     pr.topic_alias)
            alias_topic = not out.topic
        bufs, size = tmpl.patch(out.fixed.qos, out.fixed.retain,
                                out.packet_id, mid, alias_topic,
                                native=self.capabilities.native_encode)
        mps = client.properties.maximum_packet_size
        if mps and size > mps:
            return False
        if not client.send_buffers(bufs, size):
            self._count_refused_send(client, out)
            return True
        overload = self.overload
        overload.template_sends += 1
        overload.shared_bytes += tmpl.shared_len
        overload.copied_bytes += size - tmpl.shared_len
        if self.tracer.sample_n or self.tracer.adopted_open:
            self._trace_drain(client, packet)
        return True

    def _publish_to_client(self, client_id: str, sub: Subscription,
                           packet: Packet, shared: bool) -> None:
        """Parity: v2/server.go:795-868 (publishToClient)."""
        client = self.clients.get(client_id)
        if client is None:
            return
        if sub.no_local and packet.origin == client_id:
            return  # v5 NoLocal [MQTT-3.8.3-3]
        skip = packet.__dict__.get("_content_skip")
        if skip is not None and not shared and client_id in skip:
            return  # ADR 023: every claim this client has on the topic
            #         is content-gated and none passed (shared picks
            #         are exempt: $share filters carry no options)
        if self._shed_qos0(client, sub, packet):
            return  # above the high-water mark: QoS0 fan-out shed
        if self._fast_qos0_eligible(client, sub, packet):
            self._send_fast_qos0(client, packet)
            return
        template = self._template_eligible(client)
        if (template and not client.closed
                and min(packet.fixed.qos, sub.qos,
                        self.capabilities.maximum_qos) == 0
                and self._send_template_qos0(client, sub, packet)):
            return

        out = self._build_outbound(client, sub, packet)
        if client.closed and out.fixed.qos == 0:
            return  # QoS0 is not queued for offline clients
        if out.fixed.qos > 0 and not self._enqueue_qos(client, out):
            return  # dropped, exhausted, or parked on send quota
        if client.closed:
            return  # queued in inflight for session resume
        if (template and out.fixed.qos > 0
                and self._send_template_qos(client, out, packet)):
            return
        self._send_outbound(client, out, packet)

    def _send_outbound(self, client: Client, out: Packet,
                       packet: Packet) -> None:
        """Enqueue one shaped delivery: a refusal rolls back (ADR 012),
        an accepted one registers its ADR-015 drain watcher."""
        if not client.send(out):
            self._count_refused_send(client, out)
        elif self.tracer.sample_n or self.tracer.adopted_open:
            self._trace_drain(client, packet)

    def _shed_qos0(self, client: Client, sub: Subscription,
                   packet: Packet) -> bool:
        """Global load-shed (ADR 012): while above the high-water mark
        effective-QoS0 fan-out is shed outright; QoS>0 continues on the
        session/inflight rules."""
        if (not self.overload.shedding or client.closed
                or min(packet.fixed.qos, sub.qos,
                       self.capabilities.maximum_qos) > 0):
            return False
        self.overload.shed_messages += 1
        self.info.messages_dropped += 1
        client.note_drop("shed")
        return True

    def _count_refused_send(self, client: Client, out: Packet) -> None:
        """A delivery the outbound queue/byte budget refused. QoS>0 is
        rolled back so it neither leaks send quota nor leaves a stale
        inflight entry, and counts under its own reason — not the
        generic messages_dropped (docs/migration.md, round 8)."""
        self.hooks.notify("on_publish_dropped", client, out)
        if out.fixed.qos > 0:
            self._rollback_refused_qos(client, out)
        else:
            self.info.messages_dropped += 1

    def _rollback_refused_qos(self, client: Client, out: Packet,
                              release_held: bool = True) -> None:
        """The one QoS>0 rollback invariant (ADR 012): a refused
        delivery leaks nothing — inflight entry dropped, send quota
        returned, counted under qos_drops — and the freed quota is
        offered to any PARKED message, which would otherwise wedge in
        held_pids waiting for an ack that can never come."""
        self.overload.qos_drops += 1
        client.inflight.delete(out.packet_id)
        client.inflight.return_send_quota()
        self.info.inflight -= 1
        if release_held:
            self._release_held(client)

    def _build_outbound(self, client: Client, sub: Subscription,
                        packet: Packet) -> Packet:
        """Shape the delivery copy for one subscriber: effective QoS,
        retain-as-published, and the v5 property set (subscription ids,
        outbound topic alias)."""
        out = packet.copy()
        tr = self._packet_trace(packet)
        if tr is not None:
            # ADR 017: a lightweight (origin, id) tag — NOT the trace
            # itself (delivery copies must not alias the span list) —
            # so downstream hooks (session replication) can correlate
            out._trace_ref = (tr.origin or self.tracer.node_id, tr.id)
        out.protocol_version = client.properties.protocol_version
        out.fixed.qos = min(packet.fixed.qos, sub.qos,
                            self.capabilities.maximum_qos)
        out.fixed.dup = False
        if not sub.retain_as_published:
            out.fixed.retain = False
        if client.properties.protocol_version < 5:
            out.properties = type(out.properties)()
            return out
        ids = sorted(set(sub.identifiers.values())
                     or ({sub.identifier} if sub.identifier else set()))
        out.properties.subscription_ids = ids
        out.properties.topic_alias = None
        if client.aliases is not None and client.properties.topic_alias_maximum:
            alias, first = client.aliases.assign_outbound(out.topic)
            if alias and not first:
                out.properties.topic_alias = alias
                out.topic = ""
            elif alias:
                out.properties.topic_alias = alias
        return out

    def _enqueue_qos(self, client: Client, out: Packet) -> bool:
        """QoS>0 inflight bookkeeping; returns False when the message
        was dropped (cap), exhausted (no free packet id), or parked
        until an ack returns send quota (_release_held)."""
        if len(client.inflight) >= self.capabilities.maximum_inflight:
            self.info.inflight_dropped += 1
            self.hooks.notify("on_qos_dropped", client, out)
            return False
        try:
            out.packet_id = client.next_packet_id()
        except PacketIDExhausted:
            self.hooks.notify("on_packet_id_exhausted", client, out)
            return False
        out.created = time.time()
        client.inflight.set(out.copy())
        self.info.inflight += 1
        if not client.inflight.take_send_quota():
            client.held_pids.append(out.packet_id)
            # ADR 018 (satellite): a quota-parked message is IN the
            # window — notify now so the storage hook journals it and
            # the session federation replicates it (held=True rides the
            # record); the release notifies again, clearing the flag.
            # Without this, a crash or takeover silently dropped every
            # held message (the shared ADR-014/016 NOT-done gap).
            self.hooks.notify("on_qos_publish", client, out,
                              out.created, 0)
            return False
        self.hooks.notify("on_qos_publish", client, out, out.created, 0)
        return True

    # ------------------------------------------------------------------
    # QoS acknowledgement state machines (v2/server.go:909-987)
    # ------------------------------------------------------------------

    def _release_held(self, client: Client) -> None:
        """Send parked QoS messages as send quota becomes available."""
        while client.held_pids:
            if not client.inflight.take_send_quota():
                return
            pid = client.held_pids.popleft()
            held = client.inflight.get(pid)
            if held is None:
                client.inflight.return_send_quota()
                continue
            out = held.copy()
            self.hooks.notify("on_qos_publish", client, out, time.time(), 0)
            if not client.closed and not client.send(out):
                # roll back the whole release: keeping the inflight
                # entry while the quota stayed taken (the pre-ADR-012
                # behavior) leaked quota and wedged a stale entry.
                # release_held=False: the enclosing loop IS the drain.
                self._rollback_refused_qos(client, out,
                                           release_held=False)
                self.hooks.notify("on_publish_dropped", client, out)

    def _process_puback(self, client: Client, packet: Packet) -> None:
        if client.inflight.delete(packet.packet_id):
            self.info.inflight -= 1
            client.inflight.return_send_quota()
            self.hooks.notify("on_qos_complete", client, packet)
            self._release_held(client)

    def _process_pubrec(self, client: Client, packet: Packet) -> None:
        if client.inflight.get(packet.packet_id) is None:
            # unknown id -> PUBREL with not-found (v5)
            # [MQTT-4.3.3-7]; checked before the reason, as the
            # reference does (server.go:926-936)
            client.send(Packet(
                fixed=FixedHeader(type=PT.PUBREL),
                protocol_version=client.properties.protocol_version,
                packet_id=packet.packet_id,
                reason_code=codes.ErrPacketIdentifierNotFound.value
                if client.properties.protocol_version >= 5 else 0))
            return
        if packet.reason_code >= 0x80 or not packet.reason_code_valid():
            # [MQTT-4.3.3-4]: error or out-of-spec reason ends the QoS2
            # flow (MQTT5 §4.13.2 ¶2; reference server.go:930-936)
            if client.inflight.delete(packet.packet_id):
                self.info.inflight -= 1
                client.inflight.return_send_quota()
            self.hooks.notify("on_qos_dropped", client, packet)
            self._release_held(client)
            return
        rel = Packet(fixed=FixedHeader(type=PT.PUBREL),
                     protocol_version=client.properties.protocol_version,
                     packet_id=packet.packet_id)
        rel.created = time.time()
        client.inflight.set(rel.copy())
        client.send(rel)

    def _process_pubrel(self, client: Client, packet: Packet) -> None:
        t0 = client._qos2_release_t0.pop(packet.packet_id, None)
        if t0 is not None:
            # QoS2 release leg (ADR 017): PUBREC sent -> PUBREL
            # received, for sampled publishes only
            self.tracer.observe(
                "release", max(self.tracer.clock() - t0, 0) / 1e9)
        if packet.packet_id not in client.pubrec_inbound:
            # unknown id -> PUBCOMP (not-found on v5) [MQTT-4.3.3-7];
            # checked before the reason, as the reference does
            # (server.go:946-957)
            if client.properties.protocol_version < 5:
                self._send_ack(client, PT.PUBCOMP, packet, 0)
            else:
                client.send(Packet(
                    fixed=FixedHeader(type=PT.PUBCOMP),
                    protocol_version=client.properties.protocol_version,
                    packet_id=packet.packet_id,
                    reason_code=codes.ErrPacketIdentifierNotFound.value))
            return
        client.pubrec_inbound.discard(packet.packet_id)
        self._note_pubrec(client, packet.packet_id, False)
        client.inflight.return_receive_quota()
        if packet.reason_code >= 0x80 or not packet.reason_code_valid():
            # [MQTT-4.3.3-9]: the receiver abandons the inbound QoS2
            # message (reference server.go:951-957)
            self.hooks.notify("on_qos_dropped", client, packet)
            return
        self._send_ack(client, PT.PUBCOMP, packet, 0)
        self.hooks.notify("on_qos_complete", client, packet)

    def _process_pubcomp(self, client: Client, packet: Packet) -> None:
        if client.inflight.delete(packet.packet_id):
            self.info.inflight -= 1
            client.inflight.return_send_quota()
            self.hooks.notify("on_qos_complete", client, packet)
            self._release_held(client)

    # ------------------------------------------------------------------
    # SUBSCRIBE / UNSUBSCRIBE (v2/server.go:990-1129)
    # ------------------------------------------------------------------

    def _process_subscribe(self, client: Client, packet: Packet) -> None:
        packet = self.hooks.modify("on_subscribe", packet, client)
        caps = self.capabilities
        reason_codes: list[int] = []
        counts: list[int] = []
        accepted: list[Subscription] = []
        specs = self._content_specs(client, packet)
        for sub in packet.filters:
            filt = sub.filter
            spec = None
            if specs is not None:
                # ADR 023: split/parse content options (?$expr / ?$agg
                # suffix, or the v5 user-property carriage); malformed
                # options reject THIS filter cleanly
                options = None
                if "?" in filt:
                    filt, _, options = filt.partition("?")
                elif filt in specs:
                    options = specs[filt]
                if options is not None:
                    try:
                        if filt.startswith("$share/"):
                            raise ExprError(
                                "content options on a $share filter")
                        spec = self.content.parse_spec(options)
                    except ExprError:
                        self.content.rejected_subscribes += 1
                        reason_codes.append(
                            codes.ErrTopicFilterInvalid.value)
                        counts.append(0)
                        continue
                    sub.filter = filt   # index/cluster/session all see
                    #                     the base filter from here on
                    # ADR 023/024: the storage hook persists the raw
                    # option string with the subscription record so
                    # the spec survives restart + session restore
                    sub.content_options = options
            if not valid_filter(filt,
                                shared_allowed=caps.shared_sub_available,
                                wildcards_allowed=caps.wildcard_sub_available):
                if not valid_filter(filt):
                    reason_codes.append(codes.ErrTopicFilterInvalid.value)
                elif filt.startswith("$share/"):
                    reason_codes.append(
                        codes.ErrSharedSubscriptionsNotSupported.value)
                else:
                    reason_codes.append(
                        codes.ErrWildcardSubscriptionsNotSupported.value)
                counts.append(0)
                continue
            if filt.startswith("$share/") and sub.no_local:
                # [MQTT-3.8.3-4]: NoLocal on shared subscription is an error
                raise ProtocolError(codes.ErrProtocolViolation,
                                    "no-local shared subscription")
            if not self.hooks.any_allow("on_acl_check", client, filt, False):
                reason_codes.append(codes.ErrNotAuthorized.value)
                counts.append(0)
                continue
            granted = min(sub.qos, caps.maximum_qos)
            sub.qos = granted
            if not caps.sub_id_available:
                sub.identifier = 0
            if spec is not None:
                try:
                    self.content.register(client.id, filt, spec)
                except ContentQuota:
                    # refused BEFORE the topic index sees it: nothing
                    # to roll back, the quota answer is the SUBACK code
                    self.content.rejected_subscribes += 1
                    reason_codes.append(codes.ErrQuotaExceeded.value)
                    counts.append(0)
                    continue
            elif self.content is not None:
                # a plain re-SUBSCRIBE on the same filter replaces any
                # earlier content options (resubscribe semantics)
                self.content.unregister(client.id, filt)
            is_new = self.topics.subscribe(client.id, sub)
            if is_new:
                self.info.subscriptions += 1
            client.subscriptions[filt] = sub
            accepted.append((sub, is_new))
            reason_codes.append(granted)
            counts.append(1 if is_new else 0)
        client.send(Packet(fixed=FixedHeader(type=PT.SUBACK),
                           protocol_version=client.properties.protocol_version,
                           packet_id=packet.packet_id,
                           reason_codes=reason_codes))
        self.hooks.notify("on_subscribed", client, packet, reason_codes, counts)
        self._cluster_note_subs(accepted)
        for sub, is_new in accepted:
            self._publish_retained_to(client, sub, existing=not is_new)

    def _content_specs(self, client: Client,
                       packet: Packet) -> dict[str, str] | None:
        """ADR 023: the v5 user-property carriage of content options —
        each ``maxmq-filter`` property holds ``<filter>?<options>``
        and applies to the matching filter in this SUBSCRIBE. Returns
        None when the content plane is off (then ``?`` stays a plain
        topic character, the documented opt-in)."""
        if self.content is None:
            return None
        out: dict[str, str] = {}
        if client.properties.protocol_version >= 5:
            for key, val in packet.properties.user_properties:
                if key == FILTER_PROP_KEY:
                    base, sep, options = val.partition("?")
                    if sep:
                        out[base] = options
        return out

    def _cluster_note_subs(self, accepted) -> None:
        """Feed brand-new subscriptions into the federation route
        table (ADR 013) so peers learn them as aggregated deltas."""
        if self.cluster is None:
            return
        for sub, is_new in accepted:
            if is_new:
                self.cluster.note_subscribe(sub.filter)

    def _publish_retained_to(self, client: Client, sub: Subscription,
                             existing: bool) -> None:
        """Retained delivery per v5 retain-handling. Shared subscriptions get
        none [MQTT-3.3.1-13]."""
        if sub.filter.startswith("$share/"):
            return
        csub = (self.content.get(client.id, sub.filter)
                if self.content is not None else None)
        if csub is not None and csub.window is not None:
            return  # ADR 023: aggregate subs receive synthesized
            #         window publishes, never the raw retained state
        if sub.retain_handling == 2:
            return
        if sub.retain_handling == 1 and existing:
            return
        if self.overload.shedding:
            # above the high-water mark retained bursts are deferred,
            # not dropped: housekeeping re-runs this delivery once the
            # broker recovers below the low-water mark (ADR 012)
            if (client.id, sub.filter) not in self._deferred_retained:
                self.overload.deferred_retained += 1
            self._deferred_retained[(client.id, sub.filter)] = \
                (sub, existing)
            return
        # delivering now satisfies any parked deferral for this pair —
        # a stale entry would double-deliver at the next drain tick
        self._deferred_retained.pop((client.id, sub.filter), None)
        now = time.time()
        maxexp = self.capabilities.maximum_message_expiry_interval
        for msg in self.topics.retained_for(sub.filter):
            if (csub is not None and csub.pred is not None
                    and not csub.pred.eval_reference(
                        decode_payload(msg.payload))):
                continue    # ADR 023: retained state is predicate-
                #             gated via the scalar reference evaluator
                #             (a cold path; no batch to vectorize)
            if not self._message_expired(msg, now, maxexp):
                self._send_retained(client, sub, msg, now)

    def _send_retained(self, client: Client, sub: Subscription,
                       msg: Packet, now: float) -> None:
        out = msg.copy()
        out.protocol_version = client.properties.protocol_version
        out.fixed.retain = True
        out.fixed.qos = min(out.fixed.qos, sub.qos)
        out.fixed.dup = False
        if out.protocol_version < 5:
            out.properties = type(out.properties)()
        else:
            # retained deliveries carry the establishing subscription's
            # identifier like any forwarded publish [MQTT-3.3.4-3]
            out.properties.subscription_ids = \
                [sub.identifier] if sub.identifier else []
        if out.fixed.qos > 0:
            if len(client.inflight) >= self.capabilities.maximum_inflight:
                self.info.inflight_dropped += 1
                return
            try:
                out.packet_id = client.next_packet_id()
            except PacketIDExhausted:
                return
            out.created = now
            client.inflight.set(out.copy())
            self.info.inflight += 1
            if not client.inflight.take_send_quota():
                # respect the client's receive maximum [MQTT-3.3.4-9];
                # parked retained deliveries persist+replicate like any
                # held message (ADR 018)
                client.held_pids.append(out.packet_id)
                self.hooks.notify("on_qos_publish", client, out, now, 0)
                return
        if client.send(out):
            self.hooks.notify("on_retain_published", client, out)
        elif out.fixed.qos > 0:
            # refused retained delivery: same no-leak rollback as
            # _count_refused_send (ADR 012)
            self._rollback_refused_qos(client, out)

    def _process_unsubscribe(self, client: Client, packet: Packet) -> None:
        packet = self.hooks.modify("on_unsubscribe", packet, client)
        reason_codes = []
        for sub in packet.filters:
            filt = sub.filter
            if self.content is not None:
                if "?" in filt:     # ADR 023: clients unsubscribe with
                    filt = filt.partition("?")[0]  # the suffixed form;
                    #                 the index holds the base filter
                self.content.unregister(client.id, filt)
            existed = self.topics.unsubscribe(client.id, filt)
            if existed:
                self.info.subscriptions -= 1
                if self.cluster is not None:
                    self.cluster.note_unsubscribe(filt)
            client.subscriptions.pop(filt, None)
            reason_codes.append(codes.Success.value if existed
                                else codes.NoSubscriptionExisted.value)
        client.send(Packet(fixed=FixedHeader(type=PT.UNSUBACK),
                           protocol_version=client.properties.protocol_version,
                           packet_id=packet.packet_id,
                           reason_codes=reason_codes))
        self.hooks.notify("on_unsubscribed", client, packet)

    # ------------------------------------------------------------------
    # Wills
    # ------------------------------------------------------------------

    def _queue_will(self, client: Client) -> None:
        will = client.properties.will
        if will is None:
            return
        packet = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=will.qos,
                                          retain=will.retain),
                        topic=will.topic, payload=will.payload,
                        origin=client.id, created=time.time(),
                        properties=will.properties.copy())
        packet.properties.will_delay = None
        delay = client.properties.will_delay
        if delay > 0:
            self._will_delays[client.id] = (time.time() + delay, packet)
        else:
            self._fire_will(client, packet)
        client.properties.will = None

    def _fire_will(self, client: Client | None, packet: Packet) -> None:
        if packet.fixed.retain:
            self.topics.retain(packet.copy())
            self._note_retained_expiry(packet)
        self._spawn(self.publish_to_subscribers(packet), "will-fanout")
        self.hooks.notify("on_will_sent", client, packet)

    # ------------------------------------------------------------------
    # Inline publish / packet injection
    # ------------------------------------------------------------------

    async def publish(self, topic: str, payload: bytes, qos: int = 0,
                      retain: bool = False, **props) -> None:
        """Server-side publish without a network client (InjectPacket
        equivalent, v2/server.go:637-671)."""
        if not valid_topic_name(topic) and not topic.startswith("$"):
            raise ProtocolError(codes.ErrTopicNameInvalid)
        packet = Packet(fixed=FixedHeader(type=PT.PUBLISH, qos=qos,
                                          retain=retain),
                        topic=topic, payload=payload, origin="inline",
                        created=time.time())
        for k, v in props.items():
            setattr(packet.properties, k, v)
        if retain:
            self.topics.retain(packet.copy())
            self._note_retained_expiry(packet)
        await self.publish_to_subscribers(packet)

    async def inject(self, client: Client, packet: Packet) -> None:
        """Process a packet as if ``client`` had sent it over the network."""
        await self._receive_packet(client, packet)

    def new_inline_client(self, client_id: str = "inline") -> Client:
        client = Client(self, None, None, "inline", inline=True)
        client.id = client_id
        return client

    # ------------------------------------------------------------------
    # Housekeeping + $SYS (v2/server.go:284-305, 1185-1237, 1436-1493)
    # ------------------------------------------------------------------

    def disconnect_client(self, client: Client, code: codes.Code) -> None:
        """Send DISCONNECT (v5) before dropping the connection."""
        if client.properties.protocol_version >= 5 and not client.closed:
            client.send_now(Packet(fixed=FixedHeader(type=PT.DISCONNECT),
                                   protocol_version=5,
                                   reason_code=code.value))

    @staticmethod
    def _message_expired(packet: Packet, now: float, maximum: int) -> bool:
        expiry = packet.properties.message_expiry
        if expiry is None:
            expiry = maximum if maximum else 0
        if expiry <= 0:
            return False
        return now > packet.created + expiry

    async def _housekeeping_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(1.0)
                now = time.time()
                mono = time.monotonic()
                self._check_keepalives(mono)
                self._check_client_expiry(now)
                self._check_will_delays(now)
                self._check_expired_retained(now)
                self._check_expired_inflight(now)
                self._check_stalled_writers(mono)
                self._check_overload_recovery()
                if self.content is not None:
                    self.content.tick(now)
        except asyncio.CancelledError:
            pass

    def _check_stalled_writers(self, mono: float) -> None:
        """Slow-consumer policy (ADR 012): a connected client whose
        writer made no progress past the stall deadline while work is
        queued — or whose writer died outright — is disconnected with
        v5 QuotaExceeded/ServerBusy instead of eating drops forever.
        The whole rung is off at stall_deadline_ms = 0, dead-writer
        reaping included (the 'disabled by a zero' contract)."""
        deadline = self.capabilities.stall_deadline_ms / 1000.0
        if deadline <= 0:
            return
        for client in self.clients.connected():
            dead = client.write_error is not None
            stalled = (client.outbound.bytes > 0
                       and mono - client.write_progress > deadline)
            if not (dead or stalled):
                continue
            self.overload.stalled_disconnects += 1
            client.note_drop("stall")
            code = (codes.ErrServerBusy if dead
                    else codes.ErrQuotaExceeded)
            self.disconnect_client(client, code)
            self._spawn(client.stop(ProtocolError(code)), "stall-stop")

    def _check_overload_recovery(self) -> None:
        """Watermark hysteresis backstop + deferred-retained drain: the
        inline note_get path flips shedding off as queues drain, but a
        broker whose queues were released wholesale (client teardown)
        or idled must still recover and deliver parked retained."""
        over = self.overload
        if over.shedding and over.below_low_water():
            over.shedding = False
            over.recoveries += 1
        if over.shedding or not self._deferred_retained:
            return
        for key in list(self._deferred_retained):
            if over.shedding:
                return  # a drained delivery re-entered shedding: stop
            entry = self._deferred_retained.pop(key, None)
            if entry is None:
                continue
            sub, existing = entry
            cid, filt = key
            client = self.clients.get(cid)
            if client is None or filt not in client.subscriptions:
                continue    # session purged or unsubscribed: drop it
            if client.closed:
                # persistent session offline at drain time: keep the
                # delivery parked (no recount) — a resumed session
                # never re-sends SUBSCRIBE, so discarding here would
                # lose the retained message permanently; the entry
                # dies with the session
                self._deferred_retained[key] = entry
                continue
            self._publish_retained_to(client, sub, existing)

    def _check_keepalives(self, mono: float) -> None:
        grace = self.capabilities.keepalive_grace
        for client in self.clients.connected():
            if client.keepalive <= 0:
                continue
            if mono - client.last_received > client.keepalive * grace:
                self.disconnect_client(client, codes.ErrKeepAliveTimeout)
                self._spawn(
                    client.stop(ProtocolError(codes.ErrKeepAliveTimeout)),
                    "keepalive-stop")

    def _check_client_expiry(self, now: float) -> None:
        maximum = self.capabilities.maximum_session_expiry_interval
        for client in self.clients.all():
            if client.closed and client.expired(now, maximum):
                self.hooks.notify("on_client_expired", client)
                self._purge_session(client)

    def _check_will_delays(self, now: float) -> None:
        for cid in list(self._will_delays):
            due, packet = self._will_delays[cid]
            if now >= due:
                del self._will_delays[cid]
                self._fire_will(self.clients.get(cid), packet)

    def _note_retained_expiry(self, packet: Packet) -> None:
        """Index a stored retained message for the expiry sweep: min-heap
        of (due, topic) with lazy revalidation on pop, so each tick costs
        O(due entries) instead of rescanning every retained message (the
        reference sweeps its whole retained map each tick,
        v2/server.go:1436-1476 — a per-second host stall at IoT scale).
        $-topics are broker-owned and never expire (the old '#'-scan
        skipped them the same way)."""
        maximum = self.capabilities.maximum_message_expiry_interval
        if not maximum or packet.topic.startswith("$"):
            return
        if not packet.payload:          # retained CLEAR, from any path
            self._retained_due.pop(packet.topic, None)
            return
        expiry = packet.properties.message_expiry
        if expiry is None:
            expiry = maximum
        if expiry <= 0:
            return
        due = packet.created + expiry
        self._retained_due[packet.topic] = due
        heap = self._retained_expiry
        heapq.heappush(heap, (due, packet.topic))
        if len(heap) > 64 and len(heap) > 4 * len(self._retained_due):
            # compact the lazy-deleted majority: rebuild from the live
            # per-topic dues (bounded by the retained-message count)
            self._retained_expiry = [
                (d, t) for t, d in self._retained_due.items()]
            heapq.heapify(self._retained_expiry)

    def _check_expired_retained(self, now: float) -> None:
        maximum = self.capabilities.maximum_message_expiry_interval
        if not maximum:
            return
        heap = self._retained_expiry
        while heap and heap[0][0] <= now:
            due, topic = heapq.heappop(heap)
            if self._retained_due.get(topic) != due:
                continue        # superseded by a later republish
            self._retained_due.pop(topic, None)   # entry consumed
            p = self.topics.retained_get(topic)
            if p is None or not self._message_expired(p, now, maximum):
                continue        # cleared or replaced since: stale entry
            clear = Packet(fixed=FixedHeader(type=PT.PUBLISH, retain=True),
                           topic=topic, payload=b"")
            self.topics.retain(clear)
            self.info.retained -= 1
            self.hooks.notify("on_retained_expired", topic)

    def _check_expired_inflight(self, now: float) -> None:
        maximum = self.capabilities.maximum_message_expiry_interval
        if not maximum:
            return
        for client in self.clients.all():
            expired = 0
            for packet in client.inflight.all():
                if packet.created > 0 and now > packet.created + maximum:
                    if client.inflight.delete(packet.packet_id):
                        self.info.inflight -= 1
                        self.info.inflight_dropped += 1
                        client.inflight.return_send_quota()
                        expired += 1
                        self.hooks.notify("on_qos_dropped", client, packet)
            if expired and not client.closed:
                # the returned quota must reach parked messages: with
                # nothing left inflight no ack will ever drain held_pids
                self._release_held(client)

    async def _sys_topic_loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.capabilities.sys_topic_interval)
                self.publish_sys_topics()
        except asyncio.CancelledError:
            pass

    def publish_sys_topics(self) -> None:
        """Refresh + retain the $SYS/broker tree. Parity: server.go:1185-1237."""
        info = self.info
        info.time = int(time.time())
        info.uptime = info.time - info.started
        info.retained = self.topics.retained_count
        info.subscriptions = self.topics.subscription_count
        info.memory_alloc = _current_rss_bytes()
        info.threads = threading.active_count()
        self.hooks.notify("on_sys_info_tick", info)
        entries = {
            "$SYS/broker/version": info.version,
            "$SYS/broker/uptime": info.uptime,
            "$SYS/broker/time": info.time,
            "$SYS/broker/started": info.started,
            "$SYS/broker/load/bytes/received": info.bytes_received,
            "$SYS/broker/load/bytes/sent": info.bytes_sent,
            "$SYS/broker/clients/connected": info.clients_connected,
            "$SYS/broker/clients/disconnected": info.clients_disconnected,
            "$SYS/broker/clients/maximum": info.clients_maximum,
            "$SYS/broker/clients/total": info.clients_total,
            "$SYS/broker/messages/received": info.messages_received,
            "$SYS/broker/messages/sent": info.messages_sent,
            "$SYS/broker/messages/dropped": info.messages_dropped,
            "$SYS/broker/messages/inflight": info.inflight,
            # reference spellings (server.go:1214-1216) + our older
            # /count aliases, kept for consumers already scraping them
            "$SYS/broker/retained": info.retained,
            "$SYS/broker/subscriptions": info.subscriptions,
            "$SYS/broker/messages/retained/count": info.retained,
            "$SYS/broker/subscriptions/count": info.subscriptions,
            "$SYS/broker/packets/received": info.packets_received,
            "$SYS/broker/packets/sent": info.packets_sent,
            "$SYS/broker/system/memory": info.memory_alloc,
            "$SYS/broker/system/threads": info.threads,
        }
        entries.update(self._sys_overload_entries())
        if self.cluster is not None:
            entries.update(self._sys_cluster_entries())
        if self._storage_hook is not None:
            entries.update(self._sys_storage_entries())
        if self.tracer.sample_n:
            # ADR 015: the trace subtree appears only while sampling is
            # on — an untraced broker's $SYS surface is unchanged
            trace_entries = self.tracer.sys_entries()
            entries.update(trace_entries)
            self._sys_trace_topics = set(trace_entries)
        elif self._sys_trace_topics:
            # sampling just turned off: clear the subtree's retained
            # entries (empty payload = retained clear) so stale values
            # can't masquerade as live ones
            entries.update((t, "") for t in self._sys_trace_topics)
            self._sys_trace_topics = set()
        for topic, value in entries.items():
            packet = Packet(fixed=FixedHeader(type=PT.PUBLISH, retain=True),
                            topic=topic, payload=str(value).encode(),
                            origin="$SYS", created=time.time())
            self.topics.retain(packet.copy())
            if self.loop is not None:
                self._spawn(self.publish_to_subscribers(packet),
                            "sys-fanout")

    def _sys_overload_entries(self) -> dict:
        """The ADR-012 overload ladder's $SYS subtree, incl. the bounded
        top-offender report under $SYS/broker/clients/."""
        import json
        over = self.overload
        return {
            "$SYS/broker/overload/queued_bytes": over.queued_bytes,
            "$SYS/broker/overload/shedding": int(over.shedding),
            "$SYS/broker/overload/sheds": over.sheds,
            "$SYS/broker/overload/recoveries": over.recoveries,
            "$SYS/broker/overload/shed_messages": over.shed_messages,
            "$SYS/broker/overload/budget_drops": over.budget_drops,
            "$SYS/broker/overload/deferred_retained":
                over.deferred_retained,
            "$SYS/broker/overload/connects_refused":
                over.connects_refused + over.half_open_refused,
            "$SYS/broker/overload/stalled_disconnects":
                over.stalled_disconnects,
            "$SYS/broker/messages/qos_dropped": over.qos_drops,
            "$SYS/broker/clients/top_dropped":
                json.dumps(top_offenders(self.clients.all())),
        }

    def _sys_storage_entries(self) -> dict:
        """The ADR-014 storage-pipeline subtree: journal pressure,
        commit health, breaker state, and what restore had to set
        aside — readable from any MQTT client subscribed to $SYS."""
        hook = self._storage_hook
        entries = {
            "$SYS/broker/storage/boot_epoch": self.boot_epoch,
            "$SYS/broker/storage/quarantined": hook.quarantined,
            "$SYS/broker/storage/journal_sheds": hook.journal_sheds,
            "$SYS/broker/storage/barrier_waits": self.storage_barrier_waits,
        }
        jr = self._journal
        if jr is not None:
            entries.update({
                "$SYS/broker/storage/policy": jr.policy,
                "$SYS/broker/storage/queue_depth": jr.queue_depth,
                "$SYS/broker/storage/queued_bytes": jr.queued_bytes_now,
                "$SYS/broker/storage/commits": jr.commits,
                "$SYS/broker/storage/commit_failures": jr.commit_failures,
                "$SYS/broker/storage/breaker_state": jr.breaker_state,
                "$SYS/broker/storage/degraded_seconds":
                    round(jr.degraded_seconds, 3),
                "$SYS/broker/storage/dirty": int(jr.dirty),
            })
        backing = jr.inner if jr is not None else hook.store
        corruptions = getattr(backing, "corruptions", None)
        if corruptions is not None:
            entries["$SYS/broker/storage/corruptions"] = corruptions
        return entries

    def _sys_cluster_entries(self) -> dict:
        """The ADR-013 federation subtree: link/route health at a
        glance from any MQTT client subscribed to $SYS."""
        mgr = self.cluster
        entries = {
            "$SYS/broker/cluster/node_id": mgr.node_id,
            "$SYS/broker/cluster/links_up": mgr.links_up,
            "$SYS/broker/cluster/link_flaps": mgr.link_flaps,
            "$SYS/broker/cluster/routes_held":
                mgr.routes.remote_route_count,
            "$SYS/broker/cluster/forwards_sent": mgr.forwards_sent,
            "$SYS/broker/cluster/forwards_delivered":
                mgr.forwards_delivered,
            "$SYS/broker/cluster/loops_dropped": mgr.loops_dropped,
            # ADR 018: cross-node publish durability + partition health
            "$SYS/broker/cluster/fwd_parked":
                getattr(mgr, "fwd_parked_now", 0),
            "$SYS/broker/cluster/fwd_parked_resent":
                getattr(mgr, "fwd_parked_resent", 0),
            "$SYS/broker/cluster/fwd_barrier_degraded":
                getattr(mgr, "fwd_barrier_degraded", 0),
            "$SYS/broker/cluster/partition_drops":
                (getattr(mgr, "partition_drops_in", 0)
                 + getattr(mgr, "partition_drops_out", 0)),
            # ADR 020: hop-chained relay durability + blip audit
            "$SYS/broker/cluster/relay_chain_waits":
                getattr(mgr, "relay_chain_waits", 0),
            "$SYS/broker/cluster/relay_chain_timeouts":
                getattr(mgr, "relay_chain_timeouts", 0),
            "$SYS/broker/cluster/blips_detected":
                getattr(mgr, "blips_detected", 0),
            "$SYS/broker/cluster/blip_resyncs":
                getattr(mgr, "blip_resyncs", 0),
            "$SYS/broker/cluster/route_sync_waits":
                getattr(mgr, "route_sync_waits", 0),
            "$SYS/broker/cluster/route_sync_timeouts":
                getattr(mgr, "route_sync_timeouts", 0),
        }
        # ADR 017: per-peer health — link state, staleness, queue
        # pressure, replication lag and the clock-skew estimate, the
        # operator view failover/sharding work is judged against.
        # Bounded to the metrics layer's per-peer series cap.
        entries.update(self._sys_cluster_health_entries(mgr))
        sess = getattr(mgr, "sessions", None)
        if sess is not None:
            # ADR 016: the session-federation subtree — takeover and
            # replication health readable from any MQTT client
            entries.update({
                "$SYS/broker/cluster/sessions/ledger": sess.ledger_size,
                "$SYS/broker/cluster/sessions/local":
                    sess.local_sessions,
                "$SYS/broker/cluster/sessions/takeovers":
                    sess.takeovers,
                "$SYS/broker/cluster/sessions/takeovers_degraded":
                    sess.takeovers_degraded,
                "$SYS/broker/cluster/sessions/lost":
                    sess.sessions_lost,
                "$SYS/broker/cluster/sessions/sync_degraded":
                    sess.sync_degraded,
                "$SYS/broker/cluster/sessions/sync_faults":
                    sess.sync_faults,
                "$SYS/broker/cluster/sessions/share_groups":
                    sess.share_groups,
                # ADR 018: dead-owner lifecycle
                "$SYS/broker/cluster/sessions/replica_expiries":
                    sess.replica_expiries,
                "$SYS/broker/cluster/sessions/wills_fired":
                    sess.wills_fired,
            })
        return entries

    def _sys_cluster_health_entries(self, mgr) -> dict:
        """``$SYS/broker/cluster/health/<peer>/*`` (ADR 017)."""
        from ..metrics import CLUSTER_PEER_SERIES
        entries: dict = {}
        sess = getattr(mgr, "sessions", None)
        now = time.monotonic()
        peers = sorted(mgr.membership.peers.items())[:CLUSTER_PEER_SERIES]
        for peer, st in peers:
            base = f"$SYS/broker/cluster/health/{peer}"
            entries[f"{base}/state"] = int(st.connected)
            entries[f"{base}/last_seen_s"] = (
                round(max(now - st.last_seen, 0.0), 1)
                if st.last_seen else -1)
            entries[f"{base}/flaps"] = st.flaps
            entries[f"{base}/skew_ms"] = round(st.skew_ns / 1e6, 3)
            entries[f"{base}/rtt_ms"] = round(st.rtt_ns / 1e6, 3)
            link = mgr.links.get(peer)
            if link is not None:
                entries[f"{base}/queue_bytes"] = link.outbound.bytes
                # route replication lag: filters the peer should hold
                # but our link has not (successfully) advertised yet
                desired = mgr.routes.advertisement_for(peer)
                entries[f"{base}/route_lag"] = (
                    len(desired) if link.needs_snapshot
                    else len(desired ^ link.advertised))
            if sess is not None:
                entries[f"{base}/sess_lag"] = max(
                    sess._peer_ack_target.get(peer, 0)
                    - sess._peer_acked.get(peer, 0), 0)
        return entries

    # ------------------------------------------------------------------
    # Persistence restore (v2/server.go:1297-1434)
    # ------------------------------------------------------------------

    async def _restore_from_storage(self) -> None:
        self._restore_sessions()
        for rec in self.hooks.first_non_empty("stored_retained_messages"):
            packet = rec.to_packet()
            self.topics.retain(packet)
            self._note_retained_expiry(packet)
            self.info.retained += 1
        for rec in self.hooks.first_non_empty("stored_inflight_messages"):
            client = self.clients.get(rec.client_id)
            if client is not None:
                packet = rec.to_packet()
                client.inflight.set(packet)
                # restored FROM the store: resend-on-resume must not
                # rewrite a byte-identical record (ADR 014)
                client.inflight.note_stored(packet.packet_id)
                self.info.inflight += 1
                if getattr(rec, "held", False):
                    # ADR 018: quota-parked at crash time — re-park, so
                    # the resumed session's _release_held (not resend)
                    # sends it within the client's receive maximum
                    client.held_pids.append(packet.packet_id)
        stored_info = self.hooks.first_non_empty("stored_sys_info")
        if stored_info is not None:
            for k in ("bytes_received", "bytes_sent", "messages_received",
                      "messages_sent", "messages_dropped", "packets_received",
                      "packets_sent", "clients_maximum", "clients_total"):
                setattr(self.info, k, getattr(stored_info, k, 0))
        self._bump_boot_epoch()

    def _restore_sessions(self) -> None:
        for rec in self.hooks.first_non_empty("stored_clients"):
            client = Client(self, None, None, rec.listener)
            client.id = rec.client_id
            client.properties.protocol_version = rec.protocol_version
            client.properties.username = rec.username
            client.properties.clean_start = rec.clean
            client.properties.session_expiry = rec.session_expiry
            client.properties.session_expiry_set = rec.session_expiry_set
            client.disconnected_at = rec.disconnected_at or time.time()
            # a restored session is a DISCONNECTED session: without
            # this, `closed` stays False (stop() never ran on the fresh
            # object), deliveries take the live-send path and are
            # refused+rolled back as slow-consumer drops instead of
            # queueing in inflight for the resume — every message
            # published to the session between restart and reconnect
            # was silently lost (found by the ADR-018 kill-restart
            # verify drive) — and the expiry sweep never purged it
            client._stopped.set()
            self.clients.add(client)
        for rec in self.hooks.first_non_empty("stored_subscriptions"):
            sub = Subscription(filter=rec.filter, qos=rec.qos,
                               no_local=rec.no_local,
                               retain_as_published=rec.retain_as_published,
                               retain_handling=rec.retain_handling,
                               identifier=rec.identifier)
            if self.topics.subscribe(rec.client_id, sub):
                self.info.subscriptions += 1
            client = self.clients.get(rec.client_id)
            if client is not None:
                client.subscriptions[rec.filter] = sub
            options = getattr(rec, "options", "")
            if options and self.content is not None:
                # ADR 023/024: re-register the persisted content spec;
                # a spec this build can't parse (downgrade, tightened
                # caps) degrades THIS subscription to unfiltered,
                # loudly, instead of failing the restore
                try:
                    self.content.register(rec.client_id, rec.filter,
                                          self.content.parse_spec(options))
                except Exception as exc:
                    self.content.rejected_subscribes += 1
                    if self.log is not None:
                        self.log.with_prefix("broker").error(
                            "restored subscription content spec "
                            "rejected; subscription is unfiltered",
                            client=rec.client_id, filter=rec.filter,
                            error=repr(exc)[:200])

    def _bump_boot_epoch(self) -> None:
        """Persisted monotonic boot epoch (ADR 014): strictly increases
        across restarts/kills; the cluster layer (ADR 013) adopts it in
        place of wall-clock epochs. No storage hook (or a failed bump):
        wall-clock ms keeps the pre-ADR-014 behavior."""
        bump = getattr(self._storage_hook, "bump_boot_epoch", None)
        if bump is not None:
            try:
                self.boot_epoch = bump()
            except Exception as exc:
                if self.log is not None:
                    self.log.with_prefix("broker").error(
                        "boot-epoch bump failed", error=repr(exc)[:200])
        if not self.boot_epoch:
            self.boot_epoch = int(time.time() * 1000)

    # non-PUBLISH packet dispatch (PUBLISH stays inline in
    # _process_packet: it is the only async handler and the hot path)
    _DISPATCH = {
        PT.PUBACK: _process_puback,
        PT.PUBREC: _process_pubrec,
        PT.PUBREL: _process_pubrel,
        PT.PUBCOMP: _process_pubcomp,
        PT.SUBSCRIBE: _process_subscribe,
        PT.UNSUBSCRIBE: _process_unsubscribe,
        PT.PINGREQ: _process_pingreq,
        PT.DISCONNECT: _process_disconnect,
        PT.AUTH: _process_auth,
        PT.CONNECT: _process_second_connect,
    }
