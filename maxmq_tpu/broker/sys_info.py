"""$SYS broker statistics counters.

Parity surface: vendor/github.com/mochi-co/mqtt/v2/system/system.go (21 atomic
counters). Plain ints here: mutations happen on the asyncio loop thread and
reads from the metrics scrape thread are tear-free under the GIL.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field


@dataclass
class SysInfo:
    version: str = ""
    started: int = 0            # unix seconds
    time: int = 0               # last refresh, unix seconds
    uptime: int = 0
    bytes_received: int = 0
    bytes_sent: int = 0
    clients_connected: int = 0
    clients_disconnected: int = 0
    clients_maximum: int = 0
    clients_total: int = 0
    messages_received: int = 0
    messages_sent: int = 0
    messages_dropped: int = 0
    retained: int = 0
    inflight: int = 0
    inflight_dropped: int = 0
    subscriptions: int = 0
    packets_received: int = 0
    packets_sent: int = 0
    memory_alloc: int = 0
    threads: int = 0

    extra: dict = field(default_factory=dict)

    def clone(self) -> "SysInfo":
        d = asdict(self)
        d["extra"] = dict(self.extra)
        return SysInfo(**d)
