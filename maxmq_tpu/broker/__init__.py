"""Host-side broker runtime: server engine, sessions, listeners, QoS flows."""

from .client import Client, ClientRegistry, PacketIDExhausted
from .inflight import Inflight
from .listeners import (Listener, Listeners, MockListener, SocketListener,
                        TCPListener, UnixListener, WSListener)
from .server import Broker, BrokerOptions, Capabilities
from .sys_info import SysInfo

__all__ = [
    "Client", "ClientRegistry", "PacketIDExhausted", "Inflight",
    "Listener", "Listeners", "MockListener", "SocketListener",
    "TCPListener", "UnixListener", "WSListener", "Broker",
    "BrokerOptions", "Capabilities", "SysInfo",
]
