"""Host-side broker runtime: server engine, sessions, listeners, QoS flows."""

from .client import Client, ClientRegistry, OutboundQueue, PacketIDExhausted
from .inflight import Inflight
from .listeners import (Listener, Listeners, MockListener, SocketListener,
                        TCPListener, UnixListener, WSListener)
from .overload import OverloadState, TokenBucket
from .server import Broker, BrokerOptions, Capabilities
from .sys_info import SysInfo

__all__ = [
    "Client", "ClientRegistry", "OutboundQueue", "PacketIDExhausted",
    "Inflight", "Listener", "Listeners", "MockListener", "SocketListener",
    "TCPListener", "UnixListener", "WSListener", "OverloadState",
    "TokenBucket", "Broker", "BrokerOptions", "Capabilities", "SysInfo",
]
