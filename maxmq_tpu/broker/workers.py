"""Multi-core delivery: a pool of broker worker processes on one port.

The reference gets per-connection parallelism for free — one goroutine
per client spread over every host core (vendor/github.com/mochi-co/
mqtt/v2/clients.go:190-202, server.go:221). An asyncio broker caps
per-message work (decode, QoS bookkeeping, encode, socket writes) on a
single core. This module is the goroutine answer (ADR 005):

* N worker processes each run the FULL broker (codec, QoS state, fan-
  out, matcher) for the connections the kernel hands them —
  ``SO_REUSEPORT`` shards accepts across workers with no parent in the
  accept path.
* A loopback fan-out bus (unix domain stream hub, length-prefixed
  frames) broadcasts every locally-published message to the other
  workers, which deliver to THEIR local subscribers through their own
  matcher. Retained messages ride the same frames, so every worker's
  retained store converges (same-origin ordering is preserved by the
  per-connection serialization, as in the single-process broker).
* ``$share`` groups spanning workers stay exactly-once via membership
  gossip: each worker broadcasts its (group, filter) local-member
  counts on change; for every publish, the lowest-numbered worker with
  members owns the pick (documented fairness trade in ADR 005).

Scaling expectation: near-linear in delivery-bound workloads up to the
host's core count (this dev box has ONE core, so the functional tests
assert cross-worker semantics, not speedup — see ADR 005's measured
section).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import subprocess
import sys
import time

from .. import faults
from ..hooks.base import Hook
from ..protocol.packets import Packet, parse_stream

FRAME_PUBLISH = 1       # worker_id u8 + encoded v5 PUBLISH wire
FRAME_MEMBERSHIP = 2    # json {w, members: [[group, filter, n], ...]}
FRAME_TAKEOVER = 3      # json {w, cid}: session established elsewhere

BUS_CLIENT_ID = "@bus"  # origin id carried by bus-injected publishes


from ..utils.framing import frame as _frame, read_frame as _read_frame


class FanoutBus:
    """The hub: accepts worker connections on a unix socket and
    broadcasts every frame to all OTHER workers. The hub carries only
    already-encoded bytes — it never parses MQTT.

    A peer whose transport buffer exceeds ``high_water`` is evicted — a
    wedged worker must not grow the hub's memory by the whole publish
    stream. The evicted worker sees bus EOF, exits (split-brain guard),
    and the pool parent's supervision loop respawns it."""

    def __init__(self, path: str, high_water: int = 8 << 20) -> None:
        self.path = path
        self.high_water = high_water
        self._server = None
        self._peers: dict[object, asyncio.StreamWriter] = {}

    async def start(self) -> None:
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        self._server = await asyncio.start_unix_server(self._serve,
                                                       self.path)

    async def _serve(self, reader, writer) -> None:
        key = object()
        self._peers[key] = writer
        try:
            while True:
                frame = await _read_frame(reader)
                if frame is None:
                    break
                ftype, payload = frame
                data = _frame(ftype, payload)
                for k, w in list(self._peers.items()):
                    if k is key:
                        continue
                    try:
                        if (w.transport.get_write_buffer_size()
                                > self.high_water):
                            raise BufferError("peer stalled")
                        w.write(data)
                    except Exception:
                        self._peers.pop(k, None)
                        try:
                            w.close()
                        except Exception:
                            pass
        finally:
            self._peers.pop(key, None)
            try:
                writer.close()
            except Exception:
                pass

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for w in self._peers.values():
            try:
                w.close()
            except Exception:
                pass
        self._peers.clear()


class BusHook(Hook):
    """Worker-side bus endpoint, wired into the broker's hook chain.

    Outbound: every locally-published message (and every will/retained
    publish, which flow through the same fan-out) is forwarded once.
    Inbound: frames are injected through the broker's inline-client
    path, so retained storage, expiry, and local fan-out behave exactly
    as for a locally received publish.
    """

    id = "bus"

    def __init__(self, worker_id: int, bus_path: str) -> None:
        from ..cluster.routes import ShareLedger
        self.worker_id = worker_id
        self.bus_path = bus_path
        self.broker = None
        self._writer: asyncio.StreamWriter | None = None
        self._reader_task: asyncio.Task | None = None
        # $share group-membership ledger — the SAME class the cluster
        # session federation feeds (ADR 016), so a filter shared across
        # both a pool and a peer node resolves ownership through one
        # set of rules (lowest live member id owns the pick). Member
        # ids here are worker ids; gossip wire format is unchanged.
        self.shares = ShareLedger(worker_id)
        self._local: dict[tuple[str, str], int] = {}
        # client id -> its live $share keys (incremental maintenance)
        self._contrib: dict[str, set[tuple[str, str]]] = {}
        self.on_bus_lost = None      # callback: bus EOF -> shut down
        self.bus_lost = False        # latched for pre-wiring EOFs

    # -- lifecycle ----------------------------------------------------

    async def attach(self, broker) -> None:
        self.broker = broker
        reader, self._writer = await asyncio.open_unix_connection(
            self.bus_path)
        self._bus_client = broker.new_inline_client(BUS_CLIENT_ID)
        self._reader_task = asyncio.get_running_loop().create_task(
            self._drain(reader))

    def announce(self) -> None:
        """Initial gossip after the broker is serving (storage restore
        may have loaded sessions): peers learn our state — possibly
        empty, which clears anything stale from a previous incarnation
        of this worker id."""
        for client in self.broker.clients.connected():
            self._update_contrib(client)
        self._gossip()

    def stop(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass

    async def _drain(self, reader) -> None:
        while True:
            frame = await _read_frame(reader)
            if frame is None:
                # bus gone (parent died or evicted us): a worker serving
                # without the bus is split-brained — shut down so the
                # parent restarts us coherently. Latched so an EOF that
                # lands before run_worker wires the callback still stops
                # the worker.
                self.bus_lost = True
                if self.on_bus_lost is not None:
                    self.on_bus_lost()
                return
            ftype, payload = frame
            try:
                if ftype == FRAME_PUBLISH:
                    await self._inject_publish(payload)
                elif ftype == FRAME_MEMBERSHIP:
                    self._absorb_membership(payload)
                elif ftype == FRAME_TAKEOVER:
                    await self._absorb_takeover(payload)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # one bad frame must not kill the bus
                log = getattr(self.broker, "log", None)
                if log is not None:
                    log.with_prefix("bus").error("bus frame failed",
                                                 error=repr(exc))

    # -- publish forwarding -------------------------------------------

    def on_published(self, client, packet: Packet) -> None:
        if client is not None and client.id == BUS_CLIENT_ID:
            return                       # arrived from the bus: no loop
        self._forward(packet)

    def on_will_sent(self, client, packet: Packet) -> None:
        self._forward(packet)            # wills fan out pool-wide too

    def _forward(self, packet: Packet) -> None:
        if self._writer is None or packet.topic.startswith("$"):
            return                       # $SYS stays per-worker (ADR 005)
        wire = self._encode_for_bus(packet, self._bus_trace(packet))
        self._writer.write(_frame(
            FRAME_PUBLISH, bytes([self.worker_id]) + wire))

    def _bus_trace(self, packet: Packet) -> str:
        """ADR 017: a sampled publish's trace identity crosses the
        pool bus as an ``mq-trace`` user property — identity only, no
        clock frame (worker monotonic clocks have per-process epochs),
        so receiving workers open correlated child traces from their
        own arrival time. Empty (and allocation-free) when untraced."""
        tracer = getattr(self.broker, "tracer", None)
        if tracer is None or not (tracer.sample_n
                                  or tracer.adopted_open):
            return ""
        tr = packet.__dict__.get("_trace")
        if tr is None:
            return ""
        return f"{tr.origin or tracer.node_id or 'w%d' % self.worker_id}:{tr.id}"

    @staticmethod
    def _encode_for_bus(packet: Packet, trace_ref: str = "") -> bytes:
        out = packet.copy()
        out.protocol_version = 5
        # a qos>0 wire needs a nonzero pid; the receiving workers
        # allocate real per-client pids at delivery, this one is unused
        out.packet_id = 1 if packet.fixed.qos else 0
        out.fixed.dup = False
        if trace_ref:
            out.properties.user_properties.append(("mq-trace",
                                                   trace_ref))
        return out.encode()

    async def _inject_publish(self, payload: bytes) -> None:
        buf = bytearray(payload[1:])
        for fh, body in parse_stream(buf):
            packet = Packet.decode(fh, body, 5)
            # inline clients skip the per-client QoS inbound machinery;
            # delivery QoS still derives from min(sub.qos, msg qos)
            packet.origin = BUS_CLIENT_ID
            packet.created = time.time()
            tr = self._adopt_bus_trace(packet)
            try:
                if packet.fixed.retain:
                    self.broker.retain_message(self._bus_client, packet)
                await self.broker.publish_to_subscribers(packet)
            except BaseException:
                # a raising fan-out/enqueue must still settle the
                # adopted trace or tracer.adopted_open leaks the
                # stamping gates open (finish is idempotent)
                if tr is not None:
                    self.broker.tracer.finish(tr)
                raise
            if tr is not None and (self.broker.matcher is None
                                   or self.broker._pub_consumer is None):
                self.broker.tracer.finish(tr)

    def _adopt_bus_trace(self, packet: Packet):
        """Open a correlated child trace for a bus injection carrying
        ``mq-trace`` (ADR 017). Identity-only adoption: start is local
        arrival, so the e2e reads bus-arrival -> local-terminal."""
        up = packet.properties.user_properties
        if not up:
            return None
        ref = next((v for k, v in up if k == "mq-trace"), None)
        if ref is None:
            return None
        tracer = getattr(self.broker, "tracer", None)
        if tracer is None:
            return None
        try:
            origin, _sep, tid = ref.rpartition(":")
            now = tracer.clock()
            tr = tracer.adopt(origin or "bus", int(tid), packet.topic,
                              packet.fixed.qos, 1, now)
        except ValueError:
            return None
        tr.span("bridge_in", now, tracer.clock())
        packet._trace = tr
        return tr

    # -- $share ownership gossip --------------------------------------
    #
    # counts track LIVE members only (a worker whose members are all
    # offline must not own the pick — the alive-filter would drop the
    # message pool-wide), maintained incrementally per client event:
    # each event re-derives only THAT client's contribution, O(its
    # subscriptions), never a full index scan.

    def on_subscribed(self, client, packet, reason_codes, counts) -> None:
        self._update_contrib(client)

    def on_unsubscribed(self, client, packet) -> None:
        self._update_contrib(client)

    def on_disconnect(self, client, err, expire: bool) -> None:
        self._update_contrib(client, live=False)

    def on_session_established(self, client, packet) -> None:
        # resumed sessions restore their subscriptions (live again); a
        # fresh session contributes nothing yet, but the takeover frame
        # must fire either way so no other worker keeps the old live
        # session for this id
        self._update_contrib(client)
        if self._writer is not None:
            self._writer.write(_frame(FRAME_TAKEOVER, json.dumps({
                "w": self.worker_id, "cid": client.id}).encode()))

    @staticmethod
    def _client_shared(client) -> set[tuple[str, str]]:
        out = set()
        for filt in client.subscriptions:
            if filt.startswith("$share/"):
                _, group, _ = (filt.split("/", 2) + [""])[:3]
                out.add((group, filt))
        return out

    def _update_contrib(self, client, live: bool = True) -> None:
        if client is None or client.id == BUS_CLIENT_ID:
            return
        new = self._client_shared(client) if live else set()
        old = self._contrib.get(client.id, set())
        if new == old:
            return
        if new:
            self._contrib[client.id] = new
        else:
            self._contrib.pop(client.id, None)
        for key in old - new:
            n = self._local.get(key, 0) - 1
            if n > 0:
                self._local[key] = n
            else:
                self._local.pop(key, None)
        for key in new - old:
            self._local[key] = self._local.get(key, 0) + 1
        self._gossip()

    def _gossip(self) -> None:
        if self._writer is None:
            return
        # keep our own view coherent too (we never hear our own gossip)
        self.shares.replace_member(self.worker_id, self._local)
        self._writer.write(_frame(FRAME_MEMBERSHIP, json.dumps({
            "w": self.worker_id,
            "members": [[g, f, n] for (g, f), n in self._local.items()],
        }).encode()))

    async def _absorb_takeover(self, payload: bytes) -> None:
        """Another worker established a session for this client id: any
        live local session with that id is taken over [MQTT-3.1.4-2]."""
        from ..protocol import codes
        from ..protocol.packets import ProtocolError

        msg = json.loads(payload)
        client = self.broker.clients.get(msg["cid"])
        if client is None or client.closed:
            return
        client.taken_over = True
        self.broker.disconnect_client(client, codes.ErrSessionTakenOver)
        await client.stop(ProtocolError(codes.ErrSessionTakenOver))

    def _absorb_membership(self, payload: bytes) -> None:
        msg = json.loads(payload)
        w = int(msg["w"])
        self.shares.replace_member(
            w, {(g, f): int(n) for g, f, n in msg["members"]})

    def _owns(self, group: str, filt: str) -> bool:
        # no gossip yet: the ledger answers True (origin delivers) —
        # at worst a short double-delivery window at startup
        return self.shares.owns((group, filt))

    # declares that on_select_subscribers only drops keys from the
    # outer ``shared`` dict, letting the broker skip the per-record
    # deep copy on shared-free publishes (the hot path)
    select_subscribers_shared_only = True

    def on_select_subscribers(self, subscribers, packet):
        if not subscribers.shared:
            return subscribers
        drop = [key for key in subscribers.shared
                if not self._owns(*key)]
        if drop:
            for key in drop:
                del subscribers.shared[key]
        return subscribers


async def run_worker(conf, logger, worker_id: int, bus_path: str,
                     ready: asyncio.Event | None = None,
                     stop: asyncio.Event | None = None) -> None:
    """One pool worker: the standard bootstrap broker + BusHook, with
    the TCP listener bound SO_REUSEPORT (build_broker does that when
    conf.workers > 1)."""
    import dataclasses

    from ..bootstrap import build_broker, build_metrics

    if worker_id != 0:
        # SO_REUSEPORT shards the TCP/WS listeners; the unix-socket and
        # $SYS-HTTP listeners (and metrics) cannot share an address, so
        # worker 0 owns them
        conf = dataclasses.replace(conf, mqtt_unix_socket="",
                                   mqtt_sys_http_address="")
    broker = build_broker(conf, logger)
    hook = BusHook(worker_id, bus_path)
    broker.add_hook(hook)
    if conf.matcher == "service":
        # pool workers share ONE chip-owning matcher service (ADR 005):
        # every worker forwards its own clients' subscription ops and
        # all workers' match requests coalesce on the service's batcher
        # — each behind its own ADR-011 supervisor unless opted out
        # (same wiring as the single-process boot, one source of truth)
        from ..bootstrap import _maybe_attach_service
        await _maybe_attach_service(conf, broker)
    metrics = build_metrics(conf, broker, logger) if worker_id == 0 else None
    # bus first, listeners second: a client accepted before the bus is
    # connected would publish into a void
    await hook.attach(broker)
    await broker.serve()
    hook.announce()
    if metrics is not None:
        metrics.start()
    logger.with_prefix("worker").info("pool worker started",
                                      worker=worker_id)
    if ready is not None:
        ready.set()
    if stop is None:
        stop = asyncio.Event()
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
    hook.on_bus_lost = stop.set      # parent died: don't serve split-brained
    if hook.bus_lost:
        stop.set()                   # EOF landed before the wiring
    if faults.fire(faults.POOL_WORKER):
        # injected worker death (ADR 011 fault suite; armed through the
        # MAXMQ_FAULTS env the pool parent propagates): exit now so the
        # parent's supervision loop observes the crash and respawns us
        stop.set()
    try:
        await stop.wait()
    finally:
        hook.stop()
        await broker.close()
        if metrics is not None:
            metrics.stop()


class PoolStats:
    """Supervision counters for one pool parent, exported as the
    ``maxmq_pool_*`` family (metrics.register_pool_metrics)."""

    def __init__(self) -> None:
        self.worker_restarts = 0


# process-wide default (one pool parent per process); tests construct
# their own and pass it to _supervise_workers
POOL_STATS = PoolStats()


async def _supervise_workers(procs, spawn, boot, stats: PoolStats = None,
                             interval: float = 2.0) -> None:
    """A worker that dies (crash, bus eviction, OOM kill) is logged,
    counted (stats.worker_restarts -> maxmq_pool_worker_restarts_total),
    and respawned — the pool must not silently degrade to N-1.
    Throttled per slot so a crash loop can't fork-bomb the host."""
    stats = stats if stats is not None else POOL_STATS
    last_spawn = [0.0] * len(procs)
    while True:
        await asyncio.sleep(interval)
        for i, p in enumerate(procs):
            rc = p.poll()
            if rc is None:
                continue
            wait = max(0.0, 5.0 - (time.monotonic() - last_spawn[i]))
            boot.error("pool worker exited; restarting", worker=i,
                       rc=rc, backoff_s=round(wait, 1))
            if wait:
                await asyncio.sleep(wait)
            last_spawn[i] = time.monotonic()
            procs[i] = spawn(i)
            stats.worker_restarts += 1


@contextlib.asynccontextmanager
async def inprocess_pool(n: int = 2, bus_path: str | None = None):
    """N pool workers in ONE process: the same Broker/BusHook/FanoutBus
    objects the subprocess pool runs, minus the process boundary (which
    only the kernel's SO_REUSEPORT accept sharding cares about). Yields
    (brokers, ports). Used by the cross-worker test suite and the
    overhead measurement harness (tools/measure_pool.py); also the
    embedding surface for hosts that want a pool without subprocesses."""
    bus_path = bus_path or f"/tmp/maxmq-bus-inproc-{os.getpid()}.sock"
    bus = FanoutBus(bus_path)
    await bus.start()
    brokers, hooks, ports = [], [], []
    try:
        for i in range(n):
            from ..hooks import AllowHook
            from .listeners import TCPListener
            from .server import Broker, BrokerOptions, Capabilities
            b = Broker(BrokerOptions(capabilities=Capabilities(
                sys_topic_interval=0)))
            b.add_hook(AllowHook())
            hook = BusHook(i, bus_path)
            b.add_hook(hook)
            lst = b.add_listener(TCPListener(f"tcp{i}", "127.0.0.1:0"))
            await b.serve()
            await hook.attach(b)
            brokers.append(b)
            hooks.append(hook)
            ports.append(lst._server.sockets[0].getsockname()[1])
        yield brokers, ports
    finally:
        for h in hooks:
            h.stop()
        for b in brokers:
            await b.close()
        await bus.close()
        with contextlib.suppress(FileNotFoundError):
            os.unlink(bus_path)


def _worker_spawner(env: dict):
    """Build the pool's spawn(i) closure, scoping pool.worker faults
    (ADR 011 drills) to mean "kill A worker", not "kill every worker
    forever": MAXMQ_FAULTS is parsed at import in EACH subprocess, so
    an unscoped spec would re-arm in all N workers AND in every
    respawned replacement — a throttled permanent crash loop instead
    of a kill-once/recover drill. The first spawn keeps the
    pool.worker entries; every other spawn (other slots, and all
    respawns) gets them stripped."""
    fault_spec = env.get("MAXMQ_FAULTS", "")
    entries = [e.strip() for e in fault_spec.split(",") if e.strip()]
    kept = ",".join(e for e in entries
                    if not e.startswith(faults.POOL_WORKER))
    has_kill = any(e.startswith(faults.POOL_WORKER) for e in entries)
    delivered = [not has_kill]    # nothing to scope -> strip never

    def spawn(i: int):
        wenv = dict(env)
        wenv["MAXMQ_WORKER_ID"] = str(i)
        if fault_spec and delivered[0]:
            if kept:
                wenv["MAXMQ_FAULTS"] = kept
            else:
                wenv.pop("MAXMQ_FAULTS", None)
        delivered[0] = True
        return subprocess.Popen(
            [sys.executable, "-m", "maxmq_tpu", "start", "--no-banner"],
            env=wenv)

    return spawn


async def run_pool(conf, logger, ready: asyncio.Event | None = None,
                   stop: asyncio.Event | None = None) -> None:
    """The pool parent: fan-out bus + N worker subprocesses. The parent
    never touches a client socket — the kernel (SO_REUSEPORT) shards
    accepts directly onto the workers."""
    from ..utils.config import config_as_dict

    boot = logger.with_prefix("pool")
    bus_path = f"/tmp/maxmq-bus-{os.getpid()}.sock"
    bus = FanoutBus(bus_path)
    await bus.start()

    env = dict(os.environ)
    env["MAXMQ_BUS"] = bus_path
    env["MAXMQ_POOL_CONF"] = json.dumps(config_as_dict(conf))
    spawn = _worker_spawner(env)

    procs = [spawn(i) for i in range(conf.workers)]
    stats = PoolStats()
    metrics = None
    if conf.pool_metrics_address:
        # parent-side supervision metrics (worker 0 owns the broker
        # metrics address, so the pool family gets its own endpoint)
        from ..metrics import MetricsServer, Registry, register_pool_metrics
        registry = Registry()
        register_pool_metrics(registry, stats)
        metrics = MetricsServer(conf.pool_metrics_address, registry,
                                path=conf.metrics_path,
                                logger=logger.with_prefix("pool-metrics"))
        metrics.start()
    boot.info("worker pool started", workers=conf.workers,
              bus=bus_path, tcp=conf.mqtt_tcp_address)
    if ready is not None:
        ready.set()
    if stop is None:
        stop = asyncio.Event()
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass

    watcher = asyncio.get_running_loop().create_task(
        _supervise_workers(procs, spawn, boot, stats=stats))
    try:
        await stop.wait()
    finally:
        watcher.cancel()
        if metrics is not None:
            metrics.stop()
        boot.info("shutting down worker pool")
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        await bus.close()
        try:
            os.unlink(bus_path)
        except FileNotFoundError:
            pass
