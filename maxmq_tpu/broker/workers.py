"""Multi-core delivery: SO_REUSEPORT workers federated as an in-box
cluster (ADR 021, superseding the ADR-005 fan-out bus).

The reference gets per-connection parallelism for free — one goroutine
per client spread over every host core (vendor/github.com/mochi-co/
mqtt/v2/clients.go:190-202, server.go:221). An asyncio broker caps
per-message work (decode, QoS bookkeeping, encode, socket writes) on a
single core, so N worker processes each run the FULL broker for the
connections the kernel hands them (``SO_REUSEPORT`` shards accepts with
no parent in the accept path).

What changed in ADR 021: the workers no longer talk over a bespoke
fan-out bus with its own gossip/takeover frames. Each worker IS a
cluster node — ``w0..wN-1`` — meshed over unix-domain bridge links
(the ``local`` link flavor: connect-by-path, budget-exempt, skew
pinned to zero), so cross-worker publish forwarding, route-table
aggregation, retained convergence, epoch-fenced session takeover,
cluster-wide ``$share`` through the ShareLedger, and the ADR-018
``cluster_fwd_durability`` barriers are all the EXISTING ADR-013/016/
018 machinery, not a parallel implementation. What this module still
owns is process supervision (spawn, respawn-with-throttle, pool
metrics) and the per-worker config derivation.

Shared singletons per box (the perf point of ADR 021):

* ONE matcher sidecar — when the box config asks for a device engine
  (``sig``/``nfa``/``dense``), the pool parent runs a
  :class:`~..matching.service.MatcherService` on a pool socket and
  every worker attaches as a ``matcher=service`` client behind its own
  ADR-011 supervisor. Table compiles happen once per box, and match
  requests from all workers coalesce into the same device
  micro-batches.
* ONE write-behind journal — only ``worker_journal_owner`` (default 0)
  keeps ``storage_backend``; the owner restores the cluster session
  buckets at boot and the ADR-016 claim path routes each session to
  whichever worker its client reconnects to. One fsync cadence per
  box, never N processes contending on one SQLite file.

Mixed pool+cluster composition: ``cluster_peers`` entries are appended
to EVERY worker's peer list (full peering), so an external node that
lists each worker as a peer composes with the mesh under one set of
``cluster_share_balance`` ownership rules. A remote box that only
knows a single node id cannot receive from workers it never listed —
see ADR 021's topology notes.

Scaling expectation: near-linear in delivery-bound workloads up to the
host's core count (this dev box has ONE core, so the functional tests
assert cross-worker semantics, not speedup — the ``cshard`` bench
config measures the real curve on multi-core hosts).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import json
import os
import subprocess
import sys
import time

from .. import faults

POOL_DIR_ENV = "MAXMQ_POOL_DIR"

# matcher engines the pool parent hoists into the shared sidecar; a
# box already on ``service`` points at an external sidecar, and the
# CPU trie stays per-worker (no chip to share)
_SIDECAR_MATCHERS = ("sig", "nfa", "dense")


def worker_sock(pool_dir: str, worker_id: int) -> str:
    """The unix-domain socket worker ``worker_id`` accepts sibling
    bridge links on."""
    return os.path.join(pool_dir, f"w{worker_id}.sock")


def matcher_sock(pool_dir: str) -> str:
    return os.path.join(pool_dir, "matcher.sock")


def worker_node_id(conf, worker_id: int) -> str:
    """Cluster node id of one worker: ``w<i>``, prefixed with the box's
    own cluster identity when it has one (so a mixed pool+cluster mesh
    stays globally unambiguous)."""
    base = conf.cluster_node_id
    return f"{base}.w{worker_id}" if base else f"w{worker_id}"


def worker_conf(conf, worker_id: int, pool_dir: str):
    """Derive worker ``worker_id``'s Config from the box config.

    The worker mesh is expressed entirely through the existing
    ``cluster_*`` surface: node id ``w<i>``, peers = every sibling over
    ``unix:`` links plus the box's external ``cluster_peers`` verbatim,
    session sync per ``worker_session_sync`` (default ``always`` — a
    SIGKILLed worker's sibling must redeliver every PUBACKed message).
    Singleton ownership: only ``worker_journal_owner`` keeps the
    storage backend, only worker 0 keeps the unshareable listeners
    (unix socket, $SYS HTTP) and the metrics address, and device
    matchers are rewritten to ``service`` against the shared sidecar.
    """
    siblings = ",".join(
        f"{worker_node_id(conf, j)}@unix:{worker_sock(pool_dir, j)}"
        for j in range(conf.workers) if j != worker_id)
    peers = ",".join(p for p in (siblings, conf.cluster_peers.strip(", "))
                     if p)
    kw = dict(cluster_node_id=worker_node_id(conf, worker_id),
              cluster_peers=peers,
              cluster_session_sync=conf.worker_session_sync)
    if worker_id != conf.worker_journal_owner:
        kw["storage_backend"] = ""
    if worker_id != 0:
        # SO_REUSEPORT shards the TCP/WS listeners; the unix-socket and
        # $SYS-HTTP listeners (and metrics) cannot share an address
        kw["mqtt_unix_socket"] = ""
        kw["mqtt_sys_http_address"] = ""
    if conf.matcher in _SIDECAR_MATCHERS:
        kw["matcher"] = "service"
        kw["matcher_socket"] = matcher_sock(pool_dir)
    return dataclasses.replace(conf, **kw)


def _tune_local_links(manager, conf) -> None:
    """Apply the ``worker_link_*`` knobs to the loopback links ONLY —
    a mixed box's external TCP links keep the ``cluster_link_*``
    budget/keepalive they were built with."""
    if manager is None:
        return
    for link in manager.links.values():
        if link.local:
            link.byte_budget = conf.worker_link_byte_budget
            link.keepalive = float(conf.worker_link_keepalive)


def build_worker_broker(wconf, logger, worker_id: int, pool_dir: str):
    """One worker's broker: the standard bootstrap build (so cluster,
    storage, tracing, and overload wiring are production-parity) plus
    the sibling-bridge unix listener every peer worker dials."""
    from ..bootstrap import build_broker
    from .listeners import UnixListener

    broker = build_broker(wconf, logger)
    path = worker_sock(pool_dir, worker_id)
    with contextlib.suppress(OSError):
        os.unlink(path)     # stale socket from a crashed incarnation
    broker.add_listener(UnixListener("peer-bridge", path))
    _tune_local_links(broker.cluster, wconf)
    return broker


async def run_worker(conf, logger, worker_id: int, pool_dir: str,
                     ready: asyncio.Event | None = None,
                     stop: asyncio.Event | None = None) -> None:
    """One pool worker process: derive the worker config, run the full
    broker with its sibling mesh, serve until stopped."""
    from ..bootstrap import _maybe_attach_service, build_metrics

    wconf = worker_conf(conf, worker_id, pool_dir)
    broker = build_worker_broker(wconf, logger, worker_id, pool_dir)
    # service matcher attaches BEFORE metrics so the matcher families
    # register (same ordering contract as run_server)
    await _maybe_attach_service(wconf, broker)
    metrics = build_metrics(wconf, broker, logger) if worker_id == 0 else None
    await broker.serve()
    if metrics is not None:
        metrics.start()
    logger.with_prefix("worker").info("pool worker started",
                                      worker=worker_id,
                                      node=wconf.cluster_node_id)
    if ready is not None:
        ready.set()
    if stop is None:
        stop = asyncio.Event()
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass
    if faults.fire(faults.POOL_WORKER):
        # injected worker death (ADR 011 fault suite; armed through the
        # MAXMQ_FAULTS env the pool parent propagates): exit now so the
        # parent's supervision loop observes the crash and respawns us
        stop.set()
    try:
        await stop.wait()
    finally:
        await broker.close()
        if metrics is not None:
            metrics.stop()
        matcher = broker.matcher
        if matcher is not None and hasattr(matcher, "close"):
            await matcher.close()
        with contextlib.suppress(OSError):
            os.unlink(worker_sock(pool_dir, worker_id))


class PoolStats:
    """Supervision counters for one pool parent, exported as the
    ``maxmq_pool_*`` family (metrics.register_pool_metrics)."""

    def __init__(self) -> None:
        self.worker_restarts = 0


# process-wide default (one pool parent per process); tests construct
# their own and pass it to _supervise_workers
POOL_STATS = PoolStats()


async def _supervise_workers(procs, spawn, boot, stats: PoolStats = None,
                             interval: float = 2.0) -> None:
    """A worker that dies (crash, OOM kill, injected fault) is logged,
    counted (stats.worker_restarts -> maxmq_pool_worker_restarts_total),
    and respawned — the pool must not silently degrade to N-1.
    Throttled per slot so a crash loop can't fork-bomb the host. The
    respawned incarnation re-binds its SO_REUSEPORT share and its
    sibling bridge socket; peers reconnect through the local links'
    fast backoff and re-exchange routes/sessions (epoch-fenced, so the
    dead incarnation's state flushes on arrival)."""
    stats = stats if stats is not None else POOL_STATS
    last_spawn = [0.0] * len(procs)
    while True:
        await asyncio.sleep(interval)
        for i, p in enumerate(procs):
            rc = p.poll()
            if rc is None:
                continue
            wait = max(0.0, 5.0 - (time.monotonic() - last_spawn[i]))
            boot.error("pool worker exited; restarting", worker=i,
                       rc=rc, backoff_s=round(wait, 1))
            if wait:
                await asyncio.sleep(wait)
            last_spawn[i] = time.monotonic()
            procs[i] = spawn(i)
            stats.worker_restarts += 1


async def await_mesh(brokers, timeout: float = 10.0) -> None:
    """Wait until every worker's link to every sibling is connected —
    the pool's "serving" point. Route/session exchange starts at each
    link-up, so callers that need a specific filter visible on a
    specific worker poll :func:`await_routes` after subscribing."""
    deadline = time.monotonic() + timeout
    while True:
        down = [(b.cluster.node_id, peer)
                for b in brokers
                for peer, link in b.cluster.links.items()
                if link.local and not link.connected]
        if not down:
            return
        if time.monotonic() >= deadline:
            raise TimeoutError(f"worker mesh not converged: {down}")
        await asyncio.sleep(0.01)


async def await_routes(broker, topic: str, n: int = 1,
                       timeout: float = 5.0) -> None:
    """Poll until ``broker``'s route table forwards ``topic`` to at
    least ``n`` peers. Publish forwarding is route-driven (unlike the
    ADR-005 bus, which broadcast blindly), so a subscribe on one worker
    is visible to a publisher on another only after the route
    advertisement lands — tests hop this barrier explicitly instead of
    sleeping."""
    deadline = time.monotonic() + timeout
    while len(broker.cluster.routes.nodes_for(topic)) < n:
        if time.monotonic() >= deadline:
            raise TimeoutError(f"route for {topic!r} never reached "
                               f"{broker.cluster.node_id}")
        await asyncio.sleep(0.01)


@contextlib.asynccontextmanager
async def inprocess_pool(n: int = 2, link_dir: str | None = None,
                         conf=None, converge: bool = True):
    """N pool workers in ONE process: the same build_worker_broker
    wiring the subprocess pool runs — per-worker ClusterManager, unix
    mesh links, shared-singleton config derivation — minus the process
    boundary (which only the kernel's SO_REUSEPORT accept sharding
    cares about; here each worker binds its own ephemeral port so
    tests can target a specific worker). Yields (brokers, ports).
    Used by the cross-worker test suite and the overhead measurement
    harness (tools/measure_pool.py); also the embedding surface for
    hosts that want a pool without subprocesses."""
    from ..utils.config import Config
    from ..utils.logger import new_logger

    link_dir = link_dir or f"/tmp/maxmq-pool-inproc-{os.getpid()}"
    os.makedirs(link_dir, exist_ok=True)
    base = dataclasses.replace(
        conf or Config(), workers=n,
        mqtt_tcp_address="127.0.0.1:0", mqtt_unix_socket="",
        mqtt_sys_http_address="", mqtt_sys_topic_interval=0,
        metrics_enabled=False)
    logger = new_logger(fmt="json", level="error")
    brokers, ports = [], []
    try:
        for i in range(n):
            wconf = worker_conf(base, i, link_dir)
            b = build_worker_broker(wconf, logger, i, link_dir)
            await b.serve()
            brokers.append(b)
            lst = b.listeners.get("tcp")
            ports.append(lst._server.sockets[0].getsockname()[1])
        if converge:
            await await_mesh(brokers)
        yield brokers, ports
    finally:
        for b in brokers:
            await b.close()
        for i in range(n):
            with contextlib.suppress(OSError):
                os.unlink(worker_sock(link_dir, i))


def _engine_factory(conf):
    """The sidecar's engine build, mirroring bootstrap.build_matcher's
    device branches (sig/nfa/dense, mesh-sharded when configured) —
    the ONE table compile per box the workers share."""
    def factory(index):
        from ..matching.batcher import MicroBatcher
        if conf.matcher_mesh:
            from ..parallel.sharded import (ShardedNFAEngine,
                                            ShardedSigEngine, make_mesh)
            rows, _, cols = conf.matcher_mesh.partition("x")
            mesh = make_mesh(shape=(int(rows), int(cols or 1)))
            if conf.matcher == "nfa":
                engine = ShardedNFAEngine(index, mesh=mesh,
                                          max_levels=conf.matcher_max_levels)
            else:
                engine = ShardedSigEngine(index, mesh=mesh)
                engine.emit_intents = conf.matcher_intents
        elif conf.matcher == "nfa":
            from ..matching.engine import NFAEngine
            engine = NFAEngine(index, max_levels=conf.matcher_max_levels)
        elif conf.matcher == "dense":
            from ..matching.dense import DenseEngine
            engine = DenseEngine(index, max_levels=conf.matcher_max_levels)
        else:
            from ..matching.sig import SigEngine
            engine = SigEngine(index, max_levels=conf.matcher_max_levels)
            engine.emit_intents = conf.matcher_intents
        return MicroBatcher(engine,
                            window_us=conf.matcher_batch_window_us,
                            max_batch=conf.matcher_max_batch)
    return factory


async def _maybe_pool_matcher_service(conf, pool_dir: str):
    """ADR 021: one chip-owning matcher sidecar per box. The parent
    owns it (accelerator runtimes are single-claim — N workers cannot
    each hold the device), workers attach as ``matcher=service``
    clients behind their own ADR-011 supervisors, so a sidecar crash
    degrades every worker to its CPU trie and the reconnect ladder
    reseeds — never a pool-wide wedge."""
    if conf.matcher not in _SIDECAR_MATCHERS:
        return None
    from ..matching.service import MatcherService
    svc = MatcherService(matcher_sock(pool_dir),
                         engine_factory=_engine_factory(conf))
    await svc.start()
    return svc


def _worker_spawner(env: dict):
    """Build the pool's spawn(i) closure, scoping pool.worker faults
    (ADR 011 drills) to mean "kill A worker", not "kill every worker
    forever": MAXMQ_FAULTS is parsed at import in EACH subprocess, so
    an unscoped spec would re-arm in all N workers AND in every
    respawned replacement — a throttled permanent crash loop instead
    of a kill-once/recover drill. The first spawn keeps the
    pool.worker entries; every other spawn (other slots, and all
    respawns) gets them stripped."""
    fault_spec = env.get("MAXMQ_FAULTS", "")
    entries = [e.strip() for e in fault_spec.split(",") if e.strip()]
    kept = ",".join(e for e in entries
                    if not e.startswith(faults.POOL_WORKER))
    has_kill = any(e.startswith(faults.POOL_WORKER) for e in entries)
    delivered = [not has_kill]    # nothing to scope -> strip never

    def spawn(i: int):
        wenv = dict(env)
        wenv["MAXMQ_WORKER_ID"] = str(i)
        if fault_spec and delivered[0]:
            if kept:
                wenv["MAXMQ_FAULTS"] = kept
            else:
                wenv.pop("MAXMQ_FAULTS", None)
        delivered[0] = True
        return subprocess.Popen(
            [sys.executable, "-m", "maxmq_tpu", "start", "--no-banner"],
            env=wenv)

    return spawn


async def run_pool(conf, logger, ready: asyncio.Event | None = None,
                   stop: asyncio.Event | None = None) -> None:
    """The pool parent: shared matcher sidecar + N worker subprocesses
    + supervision. The parent never touches a client socket — the
    kernel (SO_REUSEPORT) shards accepts directly onto the workers —
    and (since ADR 021) never relays a message either: the workers
    mesh directly over their unix bridge sockets."""
    from ..utils.config import config_as_dict

    boot = logger.with_prefix("pool")
    pool_dir = conf.worker_link_dir or f"/tmp/maxmq-pool-{os.getpid()}"
    os.makedirs(pool_dir, exist_ok=True)
    service = await _maybe_pool_matcher_service(conf, pool_dir)

    env = dict(os.environ)
    env[POOL_DIR_ENV] = pool_dir
    env["MAXMQ_POOL_CONF"] = json.dumps(config_as_dict(conf))
    spawn = _worker_spawner(env)

    procs = [spawn(i) for i in range(conf.workers)]
    stats = PoolStats()
    metrics = None
    if conf.pool_metrics_address:
        # parent-side supervision metrics (worker 0 owns the broker
        # metrics address, so the pool family gets its own endpoint)
        from ..metrics import MetricsServer, Registry, register_pool_metrics
        registry = Registry()
        register_pool_metrics(registry, stats)
        metrics = MetricsServer(conf.pool_metrics_address, registry,
                                path=conf.metrics_path,
                                logger=logger.with_prefix("pool-metrics"))
        metrics.start()
    boot.info("worker pool started", workers=conf.workers,
              pool_dir=pool_dir, tcp=conf.mqtt_tcp_address,
              matcher_sidecar=bool(service))
    if ready is not None:
        ready.set()
    if stop is None:
        stop = asyncio.Event()
        import signal
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:
                pass

    watcher = asyncio.get_running_loop().create_task(
        _supervise_workers(procs, spawn, boot, stats=stats))
    try:
        await stop.wait()
    finally:
        watcher.cancel()
        if metrics is not None:
            metrics.stop()
        boot.info("shutting down worker pool")
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if service is not None:
            await service.close()
        for i in range(conf.workers):
            with contextlib.suppress(OSError):
                os.unlink(worker_sock(pool_dir, i))
        with contextlib.suppress(OSError):
            os.rmdir(pool_dir)
